"""ServingFront: one admission queue, N supervised replicas.

PR 6's continuous engine is a single `ContinuousScheduler`: its death
takes the whole service down with every queued and in-flight request.
The front makes availability a property of the FLEET instead:

  * **one shared admission queue.**  Requests are validated and queued
    at the front; a dispatcher hands them to the least-loaded LIVE
    replica, capped at each replica's decode-slot count, so a replica
    death can only strand the bounded set it was actually running —
    the backlog stays at the front, untouched (queue handoff).
  * **supervised replicas** (serving/replica.py): each wraps a
    `ContinuousScheduler` + decode model under the resilience
    primitives — `StepWatchdog(step_timeout)` around the decode
    dispatch, seeded `FaultPlan` injection, jittered-backoff
    `RetryPolicy` with a restart budget, device-loss rebuilds on the
    surviving mesh warmed through the strategy store.
  * **requeue with a bounded retry count.**  A request stranded by a
    replica death (or failed by a transient step fault) goes back to
    the HEAD of the admission queue and runs again on a surviving
    replica — greedy decoding makes the retry token-identical.  A
    request that exhausts `request_retry_limit` fails with a 503
    RETRIABLE error, never a client error: the front never punishes a
    request it admitted.
  * **load shedding, not unbounded queueing.**  While ZERO replicas
    are live, new submissions are refused with `ServiceUnavailable`
    (HTTP 503 + Retry-After via server.py) instead of growing the
    queue without a server; already-admitted requests keep waiting for
    the restart.  If every replica goes PERMANENTLY dead (budget
    exhausted), the queue is failed retriably — no recovery is coming.

API-compatible with the batcher contract (generate / generate_async /
latency_stats / stats / close / worker_alive), plus `health()` for
/v2/health's ok | degraded | down aggregation.  Metrics
(serving/replica_restarts, replica_deaths, requeued_requests,
shed_requests, per-replica queue-depth gauges) ride the shared
obs.metrics registry.  docs/SERVING.md "Replicated front".
"""
from __future__ import annotations

import itertools
import signal
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from ..logger import resilience_logger
from ..resilience.faults import FaultPlan
from ..resilience.retry import RetryPolicy
from .handoff import HandoffPaused
from .replica import ServingReplica


class ServiceUnavailable(RuntimeError):
    """The front cannot take (or finish) this request right now; the
    client should back off and retry.  server.py maps it to HTTP 503
    with a Retry-After header from `retry_after_s`."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class FrontRequest:
    """Front-level future for one admitted request.  Mirrors the
    scheduler handle surface the loadgen and server consume (wait /
    t_submit / t_first_token / t_done / n_generated), independent of
    which replica — or how many, after requeues — ran it."""

    __slots__ = ("prompt", "max_new_tokens", "temperature", "event",
                 "result", "error", "t_submit", "t_first_token",
                 "t_done", "n_generated", "retries",
                 "queue_depth_at_admit", "deadline_s",
                 "prefix_hit_tokens", "served_role", "migration",
                 "trace", "seed", "resume")

    def __init__(self, prompt, max_new_tokens, temperature,
                 deadline_s: Optional[float] = None):
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.event = threading.Event()
        self.result: Optional[List[int]] = None
        self.error: Optional[Exception] = None
        self.t_submit = time.monotonic()
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None
        self.n_generated = 0
        self.retries = 0  # requeues consumed (replica deaths/faults)
        self.queue_depth_at_admit = 0  # front backlog seen at admission
        self.deadline_s = deadline_s   # TTFT SLO for admission control
        self.prefix_hit_tokens = 0     # stamped from the replica handle
        self.served_role = None        # class of the replica that served
        self.migration = None  # disagg routing record (serving/disagg.py)
        self.trace = None  # TraceContext (obs/reqtrace.py) or None
        self.seed = None   # per-request sampling seed (front-minted)
        self.resume = None  # ResumeRecord after a pause/death mid-decode

    def wait(self, timeout: Optional[float] = None) -> List[int]:
        if not self.event.wait(timeout):
            raise TimeoutError("generation request timed out")
        if self.error is not None:
            raise self.error
        return self.result


class ServingFront:
    """N supervised ContinuousScheduler replicas behind one queue.

    `model_factory(replica_id, survivors=None)` builds one replica's
    decode model (see ServingReplica).  `fault_plans` optionally maps
    replica id -> FaultPlan for seeded fault injection; `step_timeout`
    arms each replica's decode-step watchdog; `max_restarts` /
    `retry_backoff` bound each replica's supervised restarts;
    `request_retry_limit` bounds per-request requeues.
    """

    def __init__(
        self,
        model_factory: Callable,
        num_replicas: int = 2,
        *,
        eos_id: int = -1,
        registry=None,
        seed: int = 0,
        step_timeout: float = 0.0,
        max_restarts: int = 3,
        retry_backoff: float = 0.1,
        request_retry_limit: int = 2,
        handoff: bool = False,
        chip_budget: int = 0,
        fault_plans: Optional[Dict[int, FaultPlan]] = None,
        roles: Optional[Sequence[str]] = None,
        check_invariants: bool = False,
        latency_window: int = 1024,
        close_timeout_s: float = 5.0,
        shed_retry_after_s: float = 1.0,
        admission_deadline_s: float = 0.0,
        rate_staleness_s: float = 30.0,
        reqtrace=None,
        sleep: Callable[[float], None] = time.sleep,
        logger=resilience_logger,
    ):
        if num_replicas < 1:
            raise ValueError(
                f"num_replicas must be >= 1, got {num_replicas}")
        if request_retry_limit < 0:
            raise ValueError(
                f"request_retry_limit must be >= 0, "
                f"got {request_retry_limit}")
        # replica roles (disaggregated serving, serving/disagg.py):
        # "prefill" replicas never serve client decodes — the
        # dispatcher skips them — while "decode"/"mixed" replicas do.
        # A fleet with no decode-capable member could admit but never
        # serve, so it is refused at construction.
        if roles is None:
            roles = ["mixed"] * num_replicas
        roles = [str(r) for r in roles]
        if len(roles) != num_replicas:
            raise ValueError(
                f"roles must name every replica: got {len(roles)} "
                f"role(s) for {num_replicas} replica(s)")
        for r in roles:
            if r not in ("prefill", "decode", "mixed"):
                raise ValueError(
                    f"unknown replica role {r!r} (expected prefill, "
                    "decode, or mixed)")
        if all(r == "prefill" for r in roles):
            raise ValueError(
                "fleet needs at least one decode-capable replica "
                "(role decode or mixed)")
        self.registry = registry
        # request-scoped tracing (obs/reqtrace.py): the front mints one
        # TraceContext per sampled admission and threads it through
        # dispatch, migration, and every replica scheduler.  None (or a
        # NullReqTracer) keeps req.trace = None everywhere — the
        # zero-allocation disabled path.
        self._reqtrace = (reqtrace if reqtrace is not None
                          and getattr(reqtrace, "enabled", True)
                          else None)
        self.request_retry_limit = int(request_retry_limit)
        self.chip_budget = int(chip_budget)  # 0 = unbounded
        # mid-decode handoff (serving/handoff.py): with the flag on, a
        # DRAINING / terminating / rebalanced replica pauses in-flight
        # generations and the front resumes them elsewhere instead of
        # waiting them out or shedding them.  Off by default: the
        # classic drain semantics (run every slot to completion).
        self.handoff = bool(handoff)
        # per-request sampling seeds: minted at admission so a
        # temperature>0 generation replays deterministically on any
        # replica (each scheduler seeds a private RandomState from it)
        self._req_seed = itertools.count(int(seed) * 1_000_003 + 1)
        self._handoff_mig = None  # lazy KVMigrator (base front only)
        self._handoff_cm = None   # lazy MigrationCostModel
        self._handoff_inflight = 0  # pauses not yet requeued
        self.handoff_requested = 0
        self.handoff_ok = 0
        self.handoff_replays = 0
        self.handoff_migrate_decisions = 0
        self.handoff_replay_decisions = 0
        self.handoff_faults: Dict[str, int] = {}
        self._pending_replicas = 0  # add_replica compiles in flight
        self.shed_retry_after_s = float(shed_retry_after_s)
        self.admission_deadline_s = float(admission_deadline_s)
        self.rate_staleness_s = float(rate_staleness_s)
        self.log = logger
        self._cv = threading.Condition()
        self._admission: "deque[FrontRequest]" = deque()
        self._closed = False
        self._terminating = False
        self.requests_done = 0
        self.requests_admitted = 0  # accepted into the queue (the
        #                             predictive autoscaler's ramp input)
        self.shed_requests = 0
        self.admission_shed = 0   # overload-control sheds (deadline)
        self.requeued_requests = 0
        self._latencies = deque(maxlen=latency_window)
        self._ttfts = deque(maxlen=latency_window)
        self._lat_lock = threading.Lock()
        # completion timestamps for the measured service rate (drain
        # rate): Retry-After and predicted-TTFT admission control both
        # read it instead of a constant.  _done_busy marks, per
        # completion, whether the admission queue was non-empty at
        # that moment — only those samples witness CAPACITY (an
        # uncontended completion merely tracks the arrival rate)
        self._done_times = deque(maxlen=256)
        self._done_busy = deque(maxlen=256)
        # per-CLASS completion windows (role -> timestamps) and
        # per-token samples: once roles split, a single fleet-wide
        # window would blend prefill-pass throughput into the decode
        # drain rate and mis-size Retry-After / admission control
        self._class_done: Dict[str, deque] = {}
        self._class_tok: Dict[str, deque] = {}
        # the autoscaler attaches itself here (serving/autoscaler.py);
        # /v2/stats surfaces its block when present
        self.autoscaler = None
        # bounded retirement history: a long-lived autoscaled front
        # cycles replicas indefinitely, so keep the last few for
        # /v2/stats and fold the rest into aggregate counters
        self.retired: List[ServingReplica] = []
        self.retired_keep = 16
        self._retired_dropped = 0
        self._retired_folded = {"batches_run": 0, "tokens_generated": 0}
        self._model_factory = model_factory
        plans = fault_plans or {}
        self._replica_kw = dict(
            eos_id=eos_id, registry=registry, seed=seed,
            step_timeout=step_timeout, max_restarts=max_restarts,
            retry_backoff=retry_backoff,
            check_invariants=check_invariants,
            close_timeout_s=close_timeout_s, sleep=sleep, logger=logger,
            reqtrace=self._reqtrace,
        )
        self.replicas: List[ServingReplica] = [
            self._build_replica(i, fault_plan=plans.get(i),
                                role=roles[i])
            for i in range(num_replicas)
        ]
        self._next_replica_id = num_replicas
        # every engine in the fleet spans the same tensor-parallel
        # degree; the chip budget bounds
        # len(replicas) * chips_per_replica (docs/SERVING.md)
        self.chips_per_replica = max(1, int(getattr(
            self.replicas[0].scheduler.model, "tp", 1)))
        if self.chip_budget and (len(self.replicas)
                                 * self.chips_per_replica
                                 > self.chip_budget):
            for r in self.replicas:
                r.close(close_timeout_s)
            raise ValueError(
                f"chip budget {self.chip_budget} cannot hold "
                f"{len(self.replicas)} replica(s) x "
                f"{self.chips_per_replica} chip(s) each")
        self.max_seq = self.replicas[0].scheduler.model.max_seq
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name="serving-front-dispatch",
        )
        self._dispatcher.start()

    def _build_replica(self, replica_id: int,
                       fault_plan=None,
                       role: str = "mixed") -> ServingReplica:
        kw = self._replica_kw
        r = ServingReplica(
            replica_id, self._model_factory,
            eos_id=kw["eos_id"], registry=kw["registry"],
            seed=kw["seed"],
            step_timeout=kw["step_timeout"],
            retry=RetryPolicy(max_restarts=kw["max_restarts"],
                              base_backoff=kw["retry_backoff"],
                              seed=kw["seed"] + replica_id),
            fault_plan=fault_plan,
            role=role,
            reqtrace=kw["reqtrace"],
            check_invariants=kw["check_invariants"],
            close_timeout_s=kw["close_timeout_s"],
            sleep=kw["sleep"],
            logger=kw["logger"],
        )
        r.on_state_change = self._on_replica_state
        return r

    @classmethod
    def from_trained(cls, ff_train, num_replicas: Optional[int] = None,
                     *, devices=None, eos_id: int = -1, registry=None,
                     fault_plans: Optional[Dict[int, FaultPlan]] = None,
                     draft_ff=None, **kw) -> "ServingFront":
        """Replicated front over a trained GPT, honoring the FFConfig
        serving knobs (--serving-replicas / --serving-step-timeout /
        --serving-max-restarts / --request-retry-limit plus the PR 6
        pool geometry).  Each replica compiles its own paged decode
        twin; with the strategy store configured the N-1 later compiles
        (and every post-death rebuild) restore instead of re-searching
        (docs/STORE.md).  A device-loss rebuild truncates `devices` to
        the surviving count.

        `draft_ff` is the smaller trained GPT that --spec-decode draft
        drafts with (docs/SERVING.md "Speculative decoding"); each
        replica builds its own single-chip draft twin from it.
        Required when cfg.spec_decode == "draft" — validated HERE so
        the missing drafter is a build-time ConfigError, not a
        per-replica death loop."""
        from ..config import resolve_spec_decode
        from .scheduler import PagedKVDecodeModel

        cfg = ff_train.config
        # inherit the run's telemetry bundle unless the caller wires
        # its own: --trace-dir alone gives the serving fleet SLO
        # metrics AND per-request traces (obs/reqtrace.py) — the
        # NULL_REQTRACER's enabled=False keeps the disabled path free
        tel = getattr(ff_train, "telemetry", None)
        if tel is not None:
            if registry is None and getattr(tel, "enabled", False):
                registry = tel.metrics
            kw.setdefault("reqtrace", getattr(tel, "reqtrace", None))
        spec_decode = resolve_spec_decode(
            getattr(cfg, "spec_decode", "off"),
            getattr(cfg, "spec_k", 4))
        spec_k = int(getattr(cfg, "spec_k", 4))
        if spec_decode == "draft" and draft_ff is None:
            from ..config import ConfigError

            raise ConfigError(
                "--spec-decode draft needs a draft model: pass "
                "ServingFront.from_trained(..., draft_ff=<smaller "
                "trained GPT>) or use --spec-decode ngram")

        def factory(replica_id, survivors=None):
            devs = devices
            if survivors is not None and devs is not None:
                devs = devs[:survivors]
            draft_model = None
            if spec_decode == "draft":
                draft_model = PagedKVDecodeModel(
                    draft_ff,
                    batch_slots=cfg.serving_slots,
                    page_size=cfg.kv_page_size,
                    devices=devs,
                    paged_kernel=getattr(cfg, "paged_kernel",
                                         "gather"),
                )
            return PagedKVDecodeModel(
                ff_train,
                batch_slots=cfg.serving_slots,
                page_size=cfg.kv_page_size,
                num_blocks=cfg.kv_pool_blocks or None,
                devices=devs,
                prefill_chunk=getattr(cfg, "prefill_chunk", 0),
                prefix_cache=getattr(cfg, "prefix_cache", True),
                paged_kernel=getattr(cfg, "paged_kernel", "gather"),
                tp=getattr(cfg, "serving_tp", 1),
                spec_decode=spec_decode,
                spec_k=spec_k,
                draft_model=draft_model,
            )

        kw.setdefault("step_timeout", cfg.serving_step_timeout)
        kw.setdefault("max_restarts", cfg.serving_max_restarts)
        kw.setdefault("request_retry_limit", cfg.request_retry_limit)
        kw.setdefault("handoff",
                      bool(getattr(cfg, "serving_handoff", False)))
        kw.setdefault("seed", cfg.seed)
        kw.setdefault("admission_deadline_s",
                      getattr(cfg, "admission_deadline_s", 0.0))
        kw.setdefault("chip_budget",
                      getattr(cfg, "serving_chip_budget", 0))
        n = cfg.serving_replicas if num_replicas is None else num_replicas
        tp = getattr(cfg, "serving_tp", 1)
        budget = int(kw.get("chip_budget") or 0)
        if budget and n * tp > budget:
            from ..config import ConfigError

            raise ConfigError(
                f"--serving-chip-budget {budget} cannot hold the "
                f"initial fleet: {n} replica(s) x --serving-tp {tp} "
                f"= {n * tp} chip(s)")
        return cls(
            factory, n,
            eos_id=eos_id, registry=registry, fault_plans=fault_plans,
            **kw,
        )

    # -- replica events --------------------------------------------------
    def _on_replica_state(self, replica: ServingReplica) -> None:
        with self._cv:
            self._cv.notify_all()

    def _live(self) -> List[ServingReplica]:
        return [r for r in self.replicas if r.alive]

    def _serving(self) -> List[ServingReplica]:
        """Decode-capable subset: the replicas client requests can be
        dispatched to.  Identical to the fleet while every role is
        mixed; prefill-class replicas only run migration passes."""
        return [r for r in self.replicas if r.role != "prefill"]

    def _serving_live(self) -> List[ServingReplica]:
        return [r for r in self._serving() if r.alive]

    def _all_permanently_dead(self) -> bool:
        # vacuous truth on an empty fleet would mislabel terminate()'s
        # residue (all replicas retired) as "restart budgets exhausted".
        # Only the decode-capable subset counts: a fleet whose decode
        # class is gone cannot finish a client request no matter how
        # healthy its prefill class is.
        serving = self._serving()
        return bool(serving) and all(
            r.state == "dead" for r in serving)

    # -- fleet lifecycle (autoscaler / SIGTERM grace) --------------------
    def add_replica(self, role: str = "mixed") -> ServingReplica:
        """Scale-up: build one more supervised replica (the compile is
        warm through the strategy store whenever any replica has paid
        it — docs/STORE.md) and put it in the dispatcher's rotation.
        With a chip budget set, a replica that would not fit
        (fleet chips + chips_per_replica > budget) is refused BEFORE
        any compile — the autoscaler counts the refusal as a spawn
        failure (serving/autoscaler_spawn_failed)."""
        if self._closed or self._terminating:
            raise RuntimeError("ServingFront is closing")
        with self._cv:
            if self.chip_budget:
                in_use = (len(self.replicas) + self._pending_replicas
                          ) * self.chips_per_replica
                if in_use + self.chips_per_replica > self.chip_budget:
                    if self.registry is not None:
                        self.registry.counter(
                            "serving/chip_budget_refused").inc()
                    raise RuntimeError(
                        f"chip budget exhausted: {in_use} of "
                        f"{self.chip_budget} chip(s) in use and a new "
                        f"replica spans {self.chips_per_replica}")
            self._pending_replicas += 1
            rid = self._next_replica_id
            self._next_replica_id += 1
        if role not in ("prefill", "decode", "mixed"):
            with self._cv:
                self._pending_replicas -= 1
            raise ValueError(f"unknown replica role {role!r}")
        try:
            # compile OUTSIDE the lock
            replica = self._build_replica(rid, role=role)
        except Exception:
            with self._cv:
                self._pending_replicas -= 1
            raise
        with self._cv:
            self._pending_replicas -= 1
            # close()/terminate() may have swept the fleet while we
            # were compiling; appending now would leak a live engine
            # nobody ever closes
            if self._closed or self._terminating:
                aborted = True
            else:
                aborted = False
                self.replicas.append(replica)
                self._cv.notify_all()
        if aborted:
            replica.close()
            raise RuntimeError("ServingFront is closing")
        if self.registry is not None:
            self.registry.counter("serving/replicas_added").inc()
        self.log.info("serving front: replica %d added (fleet %d)",
                      rid, len(self.replicas))
        return replica

    def drain_replica(self, replica: ServingReplica) -> bool:
        """Scale-down: READY -> DRAINING.  The dispatcher stops routing
        to it immediately (state leaves \"live\"); in-flight slots run
        to completion token-identically; on retirement the replica
        leaves `replicas` for `retired` and its KV pool is freed.

        With handoff enabled and another live serving replica up, the
        drain is proactive instead of patient: every in-flight
        generation with tokens left pauses onto the handoff path and
        resumes elsewhere, so the drain time is bounded by the
        migration, not by the longest generation."""
        ok = replica.drain(on_retired=self._on_replica_retired)
        if ok and self.handoff and replica.role != "prefill":
            with self._cv:
                others = [r for r in self._serving_live()
                          if r is not replica]
            if others:
                replica.request_handoff(remaining_over=0,
                                        export_kv=True)
        return ok

    def _on_replica_retired(self, replica: ServingReplica) -> None:
        dropped = []
        with self._cv:
            if replica in self.replicas:
                self.replicas.remove(replica)
                self.retired.append(replica)
                while len(self.retired) > self.retired_keep:
                    old = self.retired.pop(0)
                    st = old.stats()
                    self._retired_dropped += 1
                    for k in self._retired_folded:
                        self._retired_folded[k] += int(st.get(k, 0))
                    dropped.append(old)
            self._cv.notify_all()
        for old in dropped:
            old.close(0.1)  # outside the lock: close joins a thread
        if self.registry is not None:
            # replica ids are monotonic — the per-id gauge would
            # otherwise accumulate one dead name per scale cycle
            self.registry.remove(
                f"serving/replica/{replica.replica_id}/queue_depth")
        self.log.info("serving front: replica %d retired (fleet %d)",
                      replica.replica_id, len(self.replicas))

    # -- measured service rate -------------------------------------------
    def _note_class_done(self, role: Optional[str], t: float,
                         per_token_s: Optional[float] = None) -> None:
        """Record one completion in the per-class window.  Client
        completions land here via _complete; a disaggregated front also
        records its internal prefill passes so service_rate("prefill")
        measures that class's real pass rate instead of staying empty.
        Caller holds no lock."""
        if not role:
            return
        with self._lat_lock:
            self._class_done.setdefault(
                role, deque(maxlen=256)).append(t)
            if per_token_s is not None:
                self._class_tok.setdefault(
                    role, deque(maxlen=256)).append(per_token_s)

    def service_rate(self, role: Optional[str] = None
                     ) -> Optional[float]:
        """Measured completions/s over the recent window; None until
        two completions have landed, and None again once the newest
        completion is older than `rate_staleness_s` — after an idle
        gap the old span measures ARRIVALS, not capacity, and a stale
        near-zero rate would shed traffic an idle fleet could trivially
        serve.  This is the drain rate Retry-After and predicted-TTFT
        admission control are computed from.  With `role` set, the
        window is that replica class's alone (disaggregated fleets:
        prefill passes must not blend into the decode drain rate)."""
        with self._lat_lock:
            ts = list(self._done_times if role is None
                      else self._class_done.get(role, ()))
        if len(ts) < 2:
            return None
        if time.monotonic() - ts[-1] > self.rate_staleness_s:
            return None
        span = ts[-1] - ts[0]
        if span <= 0:
            return None
        return (len(ts) - 1) / span

    def _capacity_rate(self) -> Optional[float]:
        """Completions/s over the TRAILING RUN of completions that all
        landed with a non-empty admission queue — i.e. while the fleet
        was saturated, so the span witnesses CAPACITY.  Anything less
        (a whole-window rate, even one gated on a few busy samples)
        is contaminated by calm stretches where completions pace
        arrivals, and shedding on an arrival rate would condemn the
        first burst after every quiet period.  None until the run has
        3 members; an uncontended completion resets it (the queue
        drained — no longer saturated, and with an empty queue the
        shed path is off anyway)."""
        with self._lat_lock:
            ts = list(self._done_times)
            flags = list(self._done_busy)
        run = 0
        for b in reversed(flags):
            if not b:
                break
            run += 1
        if run < 3:
            return None
        ts = ts[-run:]
        if time.monotonic() - ts[-1] > self.rate_staleness_s:
            return None
        span = ts[-1] - ts[0]
        if span <= 0:
            return None
        return (run - 1) / span

    def _prefix_discount(self, prompt, max_new: int) -> float:
        """The candidate request's own service cost relative to an
        uncached request of the same shape: cached prefix tokens cost
        ZERO prefill steps, so a request whose prompt is largely in a
        replica's prefix cache consumes (plen - hit + max_new) of the
        (plen + max_new) steps an uncached twin would.  The dispatcher
        is CACHE-AFFINE (_pick_replica routes a request to the replica
        holding its longest cached prefix), so the discount uses the
        BEST live replica's hit — that is the replica that will
        actually serve it.  1.0 when nothing is cached or no live
        replica exposes a probe."""
        best = None
        for r in self._serving():
            sched = r.scheduler
            if r.state != "live" or sched is None:
                continue
            probe = getattr(sched, "cached_prefix_tokens", None)
            if probe is None:
                return 1.0
            try:
                hit = probe(prompt)
            except Exception:  # noqa: BLE001 — a probe must never shed
                return 1.0
            total = len(prompt) + max_new
            cost = max(0, total - hit) / max(total, 1)
            best = cost if best is None else min(best, cost)
        return 1.0 if best is None else best

    def _predict_wait_s(self, depth: int) -> Optional[float]:
        """Predicted time for `depth` queued requests to clear at the
        measured service rate (None with no measurements yet)."""
        rate = self.service_rate()
        if rate is None or rate <= 0:
            return None
        return depth / rate

    def _retry_after(self, depth: Optional[int] = None) -> float:
        """Retry-After from the measured drain rate: how long until the
        current backlog clears.  Falls back to the constructor constant
        before any completion has been measured."""
        if depth is None:
            with self._cv:
                depth = len(self._admission) + sum(
                    r.outstanding for r in self.replicas)
        predicted = self._predict_wait_s(max(depth, 1))
        if predicted is None:
            return self.shed_retry_after_s
        return min(max(predicted, self.shed_retry_after_s), 120.0)

    # -- client API ------------------------------------------------------
    def generate_async(self, prompt, max_new_tokens: int = 16,
                       temperature: float = 0.0,
                       deadline_s: Optional[float] = None) -> FrontRequest:
        if self._closed:
            raise RuntimeError("ServingFront is closed")
        # validate at admission (the batcher convention: a bad request
        # fails alone, synchronously, as a client error)
        req = FrontRequest(prompt, max_new_tokens, temperature,
                           deadline_s=deadline_s)
        if not 1 <= len(req.prompt) < self.max_seq:
            raise ValueError(
                f"prompt length {len(req.prompt)} outside "
                f"[1, {self.max_seq})")
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be > 0, got {deadline_s}")
        with self._cv:
            if self._terminating:
                # SIGTERM grace: the front is draining — redirect new
                # load with a Retry-After from the measured drain rate
                self.shed_requests += 1
                if self.registry is not None:
                    self.registry.counter("serving/shed_requests").inc()
                raise ServiceUnavailable(
                    "serving front is terminating",
                    retry_after_s=self._retry_after(
                        len(self._admission) + 1),
                )
            if not self._serving_live():
                # no decode-capable replica up: shed instead of
                # queueing against a service that may never come back
                self.shed_requests += 1
                if self.registry is not None:
                    self.registry.counter("serving/shed_requests").inc()
                raise ServiceUnavailable(
                    "all serving replicas are down",
                    retry_after_s=self.shed_retry_after_s,
                )
            depth = len(self._admission)
            backlog = depth + sum(r.outstanding for r in self.replicas)
            # overload admission control: a request whose PREDICTED
            # TTFT (backlog ahead of it / measured service rate)
            # already exceeds its deadline would only time out inside
            # the queue — shed it NOW so the front degrades to a
            # bounded-latency subset under sustained overload
            slo = (deadline_s if deadline_s is not None
                   else self.admission_deadline_s)
            # only predict when there is an actual FRONT backlog: with
            # an empty admission queue the request dispatches at once
            # and its TTFT is service time, not backlog/rate — the
            # measured rate is arrival-limited and would over-predict
            if slo and slo > 0 and depth > 0:
                # capacity-gated rate, NOT the general service rate:
                # Retry-After may hint from an arrival-paced window,
                # but shedding on one would be wrong
                rate = self._capacity_rate()
                # the request's own cost discounts its prefix-cache
                # hit: cached tokens cost zero prefill steps, so a
                # fully cached prompt predicts backlog-drain time only
                own = self._prefix_discount(req.prompt,
                                            req.max_new_tokens)
                predicted = (None if rate is None or rate <= 0
                             else (backlog + own) / rate)
                if predicted is not None and predicted > slo:
                    self.admission_shed += 1
                    if self.registry is not None:
                        self.registry.counter(
                            "serving/admission_shed").inc()
                    raise ServiceUnavailable(
                        f"predicted TTFT {predicted:.2f}s exceeds the "
                        f"{slo:.2f}s deadline (backlog {backlog} at "
                        "the measured service rate)",
                        retry_after_s=min(max(
                            predicted - slo, self.shed_retry_after_s),
                            120.0),
                    )
            req.queue_depth_at_admit = depth
            req.seed = next(self._req_seed)
            if self._reqtrace is not None:
                # mint the request's trace at admission (sampled); the
                # "queue" span stays open until the dispatcher picks
                # the request up
                req.trace = self._reqtrace.trace(
                    "request", prompt_len=len(req.prompt),
                    max_new=req.max_new_tokens)
                if req.trace is not None:
                    req.trace.begin("queue", depth=depth)
            self._admission.append(req)
            self.requests_admitted += 1
            self._cv.notify_all()
        return req

    def generate(self, prompt: Sequence[int], max_new_tokens: int = 16,
                 temperature: float = 0.0,
                 timeout: Optional[float] = 60.0) -> List[int]:
        return self.generate_async(
            prompt, max_new_tokens, temperature).wait(timeout)

    # -- dispatch --------------------------------------------------------
    def _pick_replica(self, req: Optional[FrontRequest] = None
                      ) -> Optional[ServingReplica]:
        """Cache-affine pick: among live replicas with dispatch
        headroom (the cap keeps the backlog at the FRONT, where a
        replica death can't strand it), prefer the replica whose
        prefix cache holds the LONGEST prefix of the request's prompt
        — each pool caches independently, so routing a shared-prefix
        request to the holder turns its prefill into a block-table
        metadata hit instead of a recompute on a cold pool.  Ties and
        cold prompts fall back to least-outstanding."""
        best, best_hit = None, -1
        for r in self._serving():  # prefill-class never serves clients
            sched = r.scheduler  # may concurrently flip to None on death
            if r.state != "live" or sched is None:
                continue
            if r.outstanding >= sched.model.batch_slots:
                continue
            hit = 0
            if req is not None:
                # a resumed generation's cached prefix is its whole
                # replay feed (prompt + generated), not the prompt:
                # affinity routes it to the replica that adopted its
                # migrated blocks
                toks = (req.resume.replay_tokens()
                        if req.resume is not None else req.prompt)
                probe = getattr(sched, "cached_prefix_tokens", None)
                if probe is not None:
                    try:
                        hit = int(probe(toks))
                    except Exception:  # noqa: BLE001 — a probe must
                        hit = 0        # never stall dispatch
            if (best is None or hit > best_hit
                    or (hit == best_hit
                        and r.outstanding < best.outstanding)):
                best, best_hit = r, hit
        if (best is not None and best_hit > 0
                and self.registry is not None):
            self.registry.counter("serving/cache_affine_routed").inc()
        return best

    def _divert_plan(self, req: FrontRequest,
                     replica: ServingReplica) -> Optional[Callable]:
        """Subclass hook, called under _cv with the request popped and
        `replica` the cache-affine pick.  Return None to dispatch
        normally, or a zero-arg thunk to run outside the lock instead
        (the subclass then owns the request's settlement or requeue).
        The base front never diverts."""
        return None

    def _dispatch_loop(self) -> None:
        while True:
            with self._cv:
                replica = None
                while not self._closed:
                    if self._admission:
                        if self._all_permanently_dead():
                            break
                        replica = self._pick_replica(self._admission[0])
                        if replica is not None:
                            break
                    self._cv.wait(0.2)
                if self._closed:
                    return
                req = self._admission.popleft()
                if replica is None:  # every replica permanently dead
                    self._fail(req, ServiceUnavailable(
                        "all serving replicas are permanently dead "
                        "(restart budgets exhausted)",
                        retry_after_s=self.shed_retry_after_s,
                    ))
                    continue
                if req.trace is not None:
                    # dispatch span: covers the routing decision (and
                    # any disagg cost pricing — _divert_plan annotates
                    # it) through the replica submit
                    req.trace.end("queue")
                    req.trace.begin("dispatch",
                                    replica=replica.replica_id,
                                    role=replica.role)
                # disaggregation hook (serving/disagg.py): a subclass
                # may claim the request for a prefill pass + KV
                # migration instead of direct dispatch.  The decision
                # runs under _cv (it books outstanding slots); the
                # returned thunk runs OUTSIDE the lock (it submits).
                divert = self._divert_plan(req, replica)
                if divert is None:
                    replica.outstanding += 1
                    self._observe_depth(replica)
            if divert is not None:
                divert()
                continue
            try:
                replica.submit(
                    req.prompt, req.max_new_tokens, req.temperature,
                    trace=req.trace, seed=req.seed, resume=req.resume,
                    on_done=lambda h, _req=req, _r=replica:
                        self._on_settle(_req, _r, h),
                )
                if req.trace is not None:
                    req.trace.end("dispatch")
            except ValueError as e:
                # pool geometry can never serve it: the request's
                # problem, fail alone
                with self._cv:
                    replica.outstanding -= 1
                    self._observe_depth(replica)
                self._fail(req, e)
            except Exception:
                # the replica died between pick and submit: back to the
                # queue head (dispatch never started — no retry spent).
                # Mid-terminate the residue sweep may already have run,
                # so requeueing would strand the request until close()
                # fails it NON-retriably — settle it 503 instead, as
                # the terminate contract promises.
                shed_req = None
                with self._cv:
                    replica.outstanding -= 1
                    self._observe_depth(replica)
                    if self._terminating or self._closed:
                        shed_req = req
                    else:
                        if req.trace is not None:
                            req.trace.end("dispatch", died=True)
                            req.trace.begin("queue", requeued=True)
                        self._admission.appendleft(req)
                if shed_req is not None:
                    self._fail(shed_req, ServiceUnavailable(
                        "serving front terminated before this request "
                        "was dispatched",
                        retry_after_s=self._retry_after(),
                    ))

    def _observe_depth(self, replica: ServingReplica) -> None:
        if self.registry is not None:
            self.registry.gauge(
                f"serving/replica/{replica.replica_id}/queue_depth"
            ).set(replica.outstanding)

    # -- settlement ------------------------------------------------------
    def _fail(self, req: FrontRequest, err: Exception) -> None:
        req.error = err
        if req.trace is not None:
            req.trace.finish(ok=False, error=type(err).__name__)
        req.event.set()

    def _complete(self, req: FrontRequest, handle,
                  role: Optional[str] = None) -> None:
        req.result = handle.result
        req.n_generated = handle.n_generated
        # a resumed generation's first token landed on an EARLIER
        # replica (stamped at the pause/death settle): keep it — TTFT
        # measures the client's wait, not the last leg's
        if req.t_first_token is None:
            req.t_first_token = handle.t_first_token
        req.t_done = handle.t_done or time.monotonic()
        req.prefix_hit_tokens = getattr(handle, "prefix_hit_tokens", 0)
        req.served_role = role
        per_tok = None
        if (role and req.t_first_token is not None
                and req.n_generated > 1):
            per_tok = ((req.t_done - req.t_first_token)
                       / (req.n_generated - 1))
        self._note_class_done(role, req.t_done, per_tok)
        with self._lat_lock:
            self._latencies.append(req.t_done - req.t_submit)
            if req.t_first_token is not None:
                self._ttfts.append(req.t_first_token - req.t_submit)
            self._done_times.append(req.t_done)  # service-rate window
            # relaxed read of the deque (no _cv inside _lat_lock —
            # lock order is _cv -> _lat_lock): a heuristic flag
            self._done_busy.append(bool(self._admission))
            # settles arrive from every replica's worker thread; the
            # += below is not atomic, so it rides the same lock
            self.requests_done += 1
        if req.trace is not None:
            req.trace.finish(ok=True, n_generated=req.n_generated,
                             retries=req.retries, role=role)
        req.event.set()

    def _on_settle(self, req: FrontRequest, replica: ServingReplica,
                   handle) -> None:
        """Completion hook, fired once per replica-side handle on
        whichever thread settled it (decode loop, drain, or the
        submit-raced close path)."""
        with self._cv:
            replica.outstanding -= 1
            self._observe_depth(replica)
            self._cv.notify_all()
        err = handle.error
        if err is None:
            self._complete(req, handle, role=replica.role)
            return
        if isinstance(err, HandoffPaused):
            # NOT a failure: the replica paused this generation for
            # handoff (drain / terminate / rebalance).  Checked before
            # every other branch — a pause mid-terminate must resume,
            # not shed, or the drain drops the very generation it
            # paused to save.
            self._on_handoff_paused(req, replica, handle, err)
            return
        if isinstance(err, ValueError):
            self._fail(req, err)  # unservable as posed, retry won't help
            return
        if self._terminating:
            # force-closed past the drain deadline: the contract is
            # 503 + Retry-After, never a silent drop or a requeue into
            # a dispatcher that is going away
            self._fail(req, ServiceUnavailable(
                "serving front is terminating",
                retry_after_s=self._retry_after(1),
            ))
            return
        if self._closed:
            self._fail(req, RuntimeError("ServingFront is closed"))
            return
        # replica death, hung step, or transient step fault: the
        # request was ADMITTED, so it never gets a non-retriable error.
        # If the dying scheduler managed to stamp a resume record
        # (tokens live on the host — a dead device cannot tear them),
        # the retry REPLAYS prompt+generated instead of regenerating
        # from scratch: same output, no decode work burned twice.
        rs = getattr(handle, "resume_out", None)
        if rs is not None:
            req.resume = rs
            if req.t_first_token is None:
                req.t_first_token = handle.t_first_token
            self.handoff_replays += 1
            if self.registry is not None:
                self.registry.counter("serving/handoff_replays").inc()
        req.retries += 1
        if req.retries > self.request_retry_limit:
            self._fail(req, ServiceUnavailable(
                f"request failed {req.retries} times across replicas "
                f"(last: {type(err).__name__}: {err})",
                retry_after_s=self.shed_retry_after_s,
            ))
            return
        self.requeued_requests += 1
        if self.registry is not None:
            self.registry.counter("serving/requeued_requests").inc()
        with self._cv:
            if self._closed:
                # close() may have drained the queue between the check
                # above and here; a late requeue would park the client
                # for its full timeout with no dispatcher left
                self._fail(req, RuntimeError("ServingFront is closed"))
                return
            if req.trace is not None:
                # back to the queue: the replica's phase spans ended
                # (or will end truncated); a fresh queue span tracks
                # the wait for the surviving replica
                req.trace.begin("queue", requeued=True,
                                retries=req.retries)
            self._admission.appendleft(req)  # keep its seniority
            self._cv.notify_all()

    # -- mid-decode handoff (serving/handoff.py) -------------------------
    def _handoff_migrator(self):
        """The migrator live handoffs stream through.  A disaggregated
        front reuses its existing migrator (same fabric, same fault
        injection, same counters); the base front lazily builds one
        over an in-process fabric the first time a pause carries a KV
        payload."""
        mig = getattr(self, "migrator", None)
        if mig is not None:
            return mig
        with self._cv:
            if self._handoff_mig is None and not self._closed:
                from .kv_transfer import InProcessFabric, KVMigrator

                self._handoff_mig = KVMigrator(
                    InProcessFabric(), registry=self.registry,
                    logger=self.log, reqtrace=self._reqtrace)
            return self._handoff_mig

    def _handoff_cost_model(self):
        cm = getattr(self, "cost_model", None)  # DisaggServingFront's
        if cm is not None:
            return cm
        if self._handoff_cm is None:
            from .disagg import MigrationCostModel

            self._handoff_cm = MigrationCostModel()
        return self._handoff_cm

    def _pick_handoff_dest(self, source: ServingReplica,
                           toks: Sequence[int]
                           ) -> Optional[ServingReplica]:
        """Live decode-capable destination for a handoff, excluding
        the source; prefer the replica already caching the longest
        prefix of the paused sequence (fewer blocks to ship), ties to
        least outstanding.  No slot-headroom gate: the migration only
        populates the prefix cache — the resumed request queues like
        any other."""
        best, best_hit = None, -1
        for r in self._serving():
            sched = r.scheduler
            if r is source or r.state != "live" or sched is None:
                continue
            hit = 0
            probe = getattr(sched, "cached_prefix_tokens", None)
            if probe is not None:
                try:
                    hit = int(probe(toks))
                except Exception:  # noqa: BLE001 — never stall a pause
                    hit = 0
            if (best is None or hit > best_hit
                    or (hit == best_hit
                        and r.outstanding < best.outstanding)):
                best, best_hit = r, hit
        return best

    def _on_handoff_paused(self, req: FrontRequest,
                           replica: ServingReplica, handle,
                           err: HandoffPaused) -> None:
        """A replica paused this generation for handoff.  Attach the
        resume record, optionally stream the exported KV blocks to a
        live destination, and requeue at the admission head — a pause
        consumes no retry (the request did nothing wrong).  Every
        fault on the live path degrades to replay: the resume record
        alone suffices (chunked-prefill replay of prompt+generated is
        token-identical by construction)."""
        rec = err.record
        req.resume = rec
        if req.t_first_token is None:
            req.t_first_token = handle.t_first_token
        with self._cv:
            self._handoff_inflight += 1
        self.handoff_requested += 1
        if self.registry is not None:
            self.registry.counter("serving/handoff_requested").inc()
        toks = rec.replay_tokens()[:rec.written]
        payload = bool(err.arrays) and bool(err.pages)
        dest = self._pick_handoff_dest(replica, toks) if payload else None
        dsched = dest.scheduler if dest is not None else None
        mig = self._handoff_migrator() if dsched is not None else None
        decision = None
        if (dsched is not None and mig is not None
                and getattr(dsched.model, "import_block", None)
                is not None):
            src = replica.scheduler
            step_ms = dsched.step_ms_ewma or (
                src.step_ms_ewma if src is not None else 0.0)
            decision = self._handoff_cost_model().decide_handoff(
                written=rec.written, page_size=err.page_size,
                block_bytes=int(getattr(dsched.model,
                                        "kv_block_bytes", 0)),
                chunk=int(getattr(dsched.model, "prefill_chunk", 0)),
                step_s=step_ms / 1e3)
            req.migration = decision
            if decision["decision"] != "handoff":
                dsched = None
        if dsched is None or mig is None:
            if decision is not None:
                self.handoff_replay_decisions += 1
                if self.registry is not None:
                    self.registry.counter(
                        "serving/handoff_replay_decisions").inc()
            self._settle_handoff(req, False, None)
            return
        self.handoff_migrate_decisions += 1
        if self.registry is not None:
            self.registry.counter(
                "serving/handoff_migrate_decisions").inc()
        wire = None
        if req.trace is not None:
            req.trace.begin("handoff", src=replica.replica_id,
                            dest=dest.replica_id,
                            blocks=len(err.arrays),
                            written=rec.written)
            wire = req.trace.wire(parent=req.trace.open_id("handoff"))
        mig.migrate_live(
            tokens=toks, pages=err.pages, blocks=err.arrays,
            page_size=err.page_size, target=dsched, wire=wire,
            on_done=lambda ok, detail: self._settle_handoff(
                req, ok, detail))

    def _settle_handoff(self, req: FrontRequest, ok: bool,
                        detail: Optional[Dict]) -> None:
        """Exactly-once tail of every pause: count the outcome and
        requeue at the admission head with the resume record attached.
        A live-handoff fault is NOT a request failure — the resume
        admission replays whatever was not adopted, so the output
        stays exact either way."""
        rec = req.resume
        if ok and detail is not None and rec is not None:
            # the verified partial tail page rides the resume record:
            # admission lands it in the resumed sequence's fresh
            # private block (a sub-page tail has no cache key)
            rec.kv_tail = detail.get("tail")
            self.handoff_ok += 1
            if self.registry is not None:
                self.registry.counter("serving/handoff_ok").inc()
        else:
            self.handoff_replays += 1
            if self.registry is not None:
                self.registry.counter("serving/handoff_replays").inc()
            kind = (detail or {}).get("fault")
            if kind:
                self.handoff_faults[kind] = (
                    self.handoff_faults.get(kind, 0) + 1)
                if self.registry is not None:
                    self.registry.counter(
                        f"serving/handoff_fault_{kind}").inc()
        if req.trace is not None and detail is not None:
            req.trace.end("handoff", ok=bool(ok),
                          fault=(detail or {}).get("fault"))
        with self._cv:
            self._handoff_inflight -= 1
            if self._closed:
                self._fail(req, RuntimeError("ServingFront is closed"))
                self._cv.notify_all()
                return
            if req.trace is not None:
                req.trace.begin("queue", requeued=True, resume=True)
            self._admission.appendleft(req)  # keeps its seniority
            self._cv.notify_all()

    def rebalance_replica(self, replica: ServingReplica,
                          max_sequences: int = 1) -> bool:
        """Hot-replica rebalance: pause up to `max_sequences` of the
        longest-remaining generations on `replica` so they resume on
        a cooler member.  The autoscaler's KV-occupancy trigger calls
        this; the path is the same one drain and terminate use."""
        if not self.handoff:
            return False
        with self._cv:
            others = [r for r in self._serving_live()
                      if r is not replica]
        if not others:
            return False
        ok = replica.request_handoff(
            remaining_over=0, max_sequences=int(max_sequences),
            export_kv=True)
        if ok and self.registry is not None:
            self.registry.counter("serving/handoff_rebalance").inc()
        return ok

    # -- stats / health --------------------------------------------------
    @property
    def worker_alive(self) -> bool:
        return self._dispatcher.is_alive() and not self._all_permanently_dead()

    @property
    def batches_run(self) -> int:
        with self._cv:
            fleet = list(self.replicas) + list(self.retired)
            folded = self._retired_folded["batches_run"]
        return folded + sum(r.stats()["batches_run"] for r in fleet)

    @property
    def tokens_generated(self) -> int:
        with self._cv:
            fleet = list(self.replicas) + list(self.retired)
            folded = self._retired_folded["tokens_generated"]
        return folded + sum(r.stats()["tokens_generated"] for r in fleet)

    def latency_stats(self) -> Dict[str, float]:
        from .batcher import latency_percentiles

        return latency_percentiles(self._latencies, self._lat_lock)

    def ttft_stats(self) -> Dict[str, float]:
        from .batcher import latency_percentiles

        return latency_percentiles(self._ttfts, self._lat_lock)

    @property
    def roles_active(self) -> bool:
        """True once any replica carries a non-mixed role (the fleet is
        disaggregated or transitioning)."""
        return any(r.role != "mixed" for r in self.replicas)

    def class_stats(self) -> Dict[str, Dict]:
        """Per-role fleet accounting: replica counts, outstanding,
        measured class service rate, merged TTFT percentiles from each
        member scheduler's window (the prefill class's TTFT is its
        internal pass time — there is no client TTFT for it), and
        per-token decode percentiles from front-side samples."""
        from .batcher import percentile_summary

        with self._cv:
            replicas = list(self.replicas)
        by_role: Dict[str, List[ServingReplica]] = {}
        for r in replicas:
            by_role.setdefault(r.role, []).append(r)
        with self._lat_lock:
            toks = {k: list(v) for k, v in self._class_tok.items()}
        out: Dict[str, Dict] = {}
        for role, members in sorted(by_role.items()):
            ttfts: List[float] = []
            for r in members:
                sched = r.scheduler
                if sched is None:
                    continue
                with sched._lat_lock:
                    ttfts.extend(sched._ttfts)
            rate = self.service_rate(role)
            out[role] = {
                "replicas": len(members),
                "live": sum(1 for r in members if r.alive),
                "outstanding": sum(r.outstanding for r in members),
                "chips": len(members) * self.chips_per_replica,
                "service_rate_rps": (round(rate, 3)
                                     if rate is not None else None),
                "ttft": percentile_summary(ttfts),
                "per_token": percentile_summary(toks.get(role, [])),
            }
        return out

    def health(self) -> Dict:
        """ok = every fleet member live or intentionally draining;
        degraded = a replica is restarting/dead but something still
        serves; down = nothing live (server.py rides this to HTTP
        200/200/503).  A DRAINING replica is an intentional,
        autoscaler-driven exit — it finishes its in-flight work but
        takes nothing new, and does NOT degrade the front."""
        with self._cv:
            replicas = list(self.replicas)
            retired = len(self.retired) + self._retired_dropped
        live = sum(1 for r in replicas if r.alive)
        serving_live = sum(1 for r in replicas
                           if r.alive and r.role != "prefill")
        draining = sum(1 for r in replicas if r.state == "draining")
        broken = sum(1 for r in replicas
                     if r.state in ("restarting", "dead"))
        # "down" means no replica can FINISH a client request — a
        # healthy prefill class cannot keep a decode-less fleet up
        if self._closed or serving_live == 0:
            status = "down"
        elif broken:
            status = "degraded"
        else:
            status = "ok"
        out = {
            "status": status,
            "replicas_live": live,
            "replicas_draining": draining,
            "replicas_retired": retired,
            "terminating": self._terminating,
            "replicas": [
                {"id": r.replica_id, "state": r.state,
                 "role": r.role,
                 "restarts": r.restarts, "deaths": r.deaths}
                for r in replicas
            ],
        }
        if any(r.role != "mixed" for r in replicas):
            out["roles"] = {
                role: {"replicas": sum(1 for r in replicas
                                       if r.role == role),
                       "live": sum(1 for r in replicas
                                   if r.role == role and r.alive)}
                for role in sorted({r.role for r in replicas})
            }
        return out

    @property
    def admission_depth(self) -> int:
        """Front-queue depth alone (excludes dispatched in-flight)."""
        with self._cv:
            return len(self._admission)

    def stats(self) -> Dict:
        with self._cv:
            queued = len(self._admission)
            replicas = [r.stats() for r in self.replicas]
            retired = [r.stats() for r in self.retired]
            retired_n = len(self.retired) + self._retired_dropped
            folded = dict(self._retired_folded)
        if self.registry is not None:
            self.registry.gauge("serving/replicas_live").set(
                len(self._live()))
        rate = self.service_rate()
        out = {
            "mode": "replicated",
            "chips_per_replica": self.chips_per_replica,
            "chip_budget": self.chip_budget,
            "fleet_chips": len(replicas) * self.chips_per_replica,
            "replicas_live": len(self._live()),
            "replicas_draining": sum(1 for r in replicas
                                     if r["state"] == "draining"),
            "replicas_retired": retired_n,
            "queue_depth": queued + sum(r["outstanding"]
                                        for r in replicas),
            "requests_done": self.requests_done,
            "requeued_requests": self.requeued_requests,
            "shed_requests": self.shed_requests,
            "admission_shed": self.admission_shed,
            "service_rate_rps": (round(rate, 3)
                                 if rate is not None else None),
            "tokens_generated": (folded["tokens_generated"]
                                 + sum(r["tokens_generated"]
                                       for r in replicas)
                                 + sum(r["tokens_generated"]
                                       for r in retired)),
            "steps": (folded["batches_run"]
                      + sum(r["batches_run"] for r in replicas)
                      + sum(r["batches_run"] for r in retired)),
            "ttft": self.ttft_stats(),
            "latency": self.latency_stats(),
            "replicas": replicas,
        }
        if self.roles_active:
            out["roles"] = self.class_stats()
        if self.handoff or self.handoff_requested:
            out["handoff"] = {
                "requested": self.handoff_requested,
                "ok": self.handoff_ok,
                "replays": self.handoff_replays,
                "migrate_decisions": self.handoff_migrate_decisions,
                "replay_decisions": self.handoff_replay_decisions,
                "faults": dict(self.handoff_faults),
            }
            mig = self._handoff_mig
            if mig is not None:
                out["handoff"]["kv_transfer"] = mig.stats()
        if self.autoscaler is not None:
            out["autoscaler"] = self.autoscaler.stats()
        return out

    # -- shutdown --------------------------------------------------------
    def terminate(self, deadline_s: float = 30.0) -> Dict:
        """SIGTERM grace, the serving-side twin of the training
        supervisor's preemption grace (docs/RESILIENCE.md): stop
        admitting (new submissions shed with 503 + Retry-After from the
        measured drain rate), drain every replica under `deadline_s` —
        in-flight and already-queued requests run to completion — then
        shed the residue and close.  No admitted request is ever
        silently dropped: each one either completes or settles with a
        retriable ServiceUnavailable.

        Returns a report: completed/shed counts, drained replicas,
        whether the deadline was met, and the elapsed time."""
        t0 = time.monotonic()
        with self._cv:
            if self._closed or self._terminating:
                return {"already_terminating": True}
            self._terminating = True
            done_before = self.requests_done
            self._cv.notify_all()
        if self.registry is not None:
            self.registry.counter("serving/terminations").inc()
        self.log.info("serving front terminating: draining %d replicas "
                      "under %.1fs", len(self.replicas), deadline_s)
        deadline = t0 + deadline_s
        # phase 1: the dispatcher keeps handing QUEUED requests to the
        # still-live replicas — draining them now would strand the
        # backlog, so wait for the queue to empty (or the deadline)
        with self._cv:
            while (time.monotonic() < deadline and self._admission
                   and self._live()):
                self._cv.wait(min(
                    0.05, max(0.001, deadline - time.monotonic())))
            replicas = list(self.replicas)
        # phase 2: nothing left to dispatch (or out of time) — drain
        # every replica; in-flight slots run to completion.  With
        # handoff enabled the serving class retires in two waves:
        # every member but one survivor drains first, pausing the
        # generations it cannot FINISH before the deadline (remaining
        # tokens vs the measured step rate) onto the handoff path;
        # the survivor serves the resumed requests and drains last —
        # so a long generation is migrated, never shed at the bell.
        survivor = None
        if self.handoff:
            cands = [r for r in replicas
                     if r.alive and r.role != "prefill"]
            if len(cands) > 1:
                # the busiest member keeps its own work: it migrates
                # nothing, everyone else's unfinishables land on it
                survivor = max(cands, key=lambda r: r.outstanding)
        for r in replicas:
            if r is survivor:
                continue
            r.drain(on_retired=self._on_replica_retired)
            if survivor is not None and r.role != "prefill":
                self._terminate_handoff(r, deadline)
        if survivor is not None:
            with self._cv:
                while time.monotonic() < deadline:
                    others_open = any(
                        r.state in ("live", "draining", "restarting")
                        for r in self.replicas if r is not survivor)
                    if (not others_open and not self._admission
                            and self._handoff_inflight == 0):
                        break
                    self._cv.wait(min(0.05, max(
                        0.001, deadline - time.monotonic())))
            survivor.drain(on_retired=self._on_replica_retired)
        while time.monotonic() < deadline:
            with self._cv:
                # a replica mid-rebuild at the snapshot above refused
                # its drain() and comes back "live" after — catch it
                late_live = [r for r in self.replicas
                             if r.state == "live"]
            for r in late_live:  # outside the lock: drain fans into
                r.drain(on_retired=self._on_replica_retired)  # the sched
            with self._cv:
                settled = all(
                    r.state in ("retired", "dead", "closed")
                    for r in self.replicas)
                if not self._admission and settled:
                    break
                self._cv.wait(min(
                    0.05, max(0.001, deadline - time.monotonic())))
        with self._cv:
            residue = list(self._admission)
            self._admission.clear()
        # residue past the deadline: 503 + Retry-After from the
        # measured drain rate — the client knows when to come back
        shed = 0
        for req in residue:
            self._fail(req, ServiceUnavailable(
                "serving front terminated before this request was "
                "dispatched",
                retry_after_s=self._retry_after(len(residue)),
            ))
            shed += 1
        deadline_met = not residue and time.monotonic() <= deadline
        # bounded close sweeps up wedged DRAINING replicas; their
        # in-flight requests settle as 503s through _on_settle's
        # terminating branch
        self.close(timeout_s=max(0.1, deadline - time.monotonic()))
        report = {
            "duration_s": round(time.monotonic() - t0, 3),
            "deadline_s": deadline_s,
            "deadline_met": deadline_met,
            "completed_during_drain": self.requests_done - done_before,
            "shed": shed,
            "replicas_retired": len(self.retired) + self._retired_dropped,
        }
        self.log.info("serving front terminated: %s", report)
        return report

    def _terminate_handoff(self, replica: ServingReplica,
                           deadline: float) -> None:
        """Pause the sequences a draining replica cannot finish before
        the terminate deadline: a sequence whose remaining tokens
        exceed time-left / measured-step-EWMA would otherwise still be
        decoding when the residue sweep sheds it.  Finishable
        sequences keep decoding to completion (cheaper than any
        migration); the unfinishable ones take the handoff path and
        resume on the surviving replica."""
        sched = replica.scheduler
        step_ms = (getattr(sched, "step_ms_ewma", 0.0)
                   if sched is not None else 0.0) or 5.0
        time_left = max(0.0, deadline - time.monotonic())
        budget = max(1, int(time_left / (step_ms / 1e3)))
        replica.request_handoff(remaining_over=budget, export_kv=True)

    def install_grace_handlers(self, deadline_s: float = 30.0) -> Dict:
        """SIGTERM/SIGINT -> graceful terminate() on a daemon thread
        (the supervisor's preemption-grace pattern on the serving
        side).  Main-thread only; returns the displaced handlers so an
        embedding process can restore them."""
        if threading.current_thread() is not threading.main_thread():
            return {}
        installed = {}

        def _on_signal(signum, frame):
            self.log.info(
                "%s received: graceful serving drain under %.1fs",
                signal.Signals(signum).name, deadline_s)
            threading.Thread(
                target=self.terminate, args=(deadline_s,),
                daemon=True, name="serving-front-terminate",
            ).start()

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                installed[sig] = signal.signal(sig, _on_signal)
            except (ValueError, OSError):  # exotic embeddings
                break
        return installed

    def close(self, timeout_s: Optional[float] = None):
        """Stop dispatching, close every replica, and fail whatever is
        still queued, promptly.  An explicit `timeout_s` is a TOTAL
        budget shared by the whole fleet (terminate()'s deadline
        contract — N wedged replicas must not each get the full
        bound); None lets each replica use its own close_timeout_s."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        scaler = self.autoscaler
        if scaler is not None:
            scaler.stop()
        self._dispatcher.join(timeout=2.0)
        with self._cv:
            # retired replicas released their threads at _retire();
            # sweeping them too makes close() the backstop either way
            replicas = list(self.replicas) + list(self.retired)
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        for r in replicas:
            r.close(None if deadline is None
                    else max(0.05, deadline - time.monotonic()))
        # the lazy handoff migrator (a disagg front's migrator is
        # closed by its own close override): its drain fails every
        # pending on_done, which settles the requests below
        mig = self._handoff_mig
        if mig is not None:
            self._handoff_mig = None
            mig.close()
        err = RuntimeError("ServingFront is closed")
        with self._cv:
            while self._admission:
                self._fail(self._admission.popleft(), err)
