"""Speculative decoding proposers for the continuous engine
(docs/SERVING.md "Speculative decoding").

Decode throughput is bounded by one dispatch per generated token per
slot.  Speculation (Leviathan et al., arXiv:2211.17192) breaks that
bound at temperature 0 without changing a single output token: a cheap
PROPOSER guesses up to k continuation tokens per eligible slot, the
target model scores all of them in ONE chunk-twin dispatch
(decoding.build_paged_verify_step — a lax.scan of the seq-1 decode
graph, so the per-position logits are bit-identical to stepping one
token at a time), and the scheduler accepts the longest prefix whose
tokens match the target's own greedy choices plus the first corrected
token.  Rejected positions roll back out of the KV pool
(kv_pool.rollback — un-registers any prefix-index entries covering
them and copy-on-writes a kept shared tail).

Two proposers:

* `NGramProposer` — prompt-lookup decoding: the longest suffix n-gram
  of the request's own prompt+generated tokens is matched against its
  most recent earlier occurrence and the tokens that followed it are
  proposed.  Host-only, zero device cost, and strong exactly where
  serving traffic is repetitive (templated prompts, quoting, code).

* `DraftModelProposer` — a smaller GPT from the same builder running
  through its OWN paged decode engine (an independent
  PagedKVDecodeModel + KVPool).  The draft engine catches up to each
  slot's accepted context (re-feeding divergent tails after a
  rejection, via its own pool rollback) and then free-runs k greedy
  steps.  Draft dispatches are cheap relative to the target; any draft
  fault permanently degrades to "no proposals" — the engine falls back
  to plain decode, never dies on the drafter's account.

`AdaptiveK` shrinks the per-round draft length toward 1 when measured
acceptance is poor and grows it back toward --spec-k when drafts are
landing, so a hostile workload costs at most one wasted verify
position per round — the never-worse-than-baseline knob.

The proposer contract (`propose(contexts, k, limits)`) is BATCHED: one
call per decode round with every eligible slot's context, so a draft
model services all slots with shared batched dispatches instead of a
dispatch per slot.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .kv_pool import KVPool

__all__ = ["Proposer", "NGramProposer", "DraftModelProposer",
           "AdaptiveK", "build_proposer"]


class Proposer:
    """Interface a speculative proposer implements.  All methods are
    called from the scheduler's worker thread only."""

    def propose(self, contexts: Dict[int, List[int]], k: int,
                limits: Optional[Dict[int, int]] = None,
                ) -> Dict[int, List[int]]:
        """One decode round's drafts.  `contexts[slot]` is the slot's
        full accepted token sequence (prompt + generated so far);
        `limits[slot]` bounds the total tokens the slot's sequence may
        ever reach (prompt + max_new + k, clamped to the position
        table).  Returns up to k draft tokens per slot; slots may be
        omitted (no proposal this round — they ride the round as plain
        one-token decode)."""
        raise NotImplementedError

    def release(self, slot: int) -> None:
        """The slot's request finished/failed — drop any per-slot
        drafter state."""

    def reset(self) -> None:
        """The engine reset (transient fault recovery): drop ALL
        drafter state.  Called before the engine resumes decoding."""

    def stats(self) -> Dict:
        return {}

    def trace_attrs(self) -> Dict:
        """Small JSON-safe attribute dict stamped onto each spec_verify
        batch span (obs/reqtrace.py) — which drafter produced the
        round's proposals, plus any cheap per-proposer counters.
        Called on the scheduler worker thread, once per traced round."""
        return {"proposer": type(self).__name__}


class NGramProposer(Proposer):
    """Prompt-lookup decoding: propose the continuation of the MOST
    RECENT earlier occurrence of the context's longest suffix n-gram,
    preferring longer n-grams (max_ngram down to min_ngram).  Stateless
    across rounds — the context IS the state."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1,
                 max_window: int = 4096):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"[{min_ngram}, {max_ngram}]")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        # lookback bound so one pathological context cannot make a
        # round's host time quadratic in the position table
        self.max_window = int(max_window)

    def _lookup(self, ctx: Sequence[int], k: int) -> List[int]:
        n_ctx = len(ctx)
        lo = max(0, n_ctx - self.max_window)
        for n in range(self.max_ngram, self.min_ngram - 1, -1):
            if n_ctx <= n:
                continue
            pat = list(ctx[n_ctx - n:])
            # scan right-to-left: the most recent match's continuation
            # is the likeliest to still be the live pattern
            for s in range(n_ctx - n - 1, lo - 1, -1):
                if list(ctx[s:s + n]) == pat:
                    cont = ctx[s + n:s + n + k]
                    if cont:
                        return [int(t) for t in cont]
                    break  # suffix match with no continuation room
        return []

    def propose(self, contexts, k, limits=None):
        out: Dict[int, List[int]] = {}
        for slot, ctx in contexts.items():
            d = self._lookup(ctx, k)
            if d:
                out[slot] = d
        return out


class _DraftSeq:
    """Per-slot draft-engine bookkeeping: the draft pool sequence, the
    tokens actually FED into it (KV positions 0..len(hist)-1), the
    greedy drafts minted beyond the accepted context, and the slot's
    lifetime token cap."""

    __slots__ = ("seq", "hist", "cap")

    def __init__(self, seq: int, cap: int):
        self.seq = seq
        self.hist: List[int] = []
        self.cap = int(cap)


class DraftModelProposer(Proposer):
    """Drafts with a smaller GPT through its own paged decode engine.

    `draft_model` is a PagedKVDecodeModel (or anything with its step
    contract) built from the SAME builder family as the target: its
    vocab must match (draft argmax ids are proposed verbatim), its
    position table must cover the target's, and it must have at least
    as many batch slots (draft rows mirror engine slots 1:1).

    Round shape: `propose` first RECONCILES each slot — the draft
    pool rolls back to the longest prefix its fed history shares with
    the slot's accepted context (a rejected draft tail, or a plain
    round's correction, simply re-feeds from the divergence point) —
    then catches up and free-runs greedy draft steps, all slots
    batched per dispatch.  Catch-up is bounded per round
    (`dispatch_budget`): a slot with a long un-fed prompt yields no
    proposals for a round or two instead of stalling every other
    slot's verify cadence.

    Fault posture: the draft engine is UNSUPERVISED — any exception
    from a draft dispatch marks the proposer dead (empty proposals
    forever) and the serving engine continues as a plain decoder.
    reset() revives it from zeroed pools."""

    def __init__(self, draft_model, dispatch_budget: int = 32):
        self.model = draft_model
        self.pool = KVPool(draft_model.num_blocks,
                           draft_model.page_size,
                           draft_model.max_blocks_per_seq,
                           prefix_cache=False)
        self.dispatch_budget = max(4, int(dispatch_budget))
        self._st: Dict[int, _DraftSeq] = {}
        self._next_seq = 0
        self._dead = False
        self.draft_steps = 0      # draft-engine dispatches, lifetime
        self.draft_faults = 0

    # -- slot lifecycle -------------------------------------------------
    def _ensure(self, slot: int, ctx: Sequence[int],
                limit: Optional[int]) -> Optional[_DraftSeq]:
        st = self._st.get(slot)
        if st is not None:
            return st
        cap = min(int(limit) if limit else self.model.max_seq,
                  self.model.max_seq)
        if cap <= len(ctx):
            return None  # no room to even re-feed the last token
        seq = self._next_seq
        if not self.pool.try_admit(seq, cap, prompt=None):
            return None  # draft pool full: retry after a release
        self._next_seq += 1
        st = _DraftSeq(seq, cap)
        self._st[slot] = st
        return st

    def release(self, slot: int) -> None:
        st = self._st.pop(slot, None)
        if st is not None:
            try:
                self.pool.retire(st.seq)
            except KeyError:
                pass

    def reset(self) -> None:
        for slot in list(self._st):
            self.release(slot)
        try:
            reset = getattr(self.model, "reset", None)
            if reset is not None:
                reset()
        except Exception:  # noqa: BLE001 — reviving is best-effort
            return
        self._dead = False

    def _reconcile(self, st: _DraftSeq, ctx: Sequence[int]) -> None:
        """Roll the draft sequence back to the longest prefix of `ctx`
        it has actually fed — capped at len(ctx)-1 so the context's
        final token is always (re-)fed this round, because ITS logits
        seed the first draft.  Re-fed positions rewrite byte-identical
        KV (same program, same inputs), so no copy is ever needed."""
        lcp = 0
        for a, b in zip(st.hist, ctx):
            if a != int(b):
                break
            lcp += 1
        target = min(lcp, len(ctx) - 1)
        if len(st.hist) > target:
            self.pool.rollback(st.seq, target)
            del st.hist[target:]

    # -- the round ------------------------------------------------------
    def propose(self, contexts, k, limits=None):
        if self._dead or k < 1 or not contexts:
            return {}
        limits = limits or {}
        bs = self.model.batch_slots
        active: Dict[int, List[int]] = {}
        for slot, ctx in contexts.items():
            if slot >= bs:
                continue  # geometry mismatch guard (validated upstream)
            st = self._ensure(slot, ctx, limits.get(slot))
            if st is None:
                continue
            self._reconcile(st, [int(t) for t in ctx])
            active[slot] = [int(t) for t in ctx]
        drafts: Dict[int, List[int]] = {slot: [] for slot in active}
        tw = self.pool.max_blocks_per_seq
        try:
            for _ in range(self.dispatch_budget):
                tok = np.zeros(bs, np.int32)
                slen = np.zeros(bs, np.int32)
                btab = np.zeros((bs, tw), np.int32)
                feeding = []
                for slot, ctx in active.items():
                    st = self._st[slot]
                    fed = len(st.hist)
                    if fed < len(ctx):
                        nxt = ctx[fed]          # catch-up
                    elif (len(drafts[slot]) < k and drafts[slot]
                          and fed < min(st.cap, self.model.max_seq)):
                        nxt = drafts[slot][-1]  # free-run its own draft
                    else:
                        continue                # slot done this round
                    self.pool.extend(st.seq, fed + 1, written=fed)
                    btab[slot] = self.pool.table_row(st.seq)
                    tok[slot] = nxt
                    slen[slot] = fed
                    feeding.append((slot, nxt))
                if not feeding:
                    break
                logits = self.model.step(tok, slen, btab)
                self.draft_steps += 1
                for slot, nxt in feeding:
                    st = self._st[slot]
                    st.hist.append(nxt)
                    self.pool.note_written(st.seq, len(st.hist))
                    if len(st.hist) >= len(active[slot]):
                        # this dispatch scored the context's last token
                        # (first draft) or a fed draft (the next one)
                        drafts[slot].append(int(logits[slot].argmax()))
        except Exception:  # noqa: BLE001 — draft faults NEVER kill the
            # serving engine: degrade to plain decode permanently
            # (reset() revives after an engine-level recovery)
            self._dead = True
            self.draft_faults += 1
            return {}
        return {slot: d[:k] for slot, d in drafts.items() if d}

    def stats(self) -> Dict:
        return {
            "draft_steps": self.draft_steps,
            "draft_faults": self.draft_faults,
            "dead": self._dead,
            "live_draft_seqs": len(self._st),
        }

    def trace_attrs(self) -> Dict:
        # cumulative draft-step count: the delta between consecutive
        # verify-round spans is the drafts this round cost
        return {"proposer": type(self).__name__,
                "draft_steps": self.draft_steps}


class AdaptiveK:
    """Acceptance-rate-adaptive draft length: an EWMA of per-round
    acceptance (accepted drafts / proposed drafts) shrinks k toward 1
    below `lo` and grows it back toward k_max above `hi`.  A workload
    the proposer cannot predict therefore costs at most ONE wasted
    verify position per round — speculation is never materially worse
    than plain decode."""

    def __init__(self, k_max: int, ewma: float = 0.4,
                 lo: float = 0.2, hi: float = 0.6):
        self.k_max = max(1, int(k_max))
        self.k = self.k_max
        self.rate = 1.0  # optimistic start: first rounds draft fully
        self._ewma = float(ewma)
        self._lo = float(lo)
        self._hi = float(hi)

    def update(self, proposed: int, accepted: int) -> None:
        if proposed <= 0:
            return
        r = accepted / proposed
        self.rate = (1.0 - self._ewma) * self.rate + self._ewma * r
        if self.rate < self._lo and self.k > 1:
            self.k -= 1
        elif self.rate > self._hi and self.k < self.k_max:
            self.k += 1


def build_proposer(spec_decode: str, draft_model=None) -> Proposer:
    """Proposer for a validated --spec-decode mode (the scheduler's
    build hook).  "draft" requires the draft engine to exist — missing
    it is a build-time ConfigError, not a silent fallback."""
    from ..config import ConfigError

    if spec_decode == "ngram":
        return NGramProposer()
    if spec_decode == "draft":
        if draft_model is None:
            raise ConfigError(
                "--spec-decode draft needs a draft model: build the "
                "engine with a draft twin (ContinuousScheduler."
                "from_trained(..., draft_ff=<smaller GPT>) or "
                "PagedKVDecodeModel(draft_model=...)) or use "
                "--spec-decode ngram")
        return DraftModelProposer(draft_model)
    raise ConfigError(
        f"no proposer for spec_decode mode {spec_decode!r}")
