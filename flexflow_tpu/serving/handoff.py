"""Resumable decode handoff (docs/SERVING.md "Mid-decode handoff").

An in-flight generation is a first-class migratable object: the
scheduler can pause it at a step boundary and settle the handle with
:class:`HandoffPaused`, which carries everything needed to continue
the generation elsewhere —

* a host-side :class:`ResumeRecord` (prompt + generated-so-far tokens,
  KV write position, the per-request sampling seed and the exact
  host RNG state), sufficient on its own to resume by chunked-prefill
  REPLAY of prompt+generated (never regenerate-from-scratch), and
* optionally the sequence's exported KV blocks (prompt *and*
  generated, including the partial tail page) for a live handoff that
  streams the blocks as FFKV frames so the destination adopts them
  instead of recomputing.

Replay is exact by construction: the generated tokens are re-fed as
prompt (KV bytes are a pure function of the token prefix and the
weights), and temperature>0 sampling restores the captured
``numpy.random.RandomState`` state before the next draw, so the
continuation is token-identical to the uninterrupted run.  Every
handoff fault degrades to this replay path; the front classifies them
into the ``serving/handoff_fault_{kind}`` counter family.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

#: fault kinds a live handoff can degrade on — each increments its own
#: serving/handoff_fault_{kind} counter and falls back to replay
HANDOFF_FAULTS = ("torn", "header", "fabric", "capacity", "dest_death")


class ResumeRecord:
    """Host-side snapshot of an in-flight generation, captured at a
    step boundary (pause) or at replica death (the tokens live on the
    host, so a dead device cannot tear them)."""

    __slots__ = ("prompt", "generated", "written", "seed",
                 "temperature", "rng_state", "kv_tail", "page_size")

    def __init__(self, prompt: Sequence[int], generated: Sequence[int],
                 written: int, seed: int, temperature: float,
                 rng_state: Any = None, kv_tail: Optional[Dict] = None,
                 page_size: int = 0):
        self.prompt = list(prompt)
        self.generated = list(generated)
        # KV tokens written at capture time: the pause point's
        # pool watermark, always < len(prompt)+len(generated) because
        # the newest token rides unwritten as the next step's feed
        self.written = int(written)
        self.seed = int(seed)
        self.temperature = float(temperature)
        # numpy RandomState.get_state() tuple (None for greedy):
        # restored before the first post-resume draw, so a replay
        # makes NO draws for the re-fed tokens and continues the
        # sampled stream exactly where the pause left it
        self.rng_state = rng_state
        # arrays of the partial tail KV block when a live handoff
        # verified it on the wire (full pages adopt through the prefix
        # cache; the sub-page tail cannot be indexed, so it lands
        # directly in the resumed sequence's fresh private block)
        self.kv_tail = kv_tail
        self.page_size = int(page_size)

    def replay_tokens(self) -> List[int]:
        """The feed for resume admission: the original prompt plus
        every token generated before the pause, re-fed as prompt so
        chunked prefill (or an adopted-prefix cache hit) rebuilds the
        exact KV state."""
        return self.prompt + self.generated

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"ResumeRecord(plen={len(self.prompt)}, "
                f"gen={len(self.generated)}, written={self.written}, "
                f"tail={'yes' if self.kv_tail is not None else 'no'})")


class HandoffPaused(Exception):
    """Settled into a scheduler handle when its generation is paused
    for handoff.  Not a failure: the front recognizes it, optionally
    streams the exported blocks to a destination replica, and requeues
    the request with the resume record attached (no retry consumed)."""

    def __init__(self, record: ResumeRecord,
                 pages: Optional[List[List[int]]] = None,
                 arrays: Optional[List[Dict]] = None,
                 page_size: int = 0):
        super().__init__(
            f"generation paused for handoff ({len(record.generated)} "
            f"tokens generated, {record.written} KV tokens written)")
        self.record = record
        #: token pages (last may be partial) + exported host arrays
        #: per block — None when the pause exported nothing (replay-
        #: only resume, e.g. the model has no export surface)
        self.pages = pages
        self.arrays = arrays
        self.page_size = int(page_size)


def classify_handoff_fault(reason: Optional[str],
                           exc: Optional[BaseException] = None) -> str:
    """Map a migrator failure reason (kv_transfer.KVMigrator's
    on_done detail) onto the fault-matrix counter family."""
    reason = reason or ""
    if reason == "torn" or "no block verified" in reason:
        return "torn"
    if reason == "header":
        return "header"
    if reason == "capacity":
        return "capacity"
    if reason in ("device write", "target gone", "target closed",
                  "migrator closed"):
        return "dest_death"
    if reason == "transfer":
        # a mangled frame raises KVTransferError (header/crc damage);
        # anything else is the fabric itself failing
        from .kv_transfer import KVTransferError

        if isinstance(exc, KVTransferError):
            return "header"
        return "fabric"
    return "fabric"
