"""Generation serving: KV-cached incremental decoding behind the
batcher/HTTP surface (VERDICT r4 #4 — the scope the reference's
triton/ backend never reached: it is forward-only inference,
triton/README.md:3-6).

`GenerationEngine` owns a decode twin (decoding.make_gpt_decoder) of a
trained GPT and runs whole generations as single XLA scan programs
(decoding.run_generate_scan): per-row prompt lengths are a traced
operand, so one compiled program per (total-length bucket, temperature)
serves ANY mix of prompt lengths — concurrent requests with different
prompts coalesce into one device program with zero recompiles.

`GenerationBatcher` is the request coalescer: a worker thread drains
the queue, groups compatible requests (same temperature) up to the
decode batch, runs one scan, and scatters per-request trimmed token
rows back to the waiters.  Latency percentiles ride the same ring
buffer machinery as the forward batcher.
"""
from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..decoding import _gpt_dims, make_gpt_decoder, run_generate_scan
from ..model import FFModel


def _pow2_bucket(n: int, cap: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return min(b, cap)


class GenerationEngine:
    """Batched generation on the KV-cache decode twin of a trained GPT.

    Requests are (prompt, max_new_tokens) pairs; the engine right-pads
    prompts into one [batch, total] buffer (total = the power-of-two
    bucket of the largest plen+max_new, capped at the model's position
    table), runs one scan program, and trims each row to its own
    plen + max_new_tokens (and at eos_id when set)."""

    def __init__(self, ff_train: FFModel, batch_size: int = 8,
                 devices=None, eos_id: int = -1):
        self.ffd = make_gpt_decoder(ff_train, batch_size=batch_size,
                                    devices=devices)
        self.batch_size = batch_size
        self.max_seq = _gpt_dims(self.ffd)["max_seq"]
        self.eos_id = eos_id
        self.generations_run = 0

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens=16, temperature: float = 0.0,
                 seed: int = 0) -> List[List[int]]:
        """prompts: up to batch_size token id lists (any lengths >= 1).
        max_new_tokens: int or per-prompt sequence.  Returns per-prompt
        full token lists (prompt + continuation)."""
        n = len(prompts)
        if not 1 <= n <= self.batch_size:
            raise ValueError(
                f"{n} prompts for a batch-{self.batch_size} engine")
        mnt = (list(max_new_tokens) if not isinstance(max_new_tokens, int)
               else [max_new_tokens] * n)
        if len(mnt) != n:
            raise ValueError("per-prompt max_new_tokens length mismatch")
        plens = [len(p) for p in prompts]
        if min(plens) < 1:
            raise ValueError("empty prompt")
        if max(plens) >= self.max_seq:
            raise ValueError(
                f"prompt length {max(plens)} >= max positions "
                f"{self.max_seq}")
        need = max(p + m for p, m in zip(plens, mnt))
        total = _pow2_bucket(need, self.max_seq)
        buf = np.zeros((self.batch_size, total), np.int32)
        plen_vec = np.ones(self.batch_size, np.int32)  # pad rows: plen 1
        for i, p in enumerate(prompts):
            row = np.asarray(p, np.int32)[:total]
            buf[i, :len(row)] = row
            plen_vec[i] = len(row)
        out = run_generate_scan(self.ffd, buf, plen_vec, temperature, seed)
        self.generations_run += 1
        results = []
        for i in range(n):
            end = min(plens[i] + mnt[i], total)
            row = out[i, :end]
            if self.eos_id >= 0:
                hits = np.flatnonzero(row[plens[i]:] == self.eos_id)
                if hits.size:
                    row = row[:plens[i] + hits[0] + 1]
            results.append(row.tolist())
        return results


class _PendingGen:
    __slots__ = ("prompt", "max_new_tokens", "temperature", "event",
                 "result", "error", "t_submit")

    def __init__(self, prompt, max_new_tokens, temperature):
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.event = threading.Event()
        self.result: Optional[List[int]] = None
        self.error: Optional[Exception] = None
        self.t_submit = time.monotonic()

    def wait(self, timeout: Optional[float] = None) -> List[int]:
        if not self.event.wait(timeout):
            raise TimeoutError("generation request timed out")
        if self.error is not None:
            raise self.error
        return self.result


class GenerationBatcher:
    """Coalesce concurrent generate requests into batched scans.

    Sampling (temperature > 0) draws from a per-batch PRNG key advanced
    by an internal counter, so repeated requests get distinct samples.
    Per-request seeds are deliberately not exposed: one scan program
    shares a single key across its batch, so a request-level seed could
    not be honored once coalesced."""

    def __init__(self, engine: GenerationEngine,
                 flush_timeout_s: float = 0.01,
                 latency_window: int = 1024, registry=None):
        self.engine = engine
        self.flush_timeout_s = flush_timeout_s
        # obs.metrics registry: counters/latencies fold in as
        # serving/generate_* so they drain to run_telemetry.jsonl
        # (the /v2/stats JSON shape is unchanged)
        self.registry = registry
        self._queue: "queue.Queue[_PendingGen]" = queue.Queue()
        self._stop = threading.Event()
        self._latencies = deque(maxlen=latency_window)
        self._lat_lock = threading.Lock()
        self._carry: Optional[_PendingGen] = None
        self._carry_lock = threading.Lock()  # close() vs worker
        self._seed = 0  # per-batch: repeated sampled requests differ
        self.batches_run = 0
        self.requests_done = 0
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    # -- client API -----------------------------------------------------
    def generate(self, prompt: Sequence[int], max_new_tokens: int = 16,
                 temperature: float = 0.0,
                 timeout: Optional[float] = 60.0) -> List[int]:
        return self.generate_async(
            prompt, max_new_tokens, temperature).wait(timeout)

    def generate_async(self, prompt, max_new_tokens: int = 16,
                       temperature: float = 0.0) -> _PendingGen:
        if self._stop.is_set():
            raise RuntimeError("GenerationBatcher is closed")
        # validate HERE so a bad request fails alone instead of
        # poisoning every request coalesced into its batch
        p = _PendingGen(prompt, max_new_tokens, temperature)
        if not 1 <= len(p.prompt) < self.engine.max_seq:
            raise ValueError(
                f"prompt length {len(p.prompt)} outside [1, "
                f"{self.engine.max_seq})")
        if p.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self._queue.put(p)
        if self._stop.is_set():  # close() raced the put
            p.error = RuntimeError("GenerationBatcher is closed")
            p.event.set()
        return p

    @property
    def worker_alive(self) -> bool:
        """False once the worker thread has died (crash or close) —
        /v2/health reports "degraded" then, because every request
        submitted to a dead worker can only time out."""
        return self._worker.is_alive()

    def latency_stats(self) -> Dict[str, float]:
        from .batcher import latency_percentiles

        return latency_percentiles(self._latencies, self._lat_lock)

    def close(self):
        self._stop.set()
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and self._worker.is_alive():
            self._drain()
            self._worker.join(timeout=0.2)
        self._drain()

    def _drain(self):
        with self._carry_lock:
            p, self._carry = self._carry, None
        if p is not None:
            p.error = RuntimeError("GenerationBatcher closed")
            p.event.set()
        while True:
            try:
                p = self._queue.get_nowait()
            except queue.Empty:
                return
            p.error = RuntimeError("GenerationBatcher closed")
            p.event.set()

    # -- worker ---------------------------------------------------------
    def _loop(self):
        while not self._stop.is_set():
            with self._carry_lock:
                first, self._carry = self._carry, None
            if first is None:
                try:
                    first = self._queue.get(timeout=0.05)
                except queue.Empty:
                    continue
            batch: List[_PendingGen] = [first]
            deadline = time.monotonic() + self.flush_timeout_s
            while len(batch) < self.engine.batch_size:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt.temperature != first.temperature:
                    # sampling temperature is baked into the compiled
                    # program: incompatible requests head the next batch
                    with self._carry_lock:
                        if self._stop.is_set():
                            nxt.error = RuntimeError(
                                "GenerationBatcher closed")
                            nxt.event.set()
                        else:
                            self._carry = nxt
                    break
                batch.append(nxt)
            self._run(batch)

    def _run(self, batch: List[_PendingGen]):
        try:
            self._seed += 1
            outs = self.engine.generate(
                [p.prompt for p in batch],
                [p.max_new_tokens for p in batch],
                temperature=batch[0].temperature,
                seed=self._seed,
            )
            now = time.monotonic()
            self.batches_run += 1
            for p, toks in zip(batch, outs):
                p.result = toks
                with self._lat_lock:
                    self._latencies.append(now - p.t_submit)
                self.requests_done += 1
                p.event.set()
            if self.registry is not None:
                reg = self.registry
                reg.counter("serving/generate_batches_run").inc()
                reg.counter("serving/generate_requests_done").inc(
                    len(batch))
                for p in batch:
                    reg.histogram(
                        "serving/generate_latency_ms").observe(
                        (now - p.t_submit) * 1e3)
        except Exception as e:
            for p in batch:
                p.error = e
                p.event.set()
