"""Disaggregated prefill/decode serving fleet (DistServe
arXiv:2401.09670, Splitwise arXiv:2311.18677).

Prefill is compute-bound (one big batched matmul over the prompt);
decode is memory-bandwidth-bound (one token per step against a growing
KV cache).  Colocating them makes each request's prefill stall every
other request's decode step.  The disaggregated fleet splits the
replica classes instead:

    client ──> admission queue ──> dispatcher
                                     │  cost: migrate vs re-prefill
                          ┌──────────┴──────────┐
                    [prefill replica]      [decode replica]
                     prompt pass             client decodes
                     (max_new=1)                  ▲
                          │   KV blocks           │ requeue as a
                          └──── KVMigrator ───────┘ prefix-cache hit

A MIGRATED request is a remote prefix-cache population: the prefill
replica runs the prompt once (its pool indexes every block-aligned
boundary), the finished blocks stream through a KVTransferFabric
(serving/kv_transfer.py), the decode replica adopts them as shared
cached blocks, and the request re-enters the admission queue where
cache-affine dispatch routes it to the adopter — its prefill becomes a
block-table metadata hit.  The decode replica would have written
BIT-IDENTICAL bytes for the same prefix (the KV content is a pure
function of the token prefix and the weights), so completions are
token-identical to the colocated fleet, and EVERY failure mode — torn
stream, dead fabric, died replica — degrades to a plain requeue that
re-prefills, never to wrong tokens.

A request the cost model routes the other way (sub-page prompt:
nothing block-aligned to ship; slow fabric: streaming costs more than
recomputing) dispatches straight to the decode class and re-prefills
there.  Both decisions are recorded per request and counted
(serving/disagg_migrate_decisions / disagg_reprefill_decisions).
docs/SERVING.md "Disaggregated fleet".
"""
from __future__ import annotations

import math
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..logger import resilience_logger
from .front import FrontRequest, ServingFront
from .kv_transfer import InProcessFabric, KVMigrator, KVTransferFabric
from .replica import ServingReplica

#: decode-step seconds assumed before the first EWMA sample lands —
#: only the migrate/re-prefill RATIO matters, so any positive value
#: keeps the decision well-defined on a cold fleet
_DEFAULT_STEP_S = 5e-3


def parse_serving_roles(spec: str,
                        num_replicas: Optional[int] = None
                        ) -> Optional[List[str]]:
    """--serving-roles "prefill=1,decode=2" -> per-replica role list.

    Empty/None means a colocated fleet (None: every replica mixed).
    Counts must sum to `num_replicas` when given, and at least one
    replica must be decode-capable (decode or mixed) — a prefill-only
    fleet could admit requests but never finish one."""
    spec = (spec or "").strip()
    if not spec:
        return None
    roles: List[str] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, count_s = part.partition("=")
        name = name.strip()
        if sep:
            try:
                count = int(count_s)
            except ValueError:
                raise ValueError(
                    f"--serving-roles: bad count {count_s!r} in "
                    f"{part!r}") from None
        else:
            count = 1
        if name not in ("prefill", "decode", "mixed"):
            raise ValueError(
                f"--serving-roles: unknown role {name!r} (pick from "
                "['prefill', 'decode', 'mixed'])")
        if count < 0:
            raise ValueError(
                f"--serving-roles: count for {name} must be >= 0, "
                f"got {count}")
        roles.extend([name] * count)
    if not roles:
        raise ValueError(f"--serving-roles: empty spec {spec!r}")
    if all(r == "prefill" for r in roles):
        raise ValueError(
            "--serving-roles: fleet needs at least one decode-capable "
            "replica (decode or mixed)")
    if num_replicas is not None and len(roles) != num_replicas:
        raise ValueError(
            f"--serving-roles names {len(roles)} replica(s) but the "
            f"fleet has {num_replicas}")
    return roles


class MigrationCostModel:
    """Migrate vs re-prefill, priced with the topology model's
    interconnect terms (sim/machine_model.py TpuPodModel defaults):

      migrate_s    = hop_latency + block_bytes * new_blocks / hop_bw
                     + ceil(tail_tokens / C) * step_s
      reprefill_s  = ceil(prompt_len / C) * step_s

    where C is the chunked-prefill width (1 without chunking), step_s
    the DECODE replica's measured per-dispatch EWMA, tail_tokens the
    sub-page remainder the decode replica must still prefill after
    adoption, and the hop terms come from the fabric class: an
    in-process handoff prices as one ICI hop, a blob-store hop as DCN.
    Migrate wins iff new_blocks > 0 and
    migrate_s <= cost_cap * reprefill_s (--migration-cost-cap)."""

    def __init__(self, cost_cap: float = 1.0, fabric_kind: str = "inproc",
                 machine=None):
        if cost_cap <= 0:
            raise ValueError(
                f"migration cost cap must be > 0, got {cost_cap}")
        self.cost_cap = float(cost_cap)
        if machine is None:
            from ..sim.machine_model import TpuPodModel

            machine = TpuPodModel()
        # ICI for a same-host handoff, DCN for a store-tier hop
        if fabric_kind == "blob":
            self.hop_bw = float(machine.dcn_bw)
            self.hop_lat = float(machine.dcn_lat)
        else:
            self.hop_bw = float(machine.ici_bw)
            self.hop_lat = float(machine.ici_lat)

    def decide(self, *, prompt_len: int, new_blocks: int,
               page_size: int, block_bytes: int, chunk: int,
               step_s: float) -> Dict:
        """One routing decision; returns the record stored on the
        request ({"decision", "migrate_s", "reprefill_s", ...})."""
        C = max(1, int(chunk))
        step = step_s if step_s > 0 else _DEFAULT_STEP_S
        reprefill_s = math.ceil(prompt_len / C) * step
        tail = prompt_len - (prompt_len // page_size) * page_size
        migrate_s = (self.hop_lat
                     + (block_bytes * new_blocks) / self.hop_bw
                     + math.ceil(tail / C) * step)
        migrate = (new_blocks > 0
                   and migrate_s <= self.cost_cap * reprefill_s)
        return {
            "decision": "migrate" if migrate else "reprefill",
            "new_blocks": int(new_blocks),
            "migrate_s": round(migrate_s, 6),
            "reprefill_s": round(reprefill_s, 6),
        }

    def decide_handoff(self, *, written: int, page_size: int,
                       block_bytes: int, chunk: int,
                       step_s: float) -> Dict:
        """Mid-decode handoff pricing (serving/handoff.py): ship every
        written block — prompt AND generated, including the partial
        tail page — vs replaying the whole written prefix as chunked
        prefill on the destination.  Unlike decide() there is no tail-
        replay term on the migrate side (the verified tail block rides
        the resume record into a private block), but the replay side
        grows with the GENERATED length: the longer a sequence has
        decoded, the more a handoff is worth."""
        C = max(1, int(chunk))
        step = step_s if step_s > 0 else _DEFAULT_STEP_S
        n_blocks = -(-written // page_size) if page_size > 0 else 0
        replay_s = math.ceil(written / C) * step
        handoff_s = (self.hop_lat
                     + (block_bytes * n_blocks) / self.hop_bw
                     + step)  # one adoption pass on the destination
        handoff = (n_blocks > 0
                   and handoff_s <= self.cost_cap * replay_s)
        return {
            "decision": "handoff" if handoff else "replay",
            "blocks": int(n_blocks),
            "handoff_s": round(handoff_s, 6),
            "replay_s": round(replay_s, 6),
        }


class DisaggServingFront(ServingFront):
    """ServingFront whose dispatcher costs every request's handoff.

    The cache-affine pick (decode-capable replicas only — the base
    front's role filter) stays the serving target; _divert_plan then
    decides, under the front lock, whether a prefill-class pass + KV
    migration beats re-prefilling on that target.  A diverted request
    runs max_new=1 on the least-loaded prefill replica, its finished
    block-aligned prefix streams through the migrator into the
    target's pool, and the request requeues at the HEAD of the
    admission queue — cache-affine dispatch then routes it to the
    adopter and its prompt admits as a prefix-cache hit.  Failures at
    ANY stage requeue the same way without the migration, so the
    request re-prefills: the fallback path IS the normal path.
    """

    def __init__(self, model_factory, num_replicas: int = 2, *,
                 fabric: Optional[KVTransferFabric] = None,
                 migration_cost_cap: float = 1.0,
                 machine=None,
                 **kw):
        self.fabric = fabric if fabric is not None else InProcessFabric()
        self.cost_model = MigrationCostModel(
            cost_cap=migration_cost_cap, fabric_kind=self.fabric.kind,
            machine=machine)
        self.migrator = KVMigrator(
            self.fabric, registry=kw.get("registry"),
            logger=kw.get("logger", resilience_logger),
            reqtrace=kw.get("reqtrace"))
        self.migrate_decisions = 0
        self.reprefill_decisions = 0
        self.migrations_ok = 0
        self.migrations_failed = 0
        try:
            super().__init__(model_factory, num_replicas, **kw)
        except BaseException:
            self.migrator.close()
            raise

    # -- routing ---------------------------------------------------------
    def _pick_prefill(self) -> Optional[ServingReplica]:
        """Least-loaded live prefill-class replica with slot headroom;
        None when the prefill class is absent, down, or full — the
        request then just re-prefills on the decode class."""
        best = None
        for r in self.replicas:
            sched = r.scheduler
            if r.role != "prefill" or r.state != "live" or sched is None:
                continue
            if r.outstanding >= sched.model.batch_slots:
                continue
            if best is None or r.outstanding < best.outstanding:
                best = r
        return best

    def _divert_plan(self, req: FrontRequest,
                     replica: ServingReplica) -> Optional[Callable]:
        # one migration attempt per request: a requeued request (post-
        # migration OR post-failure) always dispatches directly
        if req.migration is not None:
            return None
        # a resumed generation never takes the prefill-class detour:
        # its KV state (adopted blocks or the replay feed) belongs on
        # the decode class where it will finish
        if req.resume is not None:
            return None
        if self._terminating or self._closed:
            return None
        prefill_r = self._pick_prefill()
        dsched = replica.scheduler
        if prefill_r is None or dsched is None:
            return None
        psched = prefill_r.scheduler
        if psched is None:
            return None
        # both engines must expose the migration surface (fake models
        # without pools degrade to the colocated behavior)
        if (getattr(psched.model, "export_block", None) is None
                or getattr(dsched.model, "import_block", None) is None):
            return None
        page = dsched.pool.page_size
        plen = len(req.prompt)
        try:
            have = dsched.cached_prefix_tokens(req.prompt)
        except Exception:  # noqa: BLE001 — a probe must never stall
            have = 0       # dispatch
        # blocks the migration would actually ship: the block-aligned
        # prefix minus what the target already caches
        new_blocks = max(0, plen // page - have // page)
        step_ms = dsched.step_ms_ewma or psched.step_ms_ewma
        record = self.cost_model.decide(
            prompt_len=plen, new_blocks=new_blocks, page_size=page,
            block_bytes=int(getattr(dsched.model, "kv_block_bytes", 0)),
            chunk=int(getattr(dsched.model, "prefill_chunk", 0)),
            step_s=step_ms / 1e3)
        req.migration = record
        if req.trace is not None:
            # the priced decision lands on the open dispatch span:
            # trace_analyze and Perfetto show WHY this request migrated
            # (or re-prefilled) next to what it cost
            req.trace.annotate(
                "dispatch", decision=record["decision"],
                new_blocks=record["new_blocks"],
                migrate_s=record["migrate_s"],
                reprefill_s=record["reprefill_s"])
        if record["decision"] != "migrate":
            self.reprefill_decisions += 1
            if self.registry is not None:
                self.registry.counter(
                    "serving/disagg_reprefill_decisions").inc()
            return None  # dispatch normally: re-prefill on `replica`
        self.migrate_decisions += 1
        if self.registry is not None:
            self.registry.counter(
                "serving/disagg_migrate_decisions").inc()
        # book the prefill slot under _cv (we hold it) so concurrent
        # divert decisions see the load; released in _on_prefill_done
        prefill_r.outstanding += 1
        self._observe_depth(prefill_r)
        return lambda: self._begin_migration(req, prefill_r, replica)

    # -- migration pipeline ----------------------------------------------
    def _begin_migration(self, req: FrontRequest,
                         prefill_r: ServingReplica,
                         decode_r: ServingReplica) -> None:
        """Outside the front lock: run the prompt on the prefill
        replica.  max_new=1 — the pass exists to WRITE the prompt's KV
        and index every block boundary, not to generate."""
        if req.trace is not None:
            req.trace.end("dispatch")
            req.trace.begin("migration",
                            prefill_replica=prefill_r.replica_id,
                            decode_replica=decode_r.replica_id)
        try:
            prefill_r.submit(
                req.prompt, 1, 0.0, trace=req.trace,
                on_done=lambda h: self._on_prefill_done(
                    req, prefill_r, decode_r, h))
        except Exception:  # noqa: BLE001 — died between pick and submit
            with self._cv:
                prefill_r.outstanding -= 1
                self._observe_depth(prefill_r)
            self._settle_migration(req, False)

    def _on_prefill_done(self, req: FrontRequest,
                         prefill_r: ServingReplica,
                         decode_r: ServingReplica, handle) -> None:
        """Fires on the PREFILL replica's worker thread, between its
        steps — the only thread allowed to read the donated state, so
        the device->host block export happens here, synchronously,
        before any admission or eviction can reuse the blocks."""
        with self._cv:
            prefill_r.outstanding -= 1
            self._observe_depth(prefill_r)
            self._cv.notify_all()
        now = time.monotonic()
        self._note_class_done("prefill", now)
        psched = prefill_r.scheduler
        if handle.error is not None or psched is None:
            self._settle_migration(req, False)
            return
        try:
            blocks, pages = psched.pool.export_prefix(req.prompt)
            exporter = psched.model.export_block
            if not blocks or exporter is None:
                self._settle_migration(req, False)
                return
            arrays = [exporter(b) for b in blocks]
        except Exception:  # noqa: BLE001 — an export failure is a
            # re-prefill, never a dead prefill worker
            self._settle_migration(req, False)
            return
        dsched = decode_r.scheduler
        if dsched is None:  # target died while we prefilled
            self._settle_migration(req, False)
            return
        # the trace context rides the FFKV frame header (wire dict):
        # the adopting decode replica's kv_adopt span joins this tree
        # as a child of the migration span
        wire = (req.trace.wire(parent=req.trace.open_id("migration"))
                if req.trace is not None else None)
        self.migrator.migrate(
            prompt=req.prompt, pages=pages, blocks=arrays,
            page_size=psched.pool.page_size, target=dsched,
            wire=wire,
            on_done=lambda ok: self._settle_migration(req, ok))

    def _settle_migration(self, req: FrontRequest, ok: bool) -> None:
        """Exactly-once tail of every migration attempt, success or
        failure: record the outcome and requeue the request at the
        admission HEAD (it keeps its seniority; a migration never
        consumes a retry — the request did nothing wrong).  Cache-
        affine dispatch then finds the adopted prefix on the target,
        or re-prefills if nothing (or only a partial prefix) landed."""
        if ok:
            self.migrations_ok += 1
        else:
            self.migrations_failed += 1
        if isinstance(req.migration, dict):
            req.migration["ok"] = bool(ok)
        if req.trace is not None:
            req.trace.end("migration", ok=bool(ok))
            req.trace.begin("queue", requeued=True,
                            post_migration=True)
        with self._cv:
            if self._closed:
                self._fail(req, RuntimeError("ServingFront is closed"))
                return
            self._admission.appendleft(req)
            self._cv.notify_all()

    # -- stats / lifecycle -----------------------------------------------
    def stats(self) -> Dict:
        out = super().stats()
        out["mode"] = "disaggregated"
        out["disagg"] = {
            "migrate_decisions": self.migrate_decisions,
            "reprefill_decisions": self.reprefill_decisions,
            "migrations_ok": self.migrations_ok,
            "migrations_failed": self.migrations_failed,
            "cost_cap": self.cost_model.cost_cap,
            "kv_transfer": self.migrator.stats(),
        }
        return out

    def close(self, timeout_s: Optional[float] = None):
        super().close(timeout_s)
        # after the fleet: every pending migration's on_done has fired
        # (scheduler close settles handles; run_on_worker drops fire
        # on_dropped) or gets failed by the migrator's close drain
        self.migrator.close()


def build_front(ff_train, cfg=None, *, eos_id: int = -1, registry=None,
                fabric: Optional[KVTransferFabric] = None,
                **kw):
    """Config-driven front: a plain ServingFront when --serving-roles
    is empty, a DisaggServingFront (roles + costed migration) when
    set.  The roles spec also sizes the fleet when --serving-replicas
    disagrees (the spec is the more explicit statement)."""
    cfg = cfg if cfg is not None else ff_train.config
    roles = parse_serving_roles(getattr(cfg, "serving_roles", ""))
    if roles is None:
        return ServingFront.from_trained(
            ff_train, eos_id=eos_id, registry=registry, **kw)
    if fabric is None:
        from .kv_transfer import resolve_kv_transfer

        fabric = resolve_kv_transfer(
            getattr(cfg, "kv_transfer", "inproc") or "inproc",
            root=getattr(cfg, "strategy_store", None) or None)
    return DisaggServingFront.from_trained(
        ff_train, num_replicas=len(roles), eos_id=eos_id,
        registry=registry, roles=roles, fabric=fabric,
        migration_cost_cap=float(getattr(cfg, "migration_cost_cap",
                                         1.0) or 1.0),
        **kw)
