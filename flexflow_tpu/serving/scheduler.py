"""Continuous (iteration-level) batching: the Orca OSDI'22 scheduling
discipline on top of the paged KV-cache pool (serving/kv_pool.py).

`GenerationBatcher` (the static path) coalesces requests into ONE scan
program: every row rides to the batch's max length, a 5-token reply
pays for a 200-token neighbor, and a request arriving one step after
dispatch waits out the whole scan.  The continuous scheduler instead
keeps a persistent decode loop stepping every in-flight sequence by
one token per iteration; at EVERY step boundary it retires finished
sequences (eos / max_new_tokens) and admits queued prompts into the
freed slots — prefill is interleaved with decode (an admitted prompt
feeds one token per step at its own position), so the device never
waits for stragglers and short replies exit the moment they finish.

Allocation rides the paged pool: sequences reserve worst-case blocks
at admission (a full pool QUEUES the request — never a crash), extend
block-by-block as they grow, and free on retirement, so resident KV
HBM is sum-of-live-lengths instead of slots x max_seq.

The pool is also a PREFIX CACHE (kv_pool.py): admissions map the
longest indexed block-aligned token prefix of their prompt straight
onto shared physical blocks (skipping prefill for those tokens, with
copy-on-write isolating a full-prompt hit's tail block), and a second
compiled [slots, C] program chunk-prefills the uncached remainder C
tokens per dispatch — docs/SERVING.md "Prefix cache & chunked
prefill".  Greedy output is token-identical with sharing and chunking
on or off: shared bytes were written by the same programs at the same
positions, and the chunk program scans the seq-1 graph so every op
keeps the decode step's shapes.

Shape discipline (the TPU-native part): one compiled [slots, 1] step
program serves the engine's whole lifetime — admissions, retirements
and per-row positions are DATA (block tables + seq_lens), never
shapes, so steady state has zero recompiles.  Sampling is host-side
per row, which also lifts the static batcher's same-temperature
coalescing restriction: a continuous batch freely mixes temperatures.

SLO telemetry (obs.metrics): TTFT and per-token latency histograms,
queue depth, KV-pool occupancy/fragmentation — drained to
run_telemetry.jsonl and surfaced in /v2/stats (docs/SERVING.md).
"""
from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from .kv_pool import KVPool


class PagedKVDecodeModel:
    """Device half of the continuous engine: the paged decode twin of
    a trained GPT plus its compiled step programs.

    step(tokens[b], seq_lens[b], block_tables[b, max_blocks]) runs one
    decode step for every slot at its OWN position and returns host
    logits [b, vocab].  The block tables and seq_lens are host-owned
    scheduler data written into the op-state pytree each step.

    prefill_chunk = C > 1 additionally compiles the [b, C]
    chunked-prefill program (decoding.build_paged_prefill_step): one
    dispatch fills C prompt tokens per row at its own positions, so a
    P-token prompt costs ~P/C steps.  Internally it scans the SAME
    seq-1 graph, so the K/V bytes it writes are bit-identical to
    one-token prefill — chunked greedy output stays token-identical to
    the unchunked oracle.

    copy_block(src, dst) is the prefix cache's copy-on-write primitive
    (one physical block cloned across every layer's pool, compiled
    once); prefix_cache=False lets the scheduler skip sharing without
    rebuilding the twin.

    paged_kernel picks the attention READ formulation (docs/SERVING.md
    "Fused paged attention"): "gather" (default) materializes the
    dense [slots, decode_max_seq] K/V view — the bit-identity oracle;
    "pallas" streams each row's blocks in place through the fused
    kernel (ops/pallas/paged_attention.py), so per-step HBM reads
    scale with live tokens.  Validated + logged at build time
    (engine.resolve_paged_formulation)."""

    def __init__(self, ff_train, batch_slots: int = 8,
                 page_size: int = 16, num_blocks: Optional[int] = None,
                 devices=None, prefill_chunk: int = 0,
                 prefix_cache: bool = True,
                 paged_kernel: str = "gather", tp: int = 1,
                 spec_decode: str = "off", spec_k: int = 4,
                 draft_model=None):
        from ..config import (ConfigError, resolve_serving_tp,
                              resolve_spec_decode)
        from ..decoding import (_gpt_dims, build_paged_copy_block,
                                build_paged_decode_step,
                                build_paged_prefill_step,
                                build_paged_verify_step,
                                make_gpt_decoder)
        from .engine import resolve_paged_formulation

        self.paged_kernel = resolve_paged_formulation(paged_kernel)
        self.spec_decode = resolve_spec_decode(spec_decode, spec_k)
        self.spec_k = int(spec_k)
        dims = _gpt_dims(ff_train)
        # tensor-parallel replica degree (docs/SERVING.md
        # "Tensor-parallel replicas"): the decode twin compiles over a
        # tp-chip {"data": 1, "model": tp} mesh, heads + KV pools
        # sharded — validated against head count / visible devices
        # HERE so a bad degree is a ConfigError at build, never a
        # mid-compile shape error
        self.tp = resolve_serving_tp(
            tp, num_heads=dims["num_heads"],
            visible_devices=(len(devices) if devices is not None
                             else None))
        max_seq = dims["max_seq"]
        if page_size < 1 or max_seq % page_size:
            raise ValueError(
                f"page_size {page_size} must divide the model's "
                f"max positions {max_seq}")
        max_blocks = max_seq // page_size
        if num_blocks is None:
            # default: half of the dense footprint (+ scratch) — the
            # HBM the pool actually saves; callers needing guaranteed
            # all-slots-at-max-length admission pass the full
            # 1 + batch_slots * max_blocks
            num_blocks = 1 + max(max_blocks,
                                 (batch_slots * max_blocks + 1) // 2)
        self.ffd = make_gpt_decoder(
            ff_train, batch_size=batch_slots, devices=devices,
            kv_page_size=page_size, kv_num_blocks=num_blocks,
            kv_kernel=self.paged_kernel, tp=self.tp,
        )
        self.batch_slots = batch_slots
        self.page_size = page_size
        self.num_blocks = num_blocks
        self.max_blocks_per_seq = max_blocks
        self.max_seq = max_seq
        self.vocab = dims["vocab_size"]
        self.prefill_chunk = max(0, int(prefill_chunk))
        if self.prefill_chunk == 1:
            self.prefill_chunk = 0  # a 1-token chunk IS the decode step
        self.prefix_cache = bool(prefix_cache)
        self._step_fn = build_paged_decode_step(self.ffd)
        self._prefill_fn = (
            build_paged_prefill_step(self.ffd, self.prefill_chunk)
            if self.prefill_chunk else None)
        self._copy_fn = build_paged_copy_block(self.ffd)
        # speculative verify twin (docs/SERVING.md "Speculative
        # decoding"): ONE [slots, spec_k+1] program scores a pending
        # token plus up to spec_k drafts per row — per-position logits
        # bit-identical to seq-1 stepping, so greedy acceptance keeps
        # output token-identical to the plain engine.  counts is data:
        # adaptive-k rounds reuse the same compiled program.
        self.verify_chunk = self.spec_k + 1 if self.spec_decode != "off" \
            else 0
        self._verify_fn = (
            build_paged_verify_step(self.ffd, self.verify_chunk)
            if self.spec_decode != "off" else None)
        self.draft_model = draft_model
        if self.spec_decode == "draft":
            dm = draft_model
            if dm is None:
                raise ConfigError(
                    "--spec-decode draft needs a draft model — pass "
                    "draft_model= (or from_trained(..., draft_ff=)) "
                    "or use --spec-decode ngram")
            if int(getattr(dm, "vocab", -1)) != self.vocab:
                raise ConfigError(
                    f"draft model vocab {getattr(dm, 'vocab', None)} "
                    f"!= target vocab {self.vocab} — draft token ids "
                    f"are proposed verbatim, so the vocabularies must "
                    f"match")
            if int(getattr(dm, "max_seq", 0)) < max_seq:
                raise ConfigError(
                    f"draft model position table "
                    f"({getattr(dm, 'max_seq', 0)}) is shorter than "
                    f"the target's ({max_seq}) — the drafter must be "
                    f"able to reach every target position")
            if int(getattr(dm, "batch_slots", 0)) < batch_slots:
                raise ConfigError(
                    f"draft model has {getattr(dm, 'batch_slots', 0)} "
                    f"slots < the target's {batch_slots} — draft rows "
                    f"mirror engine slots 1:1")
        # the step fns DONATE their state argument; keep the twin's own
        # pristine pytree intact and thread a private copy (reset()
        # rebuilds from the pristine shapes after a failed step)
        import jax
        import jax.numpy as jnp

        self._state = jax.tree.map(jnp.copy, self.ffd._state)
        # bytes of ONE physical block summed across every layer's k/v
        # pool — the unit of the kernel-read telemetry (blocks read *
        # this = per-step KV bytes the fused kernel streams; the
        # dense-gather equivalent is table_width blocks per slot).
        # Shapes here are GLOBAL (GSPMD arrays report the logical
        # shape); each of a tp replica's chips holds 1/tp of the head
        # axis, so per-chip bytes are the global count / tp.
        self.kv_block_bytes = sum(
            int(np.prod(v.shape[1:])) * v.dtype.itemsize
            for entries in self._state.values()
            for k, v in entries.items()
            if k in ("k_cache", "v_cache"))
        self.kv_block_bytes_per_chip = self.kv_block_bytes // self.tp
        self.mesh_shape = {
            str(k): int(s)
            for k, s in zip(self.ffd.mesh.axis_names,
                            self.ffd.mesh.devices.shape)
        } if getattr(self.ffd, "mesh", None) is not None else {}

    def reset(self):
        """Fresh zero decode state (fault recovery: a step that died
        mid-execution may have invalidated the donated buffers).  The
        scheduler invalidates the pool's prefix index right after —
        cached blocks' bytes are zeroed with everything else.  Zeros
        are placed onto each leaf's compiled NamedSharding — on a tp
        replica mesh a bare jnp.zeros would land single-device and the
        donated step program would reject (or silently reshard) the
        mismatched state on the next dispatch."""
        import jax
        import jax.numpy as jnp

        self._state = jax.tree.map(
            lambda x: jax.device_put(
                jnp.zeros(x.shape, x.dtype), x.sharding),
            self.ffd._state)

    def step(self, tokens: np.ndarray, seq_lens: np.ndarray,
             block_tables: np.ndarray) -> np.ndarray:
        # per-token hot path: the block table / seq_lens override
        # happens INSIDE the jitted step and the state pytree is
        # donated — no host-side dict rebuild, no per-layer pool copy
        logits, self._state = self._step_fn(
            self.ffd._weights, self._state, tokens, seq_lens,
            block_tables,
        )
        return np.asarray(logits, np.float32)

    def prefill_step(self, tokens: np.ndarray, positions: np.ndarray,
                     block_tables: np.ndarray) -> None:
        """Chunked prefill: scatter tokens[b, C] at positions[b]..+C-1
        into the pool.  No logits come back — prefill ignores them."""
        self._state = self._prefill_fn(
            self.ffd._weights, self._state, tokens, positions,
            block_tables,
        )

    def verify_step(self, tokens: np.ndarray, seq_lens: np.ndarray,
                    counts: np.ndarray,
                    block_tables: np.ndarray) -> np.ndarray:
        """Speculative verify: feed tokens[b, :counts[b]] at
        seq_lens[b].. and return per-position logits
        [b, verify_chunk, vocab] — row i's logits[j] are bit-identical
        to what the decode step would have produced feeding
        tokens[i, j] at seq_lens[i]+j (docs/SERVING.md "Speculative
        decoding").  Built only when spec_decode != "off"."""
        logits, self._state = self._verify_fn(
            self.ffd._weights, self._state, tokens,
            seq_lens, counts, block_tables,
        )
        return np.asarray(logits, np.float32)

    def copy_block(self, src: int, dst: int) -> None:
        """Copy-on-write: clone physical block src -> dst in every
        layer's k/v pool (ordered with the step stream by jax's state
        dependency, so a following step reads the copied bytes)."""
        import jax.numpy as jnp

        self._state = self._copy_fn(
            self._state, jnp.int32(src), jnp.int32(dst))

    def export_block(self, block: int) -> Dict[str, np.ndarray]:
        """Device->host read of ONE physical block across every layer's
        k/v pool — the migration export path (serving/kv_transfer.py).
        Keyed "<op>/<k_cache|v_cache>" so import lands each page back
        in the matching layer.  Worker-thread only: the state pytree is
        donated to the step programs, so reads must sit between steps."""
        out: Dict[str, np.ndarray] = {}
        for name, entries in self._state.items():
            for k in ("k_cache", "v_cache"):
                if k in entries:
                    out[f"{name}/{k}"] = np.asarray(entries[k][block])
        return out

    def import_block(self, block: int,
                     arrays: Dict[str, np.ndarray]) -> None:
        """Host->device write of one migrated block into every layer's
        pool, sharding-preserving (a tp replica's head-sharded pools
        keep their NamedSharding — a bare at[].set result could land
        single-device).  Worker-thread only, like export_block."""
        import jax
        import jax.numpy as jnp

        state = {}
        for name, entries in self._state.items():
            e = dict(entries)
            for k in ("k_cache", "v_cache"):
                if k in e:
                    v = e[k]
                    page = jnp.asarray(arrays[f"{name}/{k}"], v.dtype)
                    e[k] = jax.device_put(v.at[block].set(page),
                                          v.sharding)
            state[name] = e
        self._state = state


class _PendingSeq:
    """Future-style handle for one continuous-mode request.  Besides
    the final token list it records the SLO timestamps the loadgen and
    telemetry consume: submit, first generated token (TTFT), done.

    `on_done` (set at submission, never after) fires exactly once when
    the request settles — success, fault, or drain — on whichever
    thread settled it.  The replicated front (serving/front.py) rides
    it to route completions/requeues without polling handles."""

    __slots__ = ("prompt", "max_new_tokens", "temperature", "seed",
                 "event", "result", "error", "t_submit", "t_first_token",
                 "t_done", "n_generated", "prefix_hit_tokens",
                 "spec_proposed", "spec_accepted", "on_done", "trace",
                 "resume", "resume_out", "_settle_lock", "_settled")

    def __init__(self, prompt, max_new_tokens, temperature, seed,
                 on_done=None, trace=None, resume=None):
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.seed = int(seed)
        # resume: a handoff.ResumeRecord continuing a mid-decode
        # generation (the re-fed tokens replay as prompt, the sampling
        # RNG restores mid-stream).  resume_out: stamped by the
        # scheduler when it settles this handle un-finished with
        # recoverable state (death, drain) so the front's requeue
        # resumes instead of regenerating from scratch.
        self.resume = resume
        self.resume_out = None
        self.event = threading.Event()
        self.result: Optional[List[int]] = None
        self.error: Optional[Exception] = None
        self.t_submit = time.monotonic()
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None
        self.n_generated = 0
        self.prefix_hit_tokens = 0  # prompt tokens served from cache
        self.spec_proposed = 0   # draft tokens verified for this request
        self.spec_accepted = 0   # ... of which the target agreed with
        self.on_done = on_done
        self.trace = trace  # TraceContext (obs/reqtrace.py) or None
        self._settle_lock = threading.Lock()
        self._settled = False

    def _settle(self) -> None:
        """Wake the waiter and fire the completion hook — exactly once,
        even when a drain races the submit path's late-enqueue check
        (both may settle the same request; the second is a no-op)."""
        with self._settle_lock:
            if self._settled:
                return
            self._settled = True
        self.event.set()
        if self.on_done is not None:
            try:
                self.on_done(self)
            except Exception:  # noqa: BLE001 — a hook must never kill
                pass           # the decode loop or a drain

    def wait(self, timeout: Optional[float] = None) -> List[int]:
        if not self.event.wait(timeout):
            raise TimeoutError("generation request timed out")
        if self.error is not None:
            raise self.error
        return self.result


class _Live:
    """Slot-resident decoding state for one admitted sequence.
    `start` > 0 means a prefix-cache hit: positions [0, start) are
    already in shared KV blocks and never prefill.

    `feed` is the token stream positions consume before sampling
    begins: the prompt, or — for a resumed mid-decode handoff — the
    prompt plus every previously generated token (replayed as prompt,
    so the KV state rebuilds bit-identically).  `generated` is then
    pre-seeded with those tokens: the completion and the generation
    budget count them exactly as the uninterrupted run would."""

    __slots__ = ("req", "seq_id", "pos", "next_token", "generated",
                 "max_new", "rng", "feed", "tspan")

    def __init__(self, req: _PendingSeq, seq_id: int, max_new: int,
                 start: int = 0, feed=None, generated=None,
                 rng_state=None):
        self.req = req
        self.seq_id = seq_id
        self.feed = req.prompt if feed is None else list(feed)
        self.pos = start                  # tokens already in the cache
        self.next_token = self.feed[start]  # token fed at position pos
        self.generated: List[int] = list(generated or [])
        self.max_new = max_new            # clamped to the position table
        self.rng = (np.random.RandomState(req.seed)
                    if req.temperature > 0.0 else None)
        if self.rng is not None and rng_state is not None:
            # mid-stream resume: continue the sampled sequence exactly
            # where the pause captured it — the replayed tokens make
            # no draws, so the state is already post-draw-correct
            self.rng.set_state(rng_state)
        self.tspan: Optional["_LiveTrace"] = None  # request-trace state


class _LiveTrace:
    """Per-slot request-trace bookkeeping (obs/reqtrace.py) for ONE
    traced live row.  The row owns exactly one open PHASE span at a
    time ("prefill" until its first generated token, then "decode");
    batched dispatches (prefill chunks, decode steps, verify rounds)
    each get ONE shared batch span per dispatch, and the per-request
    phase span REFERENCES those by span id instead of duplicating
    them — N rows riding one dispatch never write N copies of it."""

    __slots__ = ("ctx", "pid", "span", "chunks", "chunk_refs",
                 "steps", "spec_rounds", "batch_refs")

    MAX_REFS = 64  # cap the per-request batch-span reference list

    def __init__(self, ctx, pid: int, hit_tokens: int, plen: int):
        self.ctx = ctx
        self.pid = pid
        self.span = ctx.begin("prefill", pid=pid,
                              prefix_hit_tokens=hit_tokens,
                              prompt_len=plen)
        self.chunks = 0        # chunked-prefill dispatches ridden
        self.chunk_refs: List[int] = []  # their batch span ids
        self.steps = 0         # decode/verify dispatches ridden
        self.spec_rounds = 0   # of which were speculative verifies
        self.batch_refs: List[int] = []  # decode-phase batch span ids

    def ref_chunk(self, batch_span) -> None:
        self.chunks += 1
        if batch_span is not None and len(self.chunk_refs) < self.MAX_REFS:
            self.chunk_refs.append(batch_span.span_id)

    def ref_step(self, batch_span, spec: bool = False) -> None:
        self.steps += 1
        if spec:
            self.spec_rounds += 1
        if batch_span is not None and len(self.batch_refs) < self.MAX_REFS:
            self.batch_refs.append(batch_span.span_id)

    def to_decode(self) -> None:
        """First generated token: close the prefill phase, open decode."""
        self.span.end(chunks=self.chunks, batch_spans=self.chunk_refs)
        self.span = self.ctx.begin("decode", pid=self.pid)

    def finish(self, req: _PendingSeq) -> None:
        self.span.end(steps=self.steps, n_generated=req.n_generated,
                      spec_rounds=self.spec_rounds,
                      spec_proposed=req.spec_proposed,
                      spec_accepted=req.spec_accepted,
                      batch_spans=self.batch_refs)


class ContinuousScheduler:
    """Persistent decode loop with iteration-level admission/retirement.

    API-compatible with GenerationBatcher (generate / generate_async /
    latency_stats / close / batches_run / requests_done), so serve_http
    and the loadgen drive either engine unchanged.  `batches_run`
    counts decode steps here — the unit of batching is the step."""

    def __init__(self, model, pool: Optional[KVPool] = None,
                 eos_id: int = -1, registry=None, seed: int = 0,
                 latency_window: int = 1024,
                 close_timeout_s: float = 60.0,
                 on_death=None, check_invariants: bool = False,
                 reqtrace=None, trace_pid: int = 0):
        self.model = model
        # per-request distributed tracing (obs/reqtrace.py): requests
        # arrive carrying a TraceContext minted at the front; this
        # engine contributes phase + batch spans on its own Perfetto
        # track (`trace_pid` = replica id).  None keeps every hot-path
        # check a single `is not None` that allocates nothing.
        self._reqtrace = (reqtrace if reqtrace is not None
                          and getattr(reqtrace, "enabled", True)
                          else None)
        self._trace_pid = int(trace_pid)
        self.pool = pool or KVPool(
            model.num_blocks, model.page_size, model.max_blocks_per_seq,
            prefix_cache=bool(getattr(model, "prefix_cache", True)))
        # chunked prefill: C prompt tokens per dispatch through the
        # model's second compiled program (0 = one-token prefill, the
        # PR 6 path); COW needs the model's device block copy
        self._chunk = int(getattr(model, "prefill_chunk", 0) or 0)
        if self._chunk and getattr(model, "prefill_step", None) is None:
            self._chunk = 0
        self._can_cow = getattr(model, "copy_block", None) is not None
        # fused-kernel read telemetry (docs/SERVING.md "Fused paged
        # attention"): under paged_kernel="pallas" every dispatch
        # streams only each live row's own blocks, so we track the
        # physical blocks actually read vs what the dense gather
        # formulation would have materialized for the same dispatches
        # (scratch-block fetches excluded — they are one elided page).
        self._paged_kernel = str(getattr(model, "paged_kernel",
                                         "gather"))
        self._kv_block_bytes = int(getattr(model, "kv_block_bytes", 0))
        self.kernel_blocks_read = 0   # physical blocks streamed
        self.kernel_dense_blocks = 0  # gather-equivalent block reads
        # bench/debug: run the pool's full invariant sweep after every
        # scheduler step (the serving_prefix leg's acceptance bar)
        self._check_invariants = bool(check_invariants)
        self._evictions_seen = 0  # delta base for the obs counter
        self.prefill_steps = 0    # chunked-prefill dispatches
        # speculative decoding (serving/speculative.py,
        # docs/SERVING.md "Speculative decoding"): the model carries
        # the mode, the verify program and (for "draft") the draft
        # twin; the scheduler owns the proposer, the adaptive-k
        # controller and the accept/rollback loop.  A model without
        # the verify surface (test fakes) simply runs with spec off.
        spec = str(getattr(model, "spec_decode", "off") or "off")
        self._spec_k = int(getattr(model, "spec_k", 0) or 0)
        self._proposer = None
        if (spec != "off" and self._spec_k >= 1
                and getattr(model, "verify_step", None) is not None):
            from .speculative import AdaptiveK, build_proposer

            self._proposer = build_proposer(
                spec, getattr(model, "draft_model", None))
            self._adaptive = AdaptiveK(self._spec_k)
        self._spec = spec if self._proposer is not None else "off"
        self._spec_broken = False  # verify/proposer fault: plain decode
        self._spec_t0: Optional[float] = None
        self.spec_rounds = 0        # verify dispatches run
        self.spec_fallback_rounds = 0  # spec on, but a round had no
        self.spec_proposed = 0         # proposals -> plain decode step
        self.spec_accepted = 0
        self.spec_verify_faults = 0
        self.eos_id = int(eos_id)
        self.registry = registry
        # tensor-parallel geometry gauges (serving/tp_* group,
        # docs/OBSERVABILITY.md): static per-engine facts, set once
        if registry is not None:
            tp = int(getattr(model, "tp", 1))
            registry.gauge("serving/tp_degree").set(tp)
            registry.gauge("serving/tp_chips").set(
                max(1, int(np.prod(list(
                    (getattr(model, "mesh_shape", None) or {"": tp})
                    .values())))))
            registry.gauge("serving/tp_kv_block_bytes_per_chip").set(
                int(getattr(model, "kv_block_bytes_per_chip",
                            getattr(model, "kv_block_bytes", 0))))
            registry.gauge("serving/tp_kv_pool_bytes_per_chip").set(
                int(getattr(model, "kv_block_bytes_per_chip",
                            getattr(model, "kv_block_bytes", 0)))
                * int(getattr(model, "num_blocks", 0)))
        self._queue: "queue.Queue[_PendingSeq]" = queue.Queue()
        self._waiting: deque = deque()  # worker-local FIFO admit order
        # worker-marshalled service calls (KV block import, export):
        # the state pytree is donated to the step programs, so ONLY the
        # worker may touch it — run_on_worker() queues a callable the
        # loop executes between steps
        self._service: "queue.Queue" = queue.Queue()
        # measured per-dispatch wall time (EWMA over decode + prefill
        # dispatches): the disagg dispatcher's re-prefill cost unit
        self.step_ms_ewma = 0.0
        self._stop = threading.Event()
        self._latencies = deque(maxlen=latency_window)
        self._ttfts = deque(maxlen=latency_window)
        self._lat_lock = threading.Lock()
        self._slots: List[Optional[_Live]] = [None] * model.batch_slots
        # persistent step buffers, updated INCREMENTALLY: block-table
        # rows change only on admit/retire and when a row crosses a
        # page boundary (every page-th token), not per step — the
        # decode loop's python cost stays O(live rows), not
        # O(rows x table width)
        self._tokens = np.zeros(model.batch_slots, np.int32)
        self._slens = np.zeros(model.batch_slots, np.int32)
        self._btab = np.zeros(
            (model.batch_slots, self.pool.max_blocks_per_seq), np.int32)
        self._next_seq_id = 0
        self._seed = itertools.count(int(seed) + 1)
        self._close_timeout_s = float(close_timeout_s)
        # fired (with the exception) when the worker dies on a fault —
        # NOT on a clean close.  The replica supervisor's death signal.
        self._on_death = on_death
        # graceful drain (autoscaler scale-down / SIGTERM grace): set by
        # drain(); new submissions are refused, everything already
        # accepted runs to completion, then the worker exits cleanly
        # and fires _on_drained exactly once
        self._draining = False
        self._on_drained = None
        self.batches_run = 0       # decode steps executed
        self.requests_done = 0
        self.tokens_generated = 0
        self.step_failures = 0
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    @classmethod
    def from_trained(cls, ff_train, batch_slots: int = 8,
                     page_size: int = 16,
                     num_blocks: Optional[int] = None, devices=None,
                     eos_id: int = -1, registry=None,
                     seed: int = 0, prefill_chunk: int = 0,
                     prefix_cache: bool = True,
                     paged_kernel: str = "gather",
                     check_invariants: bool = False,
                     tp: int = 1, spec_decode: str = "off",
                     spec_k: int = 4, draft_ff=None,
                     draft_num_blocks: Optional[int] = None,
                     ) -> "ContinuousScheduler":
        # the draft twin (--spec-decode draft) is its own single-chip
        # paged engine over the smaller trained GPT: same slot count
        # and page size as the target (draft rows mirror engine slots
        # 1:1), no prefix cache or chunking of its own — catch-up IS
        # its prefill
        draft_model = None
        if spec_decode == "draft" and draft_ff is not None:
            draft_model = PagedKVDecodeModel(
                draft_ff, batch_slots=batch_slots, page_size=page_size,
                num_blocks=draft_num_blocks, devices=devices,
                paged_kernel=paged_kernel)
        model = PagedKVDecodeModel(ff_train, batch_slots=batch_slots,
                                   page_size=page_size,
                                   num_blocks=num_blocks,
                                   devices=devices,
                                   prefill_chunk=prefill_chunk,
                                   prefix_cache=prefix_cache,
                                   paged_kernel=paged_kernel, tp=tp,
                                   spec_decode=spec_decode,
                                   spec_k=spec_k,
                                   draft_model=draft_model)
        return cls(model, eos_id=eos_id, registry=registry, seed=seed,
                   check_invariants=check_invariants)

    # -- client API -----------------------------------------------------
    def generate(self, prompt: Sequence[int], max_new_tokens: int = 16,
                 temperature: float = 0.0,
                 timeout: Optional[float] = 60.0) -> List[int]:
        return self.generate_async(
            prompt, max_new_tokens, temperature).wait(timeout)

    def generate_async(self, prompt, max_new_tokens: int = 16,
                       temperature: float = 0.0,
                       on_done=None, trace=None, seed=None,
                       resume=None) -> _PendingSeq:
        if self._stop.is_set():
            raise RuntimeError("ContinuousScheduler is closed")
        if self._draining:
            # the drain cutoff: everything accepted BEFORE drain() runs
            # to completion; nothing new boards a leaving engine
            raise RuntimeError("ContinuousScheduler is draining")
        # validate HERE so a bad request fails alone (the batcher
        # convention); continuous mode has no same-temperature
        # restriction — sampling is host-side per row.  on_done rides
        # the handle from birth, so a completion can never race the
        # caller attaching it.  `seed` pins the sampling RNG (the
        # front mints one per request so a resubmission on ANY replica
        # samples identically); None keeps the per-engine counter.
        # `resume` (handoff.ResumeRecord) continues a paused/recovered
        # mid-decode generation: its generated tokens replay as prompt.
        p = _PendingSeq(prompt, max_new_tokens, temperature,
                        next(self._seed) if seed is None else int(seed),
                        on_done=on_done, trace=trace, resume=resume)
        if not 1 <= len(p.prompt) < self.model.max_seq:
            raise ValueError(
                f"prompt length {len(p.prompt)} outside [1, "
                f"{self.model.max_seq})")
        if p.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if resume is not None and not 1 <= len(
                resume.replay_tokens()) < self.model.max_seq:
            raise ValueError(
                f"resume replay length {len(resume.replay_tokens())} "
                f"outside [1, {self.model.max_seq})")
        self._queue.put(p)
        if self._stop.is_set():  # close() raced the put
            p.error = RuntimeError("ContinuousScheduler is closed")
            p._settle()
        return p

    def run_on_worker(self, fn, on_dropped=None) -> None:
        """Queue `fn` for the decode worker to run between steps — the
        only thread allowed to touch the model's donated state (KV
        block import lands here).  `fn` owns its own error handling;
        an exception it lets escape is treated like a step fault
        (fatal_to_engine propagates, anything else fails in-flight).
        If the engine closes/drains/dies before `fn` runs, `on_dropped`
        fires with the terminal error instead — a caller is never left
        waiting on a callable that will not run."""
        if self._stop.is_set():
            raise RuntimeError("ContinuousScheduler is closed")
        self._service.put((fn, on_dropped))
        if self._stop.is_set():  # close() raced the put
            self._drop_services(RuntimeError(
                "ContinuousScheduler is closed"))

    def _drop_services(self, err: Exception) -> None:
        while True:
            try:
                fn, on_dropped = self._service.get_nowait()
            except queue.Empty:
                return
            if on_dropped is not None:
                try:
                    on_dropped(err)
                except Exception:  # noqa: BLE001 — drains never mask
                    pass

    def _run_services(self) -> None:
        while True:
            try:
                fn, on_dropped = self._service.get_nowait()
            except queue.Empty:
                return
            try:
                fn()
            except Exception as e:
                if getattr(e, "fatal_to_engine", False):
                    raise
                if on_dropped is not None:
                    try:
                        on_dropped(e)
                    except Exception:  # noqa: BLE001
                        pass

    @property
    def worker_alive(self) -> bool:
        return self._worker.is_alive()

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self, on_drained=None) -> None:
        """Stop ACCEPTING and run everything already accepted to
        completion (decode proceeds undisturbed — completions are
        token-identical to an engine that was never drained).  When the
        last live sequence retires and the arrival queue is empty, the
        worker exits cleanly and fires `on_drained` exactly once; the
        engine then refuses submissions like a closed one.

        Unlike close(), drain() never fails an in-flight request.  A
        wedged drain is still bounded by close(timeout_s=), which
        overrides it."""
        if self._stop.is_set() or self._draining:
            return
        self._on_drained = on_drained
        self._draining = True

    def request_handoff(self, *, remaining_over: int = 0,
                        max_sequences: int = 0,
                        export_kv: bool = True,
                        on_paused=None) -> None:
        """Pause live generations at the next step boundary and settle
        their handles with handoff.HandoffPaused — the resumable-
        migration entry point (docs/SERVING.md "Mid-decode handoff").
        Eligible rows have MORE than `remaining_over` tokens still to
        generate (a draining replica passes 0 to shed everything; a
        terminating front passes the count that still fits its
        deadline); `max_sequences` > 0 caps how many pause, largest
        remaining budget first (the rebalance trigger moves one whale
        at a time).  With `export_kv`, each paused row's written KV
        blocks — partial tail included — ride the settle so the front
        can stream them to a destination replica; the host resume
        record rides regardless, so every downstream fault still
        degrades to replay.  `on_paused(count)` fires on the worker
        after the sweep (0 if the engine died first).  Safe to call on
        a DRAINING engine: services still run between its final steps.
        """
        def service():
            rows = [(live.max_new - len(live.generated), i, live)
                    for i, live in enumerate(self._slots)
                    if live is not None]
            rows = [r for r in rows if r[0] > int(remaining_over)]
            rows.sort(key=lambda r: (-r[0], r[1]))
            if max_sequences and int(max_sequences) > 0:
                rows = rows[:int(max_sequences)]
            for _, i, live in rows:
                self._pause_slot(i, live, export_kv)
            if on_paused is not None:
                on_paused(len(rows))

        self.run_on_worker(
            service,
            on_dropped=((lambda e: on_paused(0))
                        if on_paused is not None else None))

    def latency_stats(self) -> Dict[str, float]:
        from .batcher import latency_percentiles

        return latency_percentiles(self._latencies, self._lat_lock)

    def ttft_stats(self) -> Dict[str, float]:
        from .batcher import latency_percentiles

        return latency_percentiles(self._ttfts, self._lat_lock)

    def cached_prefix_tokens(self, prompt) -> int:
        """Read-only probe: prompt tokens the prefix cache would serve
        right now.  Admission control discounts them — cached tokens
        cost zero prefill steps (serving/front.py)."""
        return self.pool.cached_prefix_tokens(
            [int(t) for t in prompt])

    def stats(self) -> Dict:
        live = [s for s in self._slots if s is not None]
        return {
            "mode": "continuous",
            "draining": self._draining,
            "steps": self.batches_run,
            "prefill_steps": self.prefill_steps,
            "prefill_chunk": self._chunk,
            "requests_done": self.requests_done,
            "tokens_generated": self.tokens_generated,
            "step_failures": self.step_failures,
            "step_ms_ewma": round(self.step_ms_ewma, 4),
            "queue_depth": self._queue.qsize() + len(self._waiting),
            "live_sequences": len(live),
            "kv_pool": {
                "page_size": self.pool.page_size,
                "usable_blocks": self.pool.usable_blocks,
                "used_blocks": self.pool.used_blocks,
                "reserved_blocks": self.pool.reserved_blocks,
                "peak_used_blocks": self.pool.peak_used,
                "occupancy": round(self.pool.occupancy(), 4),
                "fragmentation": round(self.pool.fragmentation(), 4),
            },
            "prefix_cache": self.pool.prefix_stats(),
            "speculative": {
                "mode": self._spec,
                "k_max": self._spec_k if self._spec != "off" else 0,
                "k_current": (self._adaptive.k
                              if self._proposer is not None else 0),
                "rounds": self.spec_rounds,
                "fallback_rounds": self.spec_fallback_rounds,
                "proposed": self.spec_proposed,
                "accepted": self.spec_accepted,
                "accept_rate": round(
                    self.spec_accepted / self.spec_proposed, 4)
                if self.spec_proposed else 0.0,
                "accepted_per_round": round(
                    self.spec_accepted / self.spec_rounds, 4)
                if self.spec_rounds else 0.0,
                "verify_faults": self.spec_verify_faults,
                "degraded": self._spec_broken,
                "proposer": (self._proposer.stats()
                             if self._proposer is not None else {}),
            },
            "tp": {
                "degree": int(getattr(self.model, "tp", 1)),
                "mesh_shape": dict(getattr(self.model, "mesh_shape",
                                           {}) or {}),
                "kv_block_bytes": self._kv_block_bytes,
                "kv_block_bytes_per_chip": int(getattr(
                    self.model, "kv_block_bytes_per_chip",
                    self._kv_block_bytes)),
                "kv_pool_bytes_per_chip": int(getattr(
                    self.model, "kv_block_bytes_per_chip",
                    self._kv_block_bytes))
                * int(getattr(self.model, "num_blocks", 0)),
            },
            "paged_kernel": {
                "formulation": self._paged_kernel,
                "blocks_read": self.kernel_blocks_read,
                "dense_blocks_equiv": self.kernel_dense_blocks,
                "bytes_read":
                    self.kernel_blocks_read * self._kv_block_bytes,
                "dense_bytes_avoided":
                    max(0, self.kernel_dense_blocks
                        - self.kernel_blocks_read)
                    * self._kv_block_bytes,
            },
            "ttft": self.ttft_stats(),
            "latency": self.latency_stats(),
        }

    def close(self, timeout_s: Optional[float] = None):
        """Stop the loop and drain: in-flight sequences fail with a
        closed error (their blocks are freed), queued requests fail
        without hanging out their timeout.  The worker owns _slots and
        _waiting, so the full drain runs EITHER on the worker's way out
        of _loop OR here once the worker is confirmed dead — never
        concurrently; the thread-safe arrival queue is always drained.

        The wait for the worker is BOUNDED (`timeout_s`, defaulting to
        the constructor's close_timeout_s): a worker wedged inside a
        hung device dispatch cannot hold shutdown hostage — the drain
        proceeds without it."""
        self._stop.set()
        if timeout_s is None:
            timeout_s = self._close_timeout_s
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline and self._worker.is_alive():
            self._worker.join(timeout=min(0.2, max(0.0, timeout_s)))
        err = RuntimeError("ContinuousScheduler closed")
        # Drain even if the worker outlived the deadline (a device step
        # wedged mid-dispatch): waiters must not sit out their full
        # wait() timeouts against a hung engine.  _drain is defensive
        # about double-retires, and a worker that later un-wedges finds
        # _stop set, treats its emptied slots as idle, and exits
        # through its own (now no-op) drain.
        self._drain(err)
        while True:  # late enqueues that raced the stop flag
            try:
                p = self._queue.get_nowait()
            except queue.Empty:
                break
            p.error = err
            p._settle()

    # -- worker ---------------------------------------------------------
    def _free_slot_buffers(self, slot: int):
        """Point a vacated slot's step buffers back at scratch."""
        self._btab[slot] = 0
        self._tokens[slot] = 0
        self._slens[slot] = 0

    def _resume_record_of(self, live: _Live):
        """Host-side resume record for a live row — built on the
        failure paths too: the tokens live on the host, so a dead
        device cannot tear them, and the front's requeue replays
        prompt+generated instead of regenerating from scratch."""
        from .handoff import ResumeRecord

        try:
            return ResumeRecord(
                live.req.prompt, live.generated, live.pos,
                live.req.seed, live.req.temperature,
                rng_state=(live.rng.get_state()
                           if live.rng is not None else None),
                page_size=self.pool.page_size)
        except Exception:  # noqa: BLE001 — recovery metadata must
            return None    # never mask the original failure

    def _pause_slot(self, slot: int, live: _Live,
                    export_kv: bool = True) -> None:
        """Worker-side pause: snapshot the row (and optionally its
        written KV blocks, partial tail included), retire it, and
        settle the handle with HandoffPaused.  Runs only between
        steps, so the exported bytes are a consistent prefix of the
        generation."""
        from .handoff import HandoffPaused

        req = live.req
        written = (req.prompt + live.generated)[:live.pos]
        rec = self._resume_record_of(live)
        pages = arrays = None
        exporter = getattr(self.model, "export_block", None)
        if export_kv and exporter is not None:
            try:
                blocks, pages = self.pool.export_live(
                    live.seq_id, written)
                arrays = [exporter(b) for b in blocks]
            except Exception as e:
                if getattr(e, "fatal_to_engine", False):
                    raise
                pages = arrays = None  # replay-only resume
        if self._proposer is not None:
            self._proposer.release(slot)
        # the written prefix keys the retired blocks into the prefix
        # cache: a re-admit on THIS replica is a hit too
        self.pool.retire(live.seq_id, tokens=written)
        self._slots[slot] = None
        self._free_slot_buffers(slot)
        if live.tspan is not None:
            live.tspan.span.end(paused=True)
            live.tspan = None
        if self.registry is not None:
            self.registry.counter("serving/handoff_paused").inc()
        req.error = HandoffPaused(rec, pages=pages, arrays=arrays,
                                  page_size=self.pool.page_size)
        req._settle()

    def _drain(self, err: Exception):
        """Fail every queued/waiting/live request (close or fault).
        Runs on the worker's way out of _loop AND from close() — which
        overlap only when close() gave up on a wedged worker, so
        retires tolerate the other drain having won the race."""
        for i, s in enumerate(self._slots):
            if s is not None:
                try:
                    self.pool.retire(s.seq_id)
                except KeyError:
                    pass  # the racing drain already freed it
                if s.generated:
                    # death recovery: the front's requeue resumes from
                    # this instead of regenerating from scratch
                    s.req.resume_out = self._resume_record_of(s)
                s.req.error = err
                s.req._settle()
                self._free_slot_buffers(i)
        self._slots = [None] * self.model.batch_slots
        while self._waiting:
            p = self._waiting.popleft()
            p.error = err
            p._settle()
        while True:
            try:
                p = self._queue.get_nowait()
            except queue.Empty:
                break
            p.error = err
            p._settle()
        self._drop_services(err)

    def _admit(self):
        """Pull arrivals, then admit FIFO into free slots while the
        pool can GUARANTEE completion.  Strict FIFO: a head-of-line
        request that doesn't fit blocks later (smaller) ones — no
        starvation, predictable SLO.

        Admission consults the prefix cache: the longest indexed
        block-aligned prefix of the prompt is mapped straight onto the
        shared physical blocks (those tokens never prefill).  A
        FULL-prompt hit still re-runs the last prompt token for its
        logits — its write position lands in the shared tail block, so
        the pool copy-on-writes it here, BEFORE any step runs."""
        while True:
            try:
                self._waiting.append(self._queue.get_nowait())
            except queue.Empty:
                break
        free = [i for i, s in enumerate(self._slots) if s is None]
        while free and self._waiting:
            req = self._waiting[0]
            plen = len(req.prompt)
            rs = req.resume
            # resume admission: the previously generated tokens replay
            # as prompt (`feed`), so the whole machinery below — cache
            # hit, chunked prefill, budget clamp — continues the
            # original generation token-identically
            feed = req.prompt if rs is None else rs.replay_tokens()
            flen = len(feed)
            max_new = min(req.max_new_tokens, self.model.max_seq - plen)
            if rs is not None and len(rs.generated) >= max_new:
                # the pause raced the budget edge: nothing left to
                # decode — settle the finished completion directly
                self._waiting.popleft()
                req.result = req.prompt + list(rs.generated)
                req.n_generated = len(rs.generated)
                req.t_done = time.monotonic()
                req._settle()
                continue
            sid = self._next_seq_id
            try:
                admitted = self.pool.try_admit(
                    sid, plen + max_new, prompt=feed,
                    cow_ok=self._can_cow)
            except ValueError as e:
                # can never fit any pool state (table width): fail it
                # alone instead of wedging the FIFO head forever
                self._waiting.popleft()
                req.error = e
                req._settle()
                continue
            if not admitted:
                if self.pool.reserved_blocks == 0:
                    # empty pool and still no room: this pool can never
                    # serve the request — fail instead of starving
                    self._waiting.popleft()
                    req.error = ValueError(
                        f"request needs {self.pool.blocks_for(plen + max_new)} "
                        f"KV blocks but the pool only has "
                        f"{self.pool.usable_blocks}")
                    req._settle()
                    continue
                if self.registry is not None:
                    self.registry.counter(
                        "serving/admissions_deferred").inc()
                break
            self._waiting.popleft()
            self._next_seq_id += 1
            hit = self.pool.admit_hit_tokens(sid)
            if rs is not None:
                # a live handoff may have shipped the partial tail
                # block's bytes: land them when the cache hit covers
                # every full page, so the tail never replays either
                hit = self._import_resume_tail(sid, rs, hit)
            # a full-prompt hit still feeds the LAST prompt token (its
            # logits seed sampling); everything before `start` is
            # served from shared blocks
            start = min(hit, flen - 1)
            req.prefix_hit_tokens = hit
            if hit and self.registry is not None:
                self.registry.counter("serving/prefix_hits").inc()
                self.registry.counter(
                    "serving/prefix_hit_tokens").inc(hit)
            cow = self.pool.ensure_writable(sid, start)
            if cow is not None:
                try:
                    self.model.copy_block(*cow)
                except Exception as e:
                    # the COW device copy is a dispatch like any step:
                    # fail the admitting request alone on a transient
                    # fault; a fatal (hung copy, device loss) drains
                    # the engine through the normal death path
                    self.pool.retire(sid)
                    req.error = e
                    req._settle()
                    if getattr(e, "fatal_to_engine", False):
                        raise
                    continue
                if self.registry is not None:
                    self.registry.counter("serving/kv_cow_copies").inc()
            live = _Live(
                req, sid, max_new, start=start,
                feed=feed if rs is not None else None,
                generated=rs.generated if rs is not None else None,
                rng_state=rs.rng_state if rs is not None else None)
            if rs is not None and self.registry is not None:
                self.registry.counter("serving/handoff_resumed").inc()
            if self._reqtrace is not None and req.trace is not None:
                live.tspan = _LiveTrace(req.trace, self._trace_pid,
                                        hit, plen)
            slot = free.pop(0)
            self._slots[slot] = live
            # first private block (or a no-op after a full hit):
            # allocate-on-admit
            self.pool.extend(sid, start + 1, written=start)
            self._btab[slot] = self.pool.table_row(sid)
            self._tokens[slot] = live.next_token
            self._slens[slot] = start

    def _import_resume_tail(self, sid: int, rs, hit: int) -> int:
        """Land a resumed sequence's migrated partial-tail KV block.
        Only when the adopted full pages already cover the hit (the
        tail chains through them — importing it over a shorter hit
        would leave a hole no replay fills) and the written watermark
        actually ends sub-page.  Returns the new effective hit; any
        failure rolls the table back to the block-aligned hit and the
        tail replays through chunked prefill instead."""
        page = self.pool.page_size
        tail_len = rs.written % page
        if (rs.kv_tail is None or not tail_len
                or rs.page_size != page
                or hit != (rs.written // page) * page
                or getattr(self.model, "import_block", None) is None):
            return hit
        try:
            self.pool.extend(sid, rs.written, written=rs.written)
            blk = self.pool.table_of(sid)[-1]
            self.model.import_block(blk, rs.kv_tail)
            if self.registry is not None:
                self.registry.counter(
                    "serving/handoff_tail_imports").inc()
            return rs.written
        except Exception as e:
            if getattr(e, "fatal_to_engine", False):
                raise
            try:
                self.pool.rollback(sid, (rs.written // page) * page)
            except Exception:  # noqa: BLE001 — fall back to replay
                pass
            return hit

    def _loop(self):
        """Thread body: run the decode loop, then drain no matter how
        it exited — a crash fails pending requests immediately instead
        of parking them for their full wait timeout (and leaves
        worker_alive False for the /v2/health degraded check).  A
        fatal exit additionally fires on_death so a supervisor
        (serving/replica.py) learns of the death without polling."""
        err: Exception = RuntimeError("ContinuousScheduler closed")
        fatal: Optional[Exception] = None
        try:
            self._decode_loop()
        except Exception as e:  # scheduler bug / pool invariant breach
            err = fatal = e
        drained = (fatal is None and self._draining
                   and not self._stop.is_set())
        if drained:
            # clean drain completion: flip the closed flag so late
            # submissions refuse, then notify AFTER the residual drain
            # below settles any racer that slipped into the queue
            self._stop.set()
        if fatal is not None:
            # the engine is dead for NEW submissions too: flip the
            # closed flag and notify the supervisor BEFORE failing the
            # pending requests, so a front's requeue callbacks already
            # see this replica as down and route elsewhere (otherwise
            # a requeue can race back onto this dead engine and park
            # until its client timeout)
            self._stop.set()
            if self._on_death is not None:
                try:
                    self._on_death(fatal)
                except Exception:  # noqa: BLE001 — the worker is
                    pass           # exiting; never mask the drain
        self._drain(err)
        if drained and self._on_drained is not None:
            try:
                self._on_drained()
            except Exception:  # noqa: BLE001 — the worker is exiting;
                pass           # a retire hook must never mask that

    def _fail_inflight(self, e: Exception):
        """Transient step fault: fail in-flight only; queued requests
        survive on the same engine."""
        self.step_failures += 1
        if self.registry is not None:
            self.registry.counter("serving/step_failures").inc()
        for i, live in enumerate(self._slots):
            if live is None:
                continue
            self.pool.retire(live.seq_id)
            if live.generated:
                # the tokens survive on the host: a front retry
                # replays them instead of regenerating from scratch
                live.req.resume_out = self._resume_record_of(live)
            live.req.error = e
            live.req._settle()
            self._slots[i] = None
            self._free_slot_buffers(i)
        # a step that died mid-execution may have consumed the
        # donated state buffers — rebuild before the next admit
        reset = getattr(self.model, "reset", None)
        if reset is not None:
            reset()
            # the rebuild ZEROED the device pools: every cached
            # prefix block's bytes are garbage now — drop the index
            # so no future admission maps onto them
            self.pool.invalidate_prefix_cache()
        # drafter state describes sequences that no longer exist (and
        # a draft twin's pools may be mid-sequence): clear it so
        # speculation resumes from scratch with the fresh engine
        if self._proposer is not None:
            self._proposer.reset()

    def _note_step_time(self, dt_s: float) -> None:
        """EWMA of per-dispatch wall time (decode + chunked-prefill).
        The disagg dispatcher prices a re-prefill as chunked steps x
        this measurement (serving/disagg.py)."""
        ms = dt_s * 1e3
        self.step_ms_ewma = (ms if self.step_ms_ewma == 0.0
                             else 0.9 * self.step_ms_ewma + 0.1 * ms)

    def _note_kernel_reads(self, blocks: int, dense_blocks: int):
        """Account one fused-kernel dispatch's KV reads: `blocks`
        physical blocks actually streamed vs the `dense_blocks` the
        gather formulation would have materialized for the same
        dispatch (obs: serving/paged_kernel_* counters)."""
        self.kernel_blocks_read += blocks
        self.kernel_dense_blocks += dense_blocks
        if self.registry is None:
            return
        reg = self.registry
        reg.counter("serving/paged_kernel_blocks_read").inc(blocks)
        if self._kv_block_bytes:
            reg.counter("serving/paged_kernel_bytes_read").inc(
                blocks * self._kv_block_bytes)
            reg.counter("serving/paged_dense_bytes_avoided").inc(
                max(0, dense_blocks - blocks) * self._kv_block_bytes)

    def _prefill_chunk_step(self, pre) -> bool:
        """One [slots, C] chunked-prefill dispatch advancing every
        mid-prefill row by up to C prompt tokens (never past plen-1:
        the last prompt token runs through the decode program, whose
        logits seed sampling).  Decode-phase rows ride along pointed
        at scratch (all-zero table row, position 0), and a prefill
        row's trailing pad tokens write garbage only at positions
        PAST its own frontier — overwritten by its later real writes
        before any query can attend them, or absorbed by scratch via
        the table padding — the same argument that makes idle-slot
        writes safe.  Returns False after a transient fault (already
        handled); fatal faults propagate."""
        C = self._chunk
        tok = np.zeros((self.model.batch_slots, C), np.int32)
        slen = np.zeros(self.model.batch_slots, np.int32)
        btab = np.zeros_like(self._btab)
        plan = []
        for i, live in pre:
            flen = len(live.feed)
            upto = min(live.pos + C, flen - 1)
            self.pool.extend(live.seq_id, upto, written=live.pos)
            self._btab[i] = self.pool.table_row(live.seq_id)
            tok[i, :upto - live.pos] = live.feed[live.pos:upto]
            slen[i] = live.pos
            btab[i] = self._btab[i]
            plan.append((i, live, upto))
        bspan = None
        if self._reqtrace is not None and any(
                live.tspan is not None for _, live in pre):
            bspan = self._reqtrace.batch_span(
                "prefill_chunk", self._trace_pid,
                rows=len(plan), chunk=C)
        t0 = time.monotonic()
        try:
            self.model.prefill_step(tok, slen, btab)
        except Exception as e:
            if getattr(e, "fatal_to_engine", False):
                raise
            self._fail_inflight(e)
            return False
        if bspan is not None:
            bspan.end()
            for _, live in pre:
                if live.tspan is not None:
                    live.tspan.ref_chunk(bspan)
        self._note_step_time(time.monotonic() - t0)
        self.prefill_steps += 1
        if self._paged_kernel == "pallas":
            # the prefill program scans the seq-1 kernel C times per
            # row: account each scan position as one seq-1 dispatch
            # over the plan rows (shared formula with the kernel:
            # paged_attention.blocks_read)
            from ..ops.pallas.paged_attention import blocks_read

            tw = self.pool.max_blocks_per_seq
            slens = np.array([live.pos for _, live, _ in plan])
            mask = np.ones(len(plan), bool)
            blocks = sum(
                blocks_read(slens + j, mask, 1, self.pool.page_size, tw)
                for j in range(C))
            self._note_kernel_reads(
                blocks, self.model.batch_slots * tw * C)
        for i, live, upto in plan:
            live.pos = upto
            # the freshly written prompt blocks join the prefix index
            # NOW, so a same-prefix arrival in the next admit already
            # shares them
            self.pool.note_written(live.seq_id, upto)
            live.next_token = live.feed[live.pos]
            self._tokens[i] = live.next_token
            self._slens[i] = live.pos
        if self._check_invariants:
            self.pool.check_invariants()
        return True

    def _spec_proposals(self):
        """Ask the proposer for this round's drafts.  Eligible rows are
        GREEDY decode-phase slots with >= 2 tokens of budget left (a
        draft only helps if at least one extra token may be emitted);
        mid-prefill and sampled rows ride the verify round with
        count 1.  Per-row draft length is capped by the adaptive-k
        controller and the row's remaining budget, so fed positions
        never pass prompt+max_new (<= max_seq by admission)."""
        k = min(self._adaptive.k, self._spec_k)
        contexts: Dict[int, List[int]] = {}
        limits: Dict[int, int] = {}
        caps: Dict[int, int] = {}
        for i, live in enumerate(self._slots):
            if live is None or live.req.temperature > 0.0:
                continue
            plen = len(live.req.prompt)
            if live.pos < len(live.feed) - 1:
                continue  # still prefilling (or replaying a resume)
            rem = live.max_new - len(live.generated)
            if rem < 2:
                continue
            contexts[i] = live.req.prompt + live.generated
            limits[i] = min(plen + live.max_new + self._spec_k,
                            self.model.max_seq)
            caps[i] = min(k, rem - 1)
        if not contexts:
            return None
        try:
            props = self._proposer.propose(contexts, k, limits)
        except Exception:  # noqa: BLE001 — a proposer bug degrades to
            self._spec_broken = True   # plain decode, never kills the
            return None                # engine
        out = {}
        for i, d in (props or {}).items():
            if i in caps and d:
                d = [int(t) for t in d[:caps[i]]]
                if d:
                    out[i] = d
        return out or None

    def _spec_round(self, props) -> bool:
        """ONE speculative verify dispatch advancing EVERY live row:
        row i feeds its pending next_token followed by its draft
        tokens (counts[i] total; 1 for rows without proposals) and
        gets per-position logits back.  Greedy rows accept the longest
        prefix of drafts matching the model's own argmax chain plus
        the first corrected token; the KV pool rolls back past the
        accept point (un-registering prefix-index entries over
        rejected positions and COWing a kept shared tail).  Per-step
        logits are bit-identical to seq-1 stepping, so acceptance is
        token-identical to plain decode BY CONSTRUCTION.

        Returns True when the round ran; False after a verify fault —
        speculation is disabled (sticky for this engine instance) and
        in-flight slots continue on the plain decode path, where a
        consumed state surfaces as an ordinary step fault."""
        C = self.model.verify_chunk
        bs = self.model.batch_slots
        tok = np.zeros((bs, C), np.int32)
        counts = np.zeros(bs, np.int32)
        for i, live in enumerate(self._slots):
            if live is None:
                continue
            tok[i, 0] = live.next_token
            counts[i] = 1
            d = props.get(i)
            if d:
                m = 1 + len(d)
                tok[i, 1:m] = d
                counts[i] = m
                # the drafts' blocks must exist BEFORE dispatch; the
                # admission reservation covers them (fed positions
                # stay under prompt+max_new)
                self.pool.extend(live.seq_id, live.pos + m,
                                 written=live.pos)
                self._btab[i] = self.pool.table_row(live.seq_id)
        bspan = None
        if self._reqtrace is not None and any(
                s is not None and s.tspan is not None
                for s in self._slots):
            bspan = self._reqtrace.batch_span(
                "spec_verify", self._trace_pid,
                rows=int((counts > 0).sum()),
                drafted=len(props), fed=int(counts.sum()),
                **self._proposer.trace_attrs())
        t0 = time.monotonic()
        try:
            logits = self.model.verify_step(
                tok, self._slens, counts, self._btab)
        except Exception as e:
            if getattr(e, "fatal_to_engine", False):
                raise  # hung verify / device loss: drain-and-die
            # transient verify fault: DEGRADE, don't fail in-flight —
            # a pre-dispatch injection left the state intact and the
            # plain decode path resumes token-identically; a true
            # mid-dispatch death surfaces on the next plain step and
            # takes the normal _fail_inflight recovery
            self.spec_verify_faults += 1
            self._spec_broken = True
            if self._proposer is not None:
                self._proposer.reset()
            if self.registry is not None:
                self.registry.counter(
                    "serving/spec_verify_faults").inc()
            return False
        if bspan is not None:
            bspan.end()
        self._note_step_time(time.monotonic() - t0)
        self.batches_run += 1
        self.spec_rounds += 1
        if self._spec_t0 is None:
            self._spec_t0 = time.monotonic()
        if self._paged_kernel == "pallas":
            from ..ops.pallas.paged_attention import blocks_read

            tw = self.pool.max_blocks_per_seq
            blocks = 0
            for j in range(C):
                mask = counts > j
                if not mask.any():
                    break
                blocks += blocks_read(self._slens + j, mask, 1,
                                      self.pool.page_size, tw)
            self._note_kernel_reads(blocks, bs * tw * C)
        now = time.monotonic()
        for i, live in enumerate(self._slots):
            if live is None:
                continue
            m = int(counts[i])
            if live.pos < len(live.feed) - 1:
                # mid-prefill row rode with its prompt token (m == 1):
                # identical to the plain decode path's prefill branch
                live.pos += 1
                self.pool.note_written(live.seq_id, live.pos)
                live.next_token = live.feed[live.pos]
                self._tokens[i] = live.next_token
                self._slens[i] = live.pos
                if live.tspan is not None:
                    live.tspan.ref_step(bspan, spec=True)
                continue
            # decode-phase: walk the model's own token chain across
            # the fed positions — position j's output is valid iff
            # every fed token before it matched the chain
            out: List[int] = []
            for j in range(m):
                t = int(self._sample(logits[i, j], live))
                out.append(t)
                if self.eos_id >= 0 and t == self.eos_id:
                    break
                if j + 1 >= m or t != int(tok[i, j + 1]):
                    break
            emitted = len(out)
            proposed, accepted = m - 1, emitted - 1
            # watermark first (the dispatch really wrote all m
            # positions), then roll rejected positions back out —
            # freeing their blocks, un-registering their prefix-index
            # entries, and COWing a kept shared tail
            self.pool.note_written(live.seq_id, live.pos + m)
            new_pos = live.pos + emitted
            if m > emitted:
                cow = self.pool.rollback(live.seq_id, new_pos)
                # the table shrank (and its kept tail block may have
                # been COW-swapped): refresh the row BEFORE the next
                # dispatch can write through a stale block id
                self._btab[i] = self.pool.table_row(live.seq_id)
                if cow is not None:
                    try:
                        self.model.copy_block(*cow)
                    except Exception as e:
                        if getattr(e, "fatal_to_engine", False):
                            raise
                        # rollback's device COW failed: this row's KV
                        # is unsynced — fail the one request, like the
                        # admission COW path
                        self.pool.retire(live.seq_id)
                        if self._proposer is not None:
                            self._proposer.release(i)
                        live.req.error = e
                        live.req._settle()
                        self._slots[i] = None
                        self._free_slot_buffers(i)
                        continue
            live.pos = new_pos
            if proposed:
                self.spec_proposed += proposed
                self.spec_accepted += accepted
                live.req.spec_proposed += proposed
                live.req.spec_accepted += accepted
                self._adaptive.update(proposed, accepted)
                if self.registry is not None:
                    reg = self.registry
                    reg.counter("serving/spec_proposed").inc(proposed)
                    reg.counter("serving/spec_accepted").inc(accepted)
                    reg.histogram(
                        "serving/spec_accepted_per_round").observe(
                        accepted)
            if live.tspan is not None:
                live.tspan.ref_step(bspan, spec=True)
            if not live.generated:
                live.req.t_first_token = now
                with self._lat_lock:
                    self._ttfts.append(now - live.req.t_submit)
                if self.registry is not None:
                    self.registry.histogram("serving/ttft_ms").observe(
                        (now - live.req.t_submit) * 1e3,
                        exemplar=(live.req.trace.trace_id
                                  if live.req.trace is not None
                                  else None))
                if live.tspan is not None:
                    live.tspan.to_decode()
            live.generated.extend(out)
            self.tokens_generated += emitted
            done = (len(live.generated) >= live.max_new
                    or (self.eos_id >= 0 and out[-1] == self.eos_id))
            if done:
                self._finish(i, live)
            else:
                live.next_token = out[-1]
                self._tokens[i] = out[-1]
                self._slens[i] = live.pos
        if self.registry is not None:
            self.registry.counter("serving/spec_rounds").inc()
        return True

    def _decode_loop(self):
        page = self.pool.page_size
        while not self._stop.is_set():
            self._run_services()
            self._admit()
            if all(s is None for s in self._slots):
                if (self._draining and not self._waiting
                        and self._queue.empty()):
                    # drain complete: nothing live, nothing queued —
                    # exit cleanly (a submit that raced past the
                    # drain() cutoff sits in _queue and was admitted
                    # above, so it is NOT abandoned here)
                    return
                # idle: park on the arrival queue instead of spinning
                try:
                    self._waiting.append(self._queue.get(timeout=0.05))
                except queue.Empty:
                    pass
                continue
            if self._chunk:
                # chunked prefill first: mid-prefill rows jump up to C
                # positions, then everyone (them included) takes the
                # normal one-token decode step below
                pre = [(i, live) for i, live in enumerate(self._slots)
                       if live is not None
                       and live.pos < len(live.feed) - 1]
                if pre and not self._prefill_chunk_step(pre):
                    continue
            for i, live in enumerate(self._slots):
                if live is None:
                    continue
                # crossing a page boundary: allocate the next block
                # (admission reserved it, so this cannot fail)
                if live.pos and live.pos % page == 0:
                    self.pool.extend(live.seq_id, live.pos + 1)
                    self._btab[i] = self.pool.table_row(live.seq_id)
            if self._spec != "off" and not self._spec_broken:
                props = self._spec_proposals()
                if props:
                    # speculative round: every live row rides ONE
                    # verify dispatch (drafted rows multi-token,
                    # everyone else count-1)
                    if self._spec_round(props):
                        self._observe_step()
                    continue
                # no proposals anywhere: fall through to the plain
                # [slots, 1] decode step — the required empty-round
                # fallback (and the whole path when spec is off)
                self.spec_fallback_rounds += 1
            bspan = None
            if self._reqtrace is not None and any(
                    s is not None and s.tspan is not None
                    for s in self._slots):
                bspan = self._reqtrace.batch_span(
                    "decode_step", self._trace_pid,
                    rows=sum(1 for s in self._slots if s is not None))
            t0 = time.monotonic()
            try:
                logits = self.model.step(
                    self._tokens, self._slens, self._btab)
            except Exception as e:
                if getattr(e, "fatal_to_engine", False):
                    # device-loss-style fault (hung dispatch, lost
                    # device — serving/replica.py marks them): the
                    # ENGINE is gone, not just this batch.  Propagate
                    # so _loop drains everything and fires on_death —
                    # the supervisor restarts the replica.
                    raise
                self._fail_inflight(e)
                continue
            if bspan is not None:
                bspan.end()
            self._note_step_time(time.monotonic() - t0)
            self.batches_run += 1
            if self._paged_kernel == "pallas":
                from ..ops.pallas.paged_attention import blocks_read

                self._note_kernel_reads(
                    blocks_read(
                        self._slens,
                        np.array([s is not None for s in self._slots]),
                        1, page, self.pool.max_blocks_per_seq),
                    self.model.batch_slots
                    * self.pool.max_blocks_per_seq)
            now = time.monotonic()
            for i, live in enumerate(self._slots):
                if live is None:
                    continue
                live.pos += 1
                # keep the pool's written-token watermark current so
                # fragmentation never over-reports a mid-page tail
                self.pool.note_written(live.seq_id, live.pos)
                if live.pos < len(live.feed):
                    # prefill: the next token is given, logits ignored
                    live.next_token = live.feed[live.pos]
                    self._tokens[i] = live.next_token
                    self._slens[i] = live.pos
                    if live.tspan is not None:
                        live.tspan.ref_step(bspan)
                    continue
                tok = int(self._sample(logits[i], live))
                if live.tspan is not None:
                    live.tspan.ref_step(bspan)
                if not live.generated:
                    live.req.t_first_token = now
                    with self._lat_lock:
                        self._ttfts.append(now - live.req.t_submit)
                    if self.registry is not None:
                        self.registry.histogram(
                            "serving/ttft_ms").observe(
                            (now - live.req.t_submit) * 1e3,
                            exemplar=(live.req.trace.trace_id
                                      if live.req.trace is not None
                                      else None))
                    if live.tspan is not None:
                        live.tspan.to_decode()
                live.generated.append(tok)
                self.tokens_generated += 1
                done = (len(live.generated) >= live.max_new
                        or (self.eos_id >= 0 and tok == self.eos_id))
                if done:
                    self._finish(i, live)
                else:
                    live.next_token = tok
                    self._tokens[i] = tok
                    self._slens[i] = live.pos
            self._observe_step()

    def _sample(self, row_logits: np.ndarray, live: _Live) -> int:
        if live.req.temperature <= 0.0:  # greedy hot path: one argmax
            return int(row_logits.argmax())
        from ..models.transformer import sample_next

        return sample_next(row_logits[None], live.req.temperature,
                           live.rng)[0]

    def _finish(self, slot: int, live: _Live):
        if self._proposer is not None:
            self._proposer.release(slot)
        # the written token prefix (everything fed; excludes the final
        # sampled token, whose k/v never landed) keys the retired
        # blocks into the prefix cache — a future prompt extending
        # this completion hits them
        self.pool.retire(
            live.seq_id,
            tokens=(live.req.prompt + live.generated)[:live.pos])
        self._slots[slot] = None
        self._free_slot_buffers(slot)
        req = live.req
        req.n_generated = len(live.generated)
        req.result = req.prompt + live.generated
        req.t_done = time.monotonic()
        if live.tspan is not None:
            live.tspan.finish(req)
            live.tspan = None
        with self._lat_lock:
            self._latencies.append(req.t_done - req.t_submit)
        self.requests_done += 1
        if self.registry is not None:
            reg = self.registry
            ex = req.trace.trace_id if req.trace is not None else None
            reg.counter("serving/requests_done").inc()
            reg.histogram("serving/request_latency_ms").observe(
                (req.t_done - req.t_submit) * 1e3, exemplar=ex)
            if req.n_generated > 1 and req.t_first_token is not None:
                reg.histogram("serving/per_token_ms").observe(
                    (req.t_done - req.t_first_token) * 1e3
                    / (req.n_generated - 1), exemplar=ex)
        req._settle()

    def _observe_step(self):
        if self._check_invariants:
            self.pool.check_invariants()
        if self.registry is None:
            return
        reg = self.registry
        live = [s for s in self._slots if s is not None]
        reg.counter("serving/steps").inc()
        reg.gauge("serving/queue_depth").set(
            self._queue.qsize() + len(self._waiting))
        reg.gauge("serving/live_sequences").set(len(live))
        reg.gauge("serving/kv_used_blocks").set(self.pool.used_blocks)
        reg.gauge("serving/kv_shared_blocks").set(
            self.pool.shared_blocks)
        reg.gauge("serving/kv_cached_blocks").set(
            self.pool.cached_blocks)
        ev = self.pool.prefix_evictions
        if ev > self._evictions_seen:
            reg.counter("serving/prefix_evictions").inc(
                ev - self._evictions_seen)
            self._evictions_seen = ev
        reg.histogram("serving/kv_occupancy").observe(
            self.pool.occupancy())
        reg.histogram("serving/kv_fragmentation").observe(
            self.pool.fragmentation())
        if self.spec_rounds and self._spec_t0 is not None:
            dt = time.monotonic() - self._spec_t0
            if dt > 0:
                reg.gauge("serving/spec_rounds_per_s").set(
                    round(self.spec_rounds / dt, 4))
