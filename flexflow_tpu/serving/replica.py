"""Supervised serving replica: one ContinuousScheduler under the
training-side resilience primitives (resilience/).

The training supervisor (resilience/supervisor.py) classifies failures
into transients (restore + retry) and device-loss-style faults
(re-search + recompile + reshard-restore).  A serving replica inherits
the same taxonomy, adapted to a stateless decode engine:

  * **transient step exception** — the scheduler's existing per-step
    handling stands: only the in-flight batch fails (the front requeues
    those requests), the replica keeps serving;
  * **hung decode step** — the decode dispatch runs under a
    `StepWatchdog(step_timeout)`; a dispatch that never returns raises
    `HungStepTimeout` instead of wedging the worker forever.  That (and
    its injected twin `HungStepFault`) is FATAL to the engine: the
    wedged collective state only resets with a rebuilt engine;
  * **device loss** — `DeviceLossFault(survivors=k)` kills the engine
    and the rebuild happens on the surviving device count; the model
    factory's compile consults the strategy store's degraded-mesh key
    first (docs/STORE.md), so the re-search is warm whenever any
    replica or training run has paid it before.

Fatal faults are marked with ``fatal_to_engine = True`` — the
scheduler's contract for "drain everything and die" (scheduler.py) —
which fires the replica's `on_death` hook.  The replica's supervisor
thread then restarts the engine under a jittered-backoff `RetryPolicy`
with a hard restart budget; a replica that outruns the budget goes
permanently ``dead`` and `/v2/health` says so.

Fault injection is the training side's seeded `FaultPlan`: the plan's
step index counts DECODE steps (cumulative across restarts), so a
replica-kill benchmark replays exactly (bench.py serving_resilience).
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, Dict, Optional

from ..logger import resilience_logger
from ..resilience.faults import DeviceLossFault, FaultPlan, HungStepFault
from ..resilience.retry import RetryPolicy
from ..resilience.watchdog import HungStepTimeout, StepWatchdog
from .scheduler import ContinuousScheduler

#: failures that kill the ENGINE, not just the in-flight batch — the
#: supervisor answers them with a restart (cf. supervisor.HUNG_FAULTS)
FATAL_DECODE_FAULTS = (DeviceLossFault, HungStepFault, HungStepTimeout)

#: per-replica scheduler counters folded into `stats()` across restarts
_CARRIED_COUNTERS = ("batches_run", "requests_done", "tokens_generated",
                     "step_failures")


class SupervisedDecodeModel:
    """Decode-model wrapper adding the resilience instrumentation to
    every step: seeded fault injection, then the watchdog-bounded
    dispatch.  Proxies the geometry attributes ContinuousScheduler
    reads (batch_slots, page_size, num_blocks, ...)."""

    def __init__(self, model, watchdog: StepWatchdog,
                 fault_plan: FaultPlan, step_counter):
        self._model = model
        self._watchdog = watchdog
        self._fault_plan = fault_plan
        self._steps = step_counter  # replica-lifetime, restart-spanning
        for name in ("batch_slots", "page_size", "num_blocks",
                     "max_blocks_per_seq", "max_seq", "vocab"):
            setattr(self, name, getattr(model, name))
        # prefix-cache / chunked-prefill surface (PagedKVDecodeModel;
        # absent on bare test fakes -> the scheduler degrades cleanly)
        self.prefill_chunk = getattr(model, "prefill_chunk", 0)
        self.prefix_cache = getattr(model, "prefix_cache", True)
        # fused-kernel surface: which paged formulation runs + the
        # per-block byte unit the scheduler's read telemetry uses
        self.paged_kernel = getattr(model, "paged_kernel", "gather")
        self.kv_block_bytes = getattr(model, "kv_block_bytes", 0)
        # tensor-parallel surface: how many chips this engine spans and
        # the per-chip share of each KV block (1 chip / full block on
        # single-device engines and bare test fakes)
        self.tp = getattr(model, "tp", 1)
        self.mesh_shape = dict(getattr(model, "mesh_shape", {}) or {})
        self.kv_block_bytes_per_chip = getattr(
            model, "kv_block_bytes_per_chip", self.kv_block_bytes)
        # speculative surface (docs/SERVING.md "Speculative
        # decoding"): mode/k/verify geometry proxied; the draft twin
        # is handed through RAW — its dispatches belong to the
        # proposer and are fault-isolated there (a draft death
        # degrades to plain decode, it never counts against this
        # replica's fault plan or watchdog)
        self.spec_decode = getattr(model, "spec_decode", "off")
        self.spec_k = getattr(model, "spec_k", 0)
        self.verify_chunk = getattr(model, "verify_chunk", 0)
        self.draft_model = getattr(model, "draft_model", None)
        if getattr(model, "prefill_step", None) is None:
            self.prefill_chunk = 0
        self._has_verify = (self.spec_decode != "off" and getattr(
            model, "verify_step", None) is not None)
        if not self._has_verify:
            self.spec_decode = "off"
        self._has_copy = getattr(model, "copy_block", None) is not None
        self._has_export = (
            getattr(model, "export_block", None) is not None
            and getattr(model, "import_block", None) is not None)

    def reset(self):
        reset = getattr(self._model, "reset", None)
        if reset is not None:
            reset()

    def step(self, tokens, seq_lens, block_tables):
        idx = next(self._steps)
        try:
            self._fault_plan.check_step(idx)
            return self._watchdog.sync(
                lambda: self._model.step(tokens, seq_lens, block_tables),
                step=idx,
            )
        except FATAL_DECODE_FAULTS as e:
            # the scheduler must drain-and-die, not fail-in-flight-only
            e.fatal_to_engine = True
            raise

    def prefill_step(self, tokens, positions, block_tables):
        # chunked prefill is a decode-fleet step like any other: fault
        # injection and the hang watchdog see it under the same
        # replica-lifetime step index
        idx = next(self._steps)
        try:
            self._fault_plan.check_step(idx)
            return self._watchdog.sync(
                lambda: self._model.prefill_step(
                    tokens, positions, block_tables),
                step=idx,
            )
        except FATAL_DECODE_FAULTS as e:
            e.fatal_to_engine = True
            raise

    @property
    def verify_step(self):
        # speculative verify is a decode-fleet dispatch like any step:
        # fault injection and the hang watchdog see it under the same
        # replica-lifetime step index, and a hung/lost-device verify is
        # marked fatal so the scheduler drains-and-dies into a
        # supervised restart.  A TRANSIENT verify fault stays
        # non-fatal: the scheduler disables speculation and the
        # in-flight slots continue on the plain decode path.
        # None-propagating capability probe like copy_block.
        if not self._has_verify:
            return None

        def _verify(tokens, seq_lens, counts, block_tables):
            idx = next(self._steps)
            try:
                self._fault_plan.check_step(idx)
                return self._watchdog.sync(
                    lambda: self._model.verify_step(
                        tokens, seq_lens, counts, block_tables),
                    step=idx,
                )
            except FATAL_DECODE_FAULTS as e:
                e.fatal_to_engine = True
                raise

        return _verify

    @property
    def copy_block(self):
        # exposed as an attribute so the scheduler's capability probe
        # (getattr(..., "copy_block", None)) reflects the wrapped
        # model's.  The copy is a device dispatch like any step, so it
        # runs under the same fault plan + hang watchdog — a wedged
        # COW must surface as HungStepTimeout (fatal -> supervised
        # restart), not silently park the scheduler worker.
        if not self._has_copy:
            return None

        def _copy(src, dst):
            idx = next(self._steps)
            try:
                self._fault_plan.check_step(idx)
                return self._watchdog.sync(
                    lambda: self._model.copy_block(src, dst), step=idx)
            except FATAL_DECODE_FAULTS as e:
                e.fatal_to_engine = True
                raise

        return _copy

    @property
    def export_block(self):
        # KV migration surface (serving/kv_transfer.py): eager
        # host<->device copies on the worker thread, not watchdogged
        # step dispatches — a wedged device read surfaces on the next
        # stepped dispatch.  None-propagating capability probe like
        # copy_block: a fake model without pools disables migration.
        if not self._has_export:
            return None
        return self._model.export_block

    @property
    def import_block(self):
        if not self._has_export:
            return None
        return self._model.import_block


class ServingReplica:
    """One supervised engine slot of a ServingFront.

    `model_factory(replica_id, survivors=None)` builds the decode model
    (a PagedKVDecodeModel for real GPTs; anything with the same step
    contract in tests).  `survivors` is the device count a
    DeviceLossFault left standing — a real factory maps it to a device
    list and recompiles, which consults the strategy store's
    degraded-mesh key before paying a search (docs/STORE.md).

    States: ``live`` (serving — READY), ``restarting`` (death observed,
    rebuild pending/underway), ``draining`` (autoscaler scale-down or
    SIGTERM grace: no new dispatches, in-flight slots run to
    completion), ``retired`` (drain finished — the engine and its KV
    pool are released, permanently out of the fleet), ``dead`` (restart
    budget exhausted — permanent), ``closed``.  `on_state_change` (set
    by the front) fires on every transition so the dispatcher never
    polls.
    """

    def __init__(
        self,
        replica_id: int,
        model_factory: Callable,
        *,
        eos_id: int = -1,
        registry=None,
        seed: int = 0,
        step_timeout: float = 0.0,
        retry: Optional[RetryPolicy] = None,
        fault_plan: Optional[FaultPlan] = None,
        close_timeout_s: float = 5.0,
        sleep: Callable[[float], None] = time.sleep,
        logger=resilience_logger,
        role: str = "mixed",
        check_invariants: bool = False,
        reqtrace=None,
    ):
        self.replica_id = int(replica_id)
        self.model_factory = model_factory
        # replica class in a disaggregated fleet (serving/disagg.py):
        # "prefill" runs prompt passes whose KV migrates out, "decode"
        # serves client requests, "mixed" (default) does both — the
        # colocated fleet unchanged
        if role not in ("prefill", "decode", "mixed"):
            raise ValueError(
                f"replica role {role!r}: pick from "
                "['prefill', 'decode', 'mixed']")
        self.role = role
        self._check_invariants = bool(check_invariants)
        # request tracer shared fleet-wide (obs/reqtrace.py): every
        # rebuild hands it to the fresh scheduler with this replica's
        # id as the Perfetto track (pid)
        self._reqtrace = reqtrace
        self.eos_id = int(eos_id)
        self.registry = registry
        self.seed = int(seed)
        self.retry = retry or RetryPolicy()
        self.fault_plan = fault_plan or FaultPlan()
        self.watchdog = StepWatchdog(step_timeout)
        self.close_timeout_s = float(close_timeout_s)
        self.sleep = sleep
        self.log = logger
        self.on_state_change: Optional[Callable] = None
        # dispatch bookkeeping owned by the front (under ITS lock)
        self.outstanding = 0
        self.state = "restarting"  # -> live after the first build
        self.restarts = 0       # successful rebuilds
        self.deaths = 0         # fatal engine exits observed
        self.last_death_t: Optional[float] = None
        self.last_live_t: Optional[float] = None
        self.last_recovery_s: Optional[float] = None
        self.last_error: Optional[Exception] = None
        self.scheduler: Optional[ContinuousScheduler] = None
        self._steps = itertools.count()  # decode-step index, all lives
        self._carried: Dict[str, int] = {k: 0 for k in _CARRIED_COUNTERS}
        self._survivors: Optional[int] = None
        self._death_evt = threading.Event()
        self._closed = False
        self._draining = False
        self._retire_guard = threading.Lock()
        self._retire_done = False
        self._on_retired: Optional[Callable] = None
        self.drain_started_t: Optional[float] = None
        self.retired_t: Optional[float] = None
        self._build()
        self._set_state("live")
        self._supervisor = threading.Thread(
            target=self._supervise, daemon=True,
            name=f"serving-replica-{replica_id}",
        )
        self._supervisor.start()

    # -- state ----------------------------------------------------------
    @property
    def alive(self) -> bool:
        return self.state == "live" and self.scheduler is not None

    def _set_state(self, state: str) -> None:
        if self._closed and state != "closed":
            return  # a rebuild that raced close() must not resurrect us
        if self.state == "retired" and state not in ("closed",):
            return  # retirement is permanent — no resurrection
        self.state = state
        if state == "live":
            self.last_live_t = time.monotonic()
            if self.last_death_t is not None:
                self.last_recovery_s = self.last_live_t - self.last_death_t
        hook = self.on_state_change
        if hook is not None:
            try:
                hook(self)
            except Exception:  # noqa: BLE001 — never kill the supervisor
                pass

    def _count(self, name: str) -> None:
        if self.registry is not None:
            self.registry.counter(f"serving/{name}").inc()

    # -- engine lifecycle ------------------------------------------------
    def _build(self) -> None:
        model = self.model_factory(self.replica_id,
                                   survivors=self._survivors)
        wrapped = SupervisedDecodeModel(model, self.watchdog,
                                        self.fault_plan, self._steps)
        self.scheduler = ContinuousScheduler(
            wrapped,
            eos_id=self.eos_id,
            registry=self.registry,
            seed=self.seed + 7919 * self.replica_id,
            close_timeout_s=self.close_timeout_s,
            on_death=self._on_death,
            check_invariants=self._check_invariants,
            reqtrace=self._reqtrace,
            trace_pid=self.replica_id,
        )

    def _on_death(self, exc: Exception) -> None:
        """Runs on the dying scheduler worker: record why, flip the
        state so the dispatcher stops routing here, and wake the
        supervisor thread to do the heavy rebuild off this stack."""
        self.last_error = exc
        self.last_death_t = time.monotonic()
        if isinstance(exc, DeviceLossFault):
            self._survivors = exc.survivors
        self.deaths += 1
        self._count("replica_deaths")
        self.log.info("serving replica %d died: %s", self.replica_id, exc)
        if not self._draining:
            # a DRAINING replica was leaving anyway: stay in draining
            # (the supervisor retires it instead of rebuilding)
            self._set_state("restarting")
        self._death_evt.set()

    def _fold_carried(self) -> None:
        sched = self.scheduler
        if sched is None:
            return
        for k in _CARRIED_COUNTERS:
            self._carried[k] += int(getattr(sched, k, 0))

    def _supervise(self) -> None:
        """Restart loop: each observed death costs one unit of the
        retry budget; past the budget the replica is permanently dead
        (a replica that dies on every rebuild must fail loudly, not
        flap forever)."""
        while True:
            self._death_evt.wait()
            self._death_evt.clear()
            if self._closed:
                return
            if self._draining:
                # death observed while leaving the fleet: the front
                # already requeued the stranded in-flight requests —
                # retire instead of paying a rebuild nobody wants
                self._retire()
                return
            self._fold_carried()
            self.scheduler = None
            attempt = self.deaths
            if not self.retry.admits(attempt):
                self._set_state("dead")
                self.log.info(
                    "serving replica %d: restart budget (%d) exhausted — "
                    "permanently dead", self.replica_id,
                    self.retry.max_restarts,
                )
                continue  # stay parked until close()
            self.sleep(self.retry.backoff(attempt))
            if self._closed:
                return
            try:
                self._build()
            except Exception as e:  # noqa: BLE001 — a failed rebuild is
                # another death: budget-capped, never an escaped crash
                self.last_error = e
                self.deaths += 1
                self._count("replica_deaths")
                self.log.info(
                    "serving replica %d rebuild failed: %s",
                    self.replica_id, e,
                )
                self._death_evt.set()
                continue
            if self._closed:
                # close() raced the rebuild (its bounded join expired
                # while _build was compiling): the fresh engine must
                # not leak a worker thread or flip us back to live
                sched = self.scheduler
                self.scheduler = None
                if sched is not None:
                    sched.close(self.close_timeout_s)
                return
            self.restarts += 1
            self._count("replica_restarts")
            self._survivors_note()
            self._set_state("live")

    def _survivors_note(self) -> None:
        if self._survivors is not None:
            self.log.info(
                "serving replica %d restarted on %d surviving devices "
                "(restart %d)", self.replica_id, self._survivors,
                self.restarts,
            )
        else:
            self.log.info("serving replica %d restarted (restart %d)",
                          self.replica_id, self.restarts)

    # -- drain lifecycle (autoscaler scale-down / SIGTERM grace) ---------
    def drain(self, on_retired: Optional[Callable] = None) -> bool:
        """READY -> DRAINING: stop taking new work, let in-flight slots
        run to completion (token-identical — decode is undisturbed),
        then retire and release the engine + KV pool.  Returns False if
        the replica is not currently live (nothing to drain).

        `on_retired(replica)` fires exactly once when the drain
        completes — including when a fault kills the draining engine
        (in-flight requests are requeued by the front; a leaving
        replica is never rebuilt)."""
        sched = self.scheduler
        if self.state != "live" or sched is None or self._closed:
            return False
        self._draining = True
        self._on_retired = on_retired
        self.drain_started_t = time.monotonic()
        self._count("replica_drains")
        self.log.info("serving replica %d draining", self.replica_id)
        self._set_state("draining")  # dispatcher stops routing here
        sched.drain(on_drained=self._retire)
        return True

    def _retire(self) -> None:
        """DRAINING -> RETIRED: release the engine (the KV pool goes
        with it) and notify the front.  Idempotent under CONCURRENT
        callers — a clean drain completion, a death-while-draining,
        and a force_retire may all arrive, from different threads;
        exactly one runs the body (else _fold_carried double-counts
        and on_retired fires twice)."""
        with self._retire_guard:
            if self._retire_done or self.state == "retired":
                return
            self._retire_done = True
        self._fold_carried()
        self.scheduler = None  # drops the pool: KV blocks are freed
        self.retired_t = time.monotonic()
        if self.drain_started_t is not None and self.registry is not None:
            self.registry.histogram("serving/drain_ms").observe(
                (self.retired_t - self.drain_started_t) * 1e3)
        self._count("replica_retired")
        self.log.info("serving replica %d retired", self.replica_id)
        self._set_state("retired")
        hook = self._on_retired
        self._on_retired = None
        if hook is not None:
            try:
                hook(self)
            except Exception:  # noqa: BLE001 — never kill the worker
                pass           # or supervisor retiring us
        # retirement is the replica's end of life: release the parked
        # supervisor thread too.  front.close() only sweeps fleet
        # members, so without this every clean scale-down would leave
        # one daemon thread blocked on _death_evt until process exit.
        self._closed = True
        self._death_evt.set()

    def force_retire(self, timeout_s: Optional[float] = None) -> None:
        """Bounded end of a wedged drain: close the engine (in-flight
        requests fail and the front requeues them onto survivors),
        then retire.  The autoscaler calls this when a drain outlives
        its deadline."""
        sched = self.scheduler
        if sched is not None:
            sched.close(timeout_s if timeout_s is not None
                        else self.close_timeout_s)
        self._retire()

    # -- front-facing ----------------------------------------------------
    def submit(self, prompt, max_new_tokens, temperature, on_done,
               trace=None, seed=None, resume=None):
        sched = self.scheduler
        if self.state != "live" or sched is None:
            raise RuntimeError(
                f"serving replica {self.replica_id} is {self.state}")
        return sched.generate_async(prompt, max_new_tokens, temperature,
                                    on_done=on_done, trace=trace,
                                    seed=seed, resume=resume)

    def request_handoff(self, **kw) -> bool:
        """Ask the scheduler to pause in-flight generations for
        handoff (see ContinuousScheduler.request_handoff).  Unlike
        submit this works while DRAINING — that is its main caller:
        a draining replica migrates its long generations off instead
        of waiting them out.  Returns False when there is no engine
        to ask (the on_paused callback will not fire)."""
        sched = self.scheduler
        if sched is None or self.state in ("retired", "closed"):
            return False
        try:
            sched.request_handoff(**kw)
            return True
        except Exception:  # noqa: BLE001 — racing a death/close
            return False

    def stats(self) -> Dict:
        sched = self.scheduler
        out = {
            "id": self.replica_id,
            "state": self.state,
            "role": self.role,
            "restarts": self.restarts,
            "deaths": self.deaths,
            "outstanding": self.outstanding,
            "last_recovery_s": self.last_recovery_s,
        }
        for k in _CARRIED_COUNTERS:
            out[k] = self._carried[k] + int(getattr(sched, k, 0) or 0)
        if sched is not None:
            sstats = sched.stats()
            out["queue_depth"] = sstats["queue_depth"]
            # prefix-cache visibility per replica (each pool caches
            # independently; shared blocks counted once per pool)
            if "prefix_cache" in sstats:
                out["prefix_cache"] = sstats["prefix_cache"]
            # which paged formulation this replica runs + its fused
            # kernel's KV-read counters (zeroes under the gather oracle)
            if "paged_kernel" in sstats:
                out["paged_kernel"] = sstats["paged_kernel"]
            # tensor-parallel geometry: chips spanned + per-chip KV share
            if "tp" in sstats:
                out["tp"] = sstats["tp"]
        return out

    def close(self, timeout_s: Optional[float] = None) -> None:
        self._closed = True
        self._death_evt.set()  # unpark the supervisor so it exits
        bound = timeout_s if timeout_s is not None else self.close_timeout_s
        sched = self.scheduler
        if sched is not None:
            sched.close(bound)
        self._supervisor.join(timeout=2.0)
        # a rebuild may have landed between the close above and the
        # supervisor noticing _closed; the supervisor's own post-build
        # check handles the still-in-_build case
        sched = self.scheduler
        self.scheduler = None
        if sched is not None:
            sched.close(bound)
        self._set_state("closed")
