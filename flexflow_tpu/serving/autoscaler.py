"""SLO-driven autoscaling of the replicated serving front.

The paper's thesis is that placement decisions should be measured and
costed, not hardcoded; the serving fleet treats its replica count the
same way — a controlled variable driven by the load signals the front
already emits (PR 8), not a static ``--serving-replicas`` knob:

  * **queue depth per live replica** — the admission backlog the
    dispatcher hasn't placed yet, normalized by fleet size;
  * **windowed p99 TTFT** — the user-facing SLO, from the front's
    rolling TTFT window;
  * **KV-pool occupancy** — the capacity signal: a fleet whose pools
    run full queues at admission even when TTFT still looks fine.

Control discipline (the loop must not flap):

  * **hysteresis bands**: scale-up and scale-down thresholds are
    separated (`queue_high` vs `queue_low`, SLO breach vs comfortable
    margin), so a signal oscillating around one threshold cannot
    bounce the fleet;
  * **cooldown**: after any action the loop holds for `cooldown_s`
    before deciding again — a freshly spawned replica needs time to
    absorb load before the signals mean anything;
  * **bounds**: `min_replicas <= fleet <= max_replicas`, the
    ``--serving-min/max-replicas`` contract;
  * **one transition at a time**: while a drain or spin-up is in
    flight, the loop only watches (and bounds a wedged drain with
    `drain_timeout_s` -> `force_retire`, which requeues the stragglers
    onto survivors).

Scale-up spawns through the front's `model_factory` — warm via the
strategy store (docs/STORE.md), so spin-up is compile-cache-bounded,
not search-bounded.  Scale-down picks the least-loaded live replica
and DRAINS it (READY -> DRAINING -> RETIRED, serving/replica.py): the
dispatcher stops routing to it, in-flight slots run to completion
token-identically, then the engine retires and frees its KV pool.

Metrics (obs.metrics, docs/OBSERVABILITY.md "serving/autoscaler_*"):
current/target replica gauges, scale_up/scale_down/hold counters, a
decision event per action, and the drain-duration histogram the
replica emits.  /v2/stats surfaces `stats()` as the "autoscaler"
block.  docs/SERVING.md "Autoscaling & drain lifecycle".
"""
from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from ..logger import resilience_logger


class ServingAutoscaler:
    """Control loop over a ServingFront's load gauges.

    Deterministic core: `observe()` -> signals, `decide(signals)` ->
    (action, reason), `tick()` -> one observe/decide/act cycle.  Tests
    drive `tick()` directly with a fake `time_fn`; production calls
    `start()` for the daemon-thread loop at `interval_s`.
    """

    def __init__(
        self,
        front,
        min_replicas: int = 1,
        max_replicas: int = 4,
        *,
        interval_s: float = 1.0,
        cooldown_s: float = 5.0,
        queue_high: float = 4.0,
        queue_low: float = 0.5,
        slo_ttft_s: float = 0.0,
        kv_high: float = 0.9,
        rebalance_kv: float = 0.0,
        drain_timeout_s: float = 30.0,
        predictive: bool = False,
        predict_horizon_s: float = 10.0,
        slo_per_token_s: float = 0.0,
        history: int = 256,
        registry=None,
        time_fn: Callable[[], float] = time.monotonic,
        logger=resilience_logger,
    ):
        if min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas ({max_replicas}) must be >= "
                f"min_replicas ({min_replicas})")
        if queue_low >= queue_high:
            raise ValueError(
                f"hysteresis band inverted: queue_low ({queue_low}) "
                f"must be < queue_high ({queue_high})")
        if interval_s <= 0:
            raise ValueError(
                f"interval_s must be > 0, got {interval_s}")
        if drain_timeout_s <= 0:
            raise ValueError(
                f"drain_timeout_s must be > 0, got {drain_timeout_s}")
        if not 0.0 <= rebalance_kv < 1.0:
            raise ValueError(
                f"rebalance_kv must be in [0, 1) (occupancy fraction; "
                f"0 disables), got {rebalance_kv}")
        self.front = front
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.interval_s = float(interval_s)
        self.cooldown_s = float(cooldown_s)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.slo_ttft_s = float(slo_ttft_s)
        self.kv_high = float(kv_high)
        # hot-replica rebalance (mid-decode handoff, serving/handoff.py):
        # a live replica whose KV occupancy exceeds this fraction while
        # a peer sits below half of it pauses its longest-remaining
        # generation onto the handoff path.  0 = off; needs the front's
        # handoff flag too.
        self.rebalance_kv = float(rebalance_kv)
        self.drain_timeout_s = float(drain_timeout_s)
        # predictive scaling (--autoscale-predictive): project the
        # admission queue forward from the measured admission-rate
        # slope and scale BEFORE the reactive thresholds breach — a
        # loadgen ramp is visible in the slope several intervals
        # before it is visible in the queue
        self.predictive = bool(predictive)
        self.predict_horizon_s = float(predict_horizon_s)
        # decode-class per-token SLO (role-aware fleets; 0 = off)
        self.slo_per_token_s = float(slo_per_token_s)
        self._admit_samples: "deque[tuple]" = deque(maxlen=8)
        self.registry = registry if registry is not None \
            else front.registry
        self.time_fn = time_fn
        self.log = logger
        self.scale_ups = 0
        self.scale_downs = 0
        self.spawn_failures = 0  # add_replica refusals (chip budget,
        #                          compile errors) observed by tick()
        self.forced_retires = 0
        self.rebalances = 0
        self._last_rebalance_t: Optional[float] = None
        self.ticks = 0
        self.last_action_t: Optional[float] = None
        self.last_decision: Optional[Dict] = None
        self.up_role: Optional[str] = None  # roles fleet: class the
        #                                     next scale-up grows
        self.history: "deque[Dict]" = deque(maxlen=history)
        self._draining = None  # replica with a drain in flight
        self._spawning = False  # a scale-up build (compile) in flight
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        front.autoscaler = self  # /v2/stats picks up the block

    @classmethod
    def from_config(cls, front, cfg, **kw) -> "ServingAutoscaler":
        """Bounds + pacing from the FFConfig serving knobs
        (--serving-min/max-replicas, --autoscale-interval,
        --autoscale-cooldown, --serving-slo-ttft,
        --serving-drain-timeout).  serving_max_replicas=0 means
        autoscaling is OFF (the documented static-fleet contract) —
        building a scaler anyway would drain a --serving-replicas N
        fleet down to min_replicas, so refuse loudly."""
        if cfg.serving_max_replicas <= 0:
            raise ValueError(
                "autoscaling is off (serving_max_replicas=0): set "
                "--serving-max-replicas >= --serving-min-replicas to "
                "enable, or don't build a ServingAutoscaler")
        kw.setdefault("interval_s", cfg.autoscale_interval)
        kw.setdefault("cooldown_s", cfg.autoscale_cooldown)
        kw.setdefault("slo_ttft_s", cfg.serving_slo_ttft)
        kw.setdefault("drain_timeout_s", cfg.serving_drain_timeout)
        kw.setdefault("predictive",
                      getattr(cfg, "autoscale_predictive", False))
        kw.setdefault("rebalance_kv",
                      float(getattr(cfg, "serving_rebalance_kv", 0.0)
                            or 0.0))
        return cls(front, cfg.serving_min_replicas,
                   cfg.serving_max_replicas, **kw)

    # -- signals ---------------------------------------------------------
    def observe(self) -> Dict:
        """One sample of the control inputs, from gauges the front and
        schedulers already maintain — observing never blocks decode."""
        front = self.front
        with front._cv:
            replicas = list(front.replicas)
            queue_depth = len(front._admission)
            admitted = int(getattr(front, "requests_admitted", 0))
        live = [r for r in replicas if r.alive]
        draining = [r for r in replicas if r.state == "draining"]
        # restarting replicas come back live after their rebuild, so
        # they count against max_replicas (permanently-dead ones hold
        # no engine and never return — they don't)
        restarting = [r for r in replicas if r.state == "restarting"]
        outstanding = sum(r.outstanding for r in live)
        # disaggregated fleets (serving/disagg.py) scale the two
        # classes on their OWN signals: KV occupancy is a DECODE-class
        # signal there (the prefill pool recycles per pass and its
        # occupancy says nothing about serving capacity)
        roles_active = any(r.role != "mixed" for r in replicas)
        occ = 0.0
        for r in live:
            sched = r.scheduler
            if sched is None or (roles_active and r.role == "prefill"):
                continue
            try:
                occ = max(occ, sched.pool.occupancy())
            except Exception:  # noqa: BLE001 — a dying replica's
                pass           # pool must not kill the loop
        ttft = front.ttft_stats()  # percentile_summary keys, in ms
        t = self.time_fn()
        s = {
            "t": t,
            "live": len(live),
            "draining": len(draining),
            "restarting": len(restarting),
            "fleet": len(replicas),
            "queue_depth": queue_depth,
            "outstanding": outstanding,
            "queue_per_replica": queue_depth / max(len(live), 1),
            "p99_ttft_s": (ttft.get("p99_ms", 0.0) or 0.0) / 1e3,
            "kv_occupancy": occ,
            "roles_active": roles_active,
        }
        if roles_active:
            s["prefill_live"] = sum(1 for r in live
                                    if r.role == "prefill")
            s["decode_live"] = sum(1 for r in live
                                   if r.role != "prefill")
            tok = None
            with front._lat_lock:
                samples = sorted(front._class_tok.get("decode", ()))
            if len(samples) >= 3:  # nearest-rank p99
                tok = samples[min(len(samples) - 1,
                                  math.ceil(0.99 * len(samples)) - 1)]
            s["decode_per_token_s"] = tok
            s["decode_rate_rps"] = front.service_rate("decode")
        # admission-rate slope (predictive scaling): completions/s the
        # queue is FILLING at, measured over the sample window
        self._admit_samples.append((t, admitted))
        rate = None
        if len(self._admit_samples) >= 2:
            (t0, a0), (t1, a1) = (self._admit_samples[0],
                                  self._admit_samples[-1])
            if t1 > t0:
                rate = (a1 - a0) / (t1 - t0)
        s["admit_rate_rps"] = rate
        # the measured drain rate the projection subtracts: the decode
        # class's own window in a roles fleet, the fleet's otherwise
        drain_rate = (s.get("decode_rate_rps") if roles_active
                      else front.service_rate())
        s["drain_rate_rps"] = drain_rate
        return s

    # -- policy ----------------------------------------------------------
    def decide(self, s: Dict) -> tuple:
        """(action, reason) for one signal sample.  Pure policy over
        the sample (directly unit-testable); in a roles fleet it also
        records WHICH class a scale-up targets (self.up_role — queue/
        TTFT breaches grow the prefill class, KV-occupancy/per-token
        breaches grow decode), which tick() passes to add_replica."""
        self.up_role = None
        if self._draining is not None:
            return "hold", "drain in flight"
        if (self.last_action_t is not None
                and s["t"] - self.last_action_t < self.cooldown_s):
            return "hold", "cooldown"
        if s["live"] == 0:
            # replica supervision (restarts) owns total outages; the
            # autoscaler only sizes a serving fleet
            return "hold", "no live replicas"
        committed = s["live"] + s["draining"] + s.get("restarting", 0)
        if committed < self.min_replicas:
            # a permanently-dead replica leaves the fleet below its
            # contracted floor with NO load signal to restore it —
            # min_replicas is a bound, not a suggestion
            return "up", (f"fleet {committed} < "
                          f"min_replicas={self.min_replicas}")
        # the TTFT window is count-based (last N completions), so with
        # NO traffic it never refreshes: a past burst's p99 would pin
        # an idle fleet at max forever (and block its drain).  Gate the
        # TTFT signal on actual load — an idle fleet breaches no SLO.
        busy = s["queue_depth"] + s["outstanding"] > 0
        roles = bool(s.get("roles_active"))
        # ingest-side breaches (grow the PREFILL class in a roles
        # fleet: the queue backs up when prompts wait for a pass)
        ingest_reasons = []
        # capacity-side breaches (grow the DECODE class: its pools and
        # per-token pace bound how many streams the fleet sustains)
        capacity_reasons = []
        if s["queue_per_replica"] > self.queue_high:
            ingest_reasons.append(
                f"queue/replica {s['queue_per_replica']:.1f} > "
                f"{self.queue_high:.1f}")
        if (self.slo_ttft_s > 0 and busy
                and s["p99_ttft_s"] > self.slo_ttft_s):
            ingest_reasons.append(
                f"p99 TTFT {s['p99_ttft_s'] * 1e3:.0f}ms > SLO "
                f"{self.slo_ttft_s * 1e3:.0f}ms")
        if (self.predictive and s.get("admit_rate_rps") is not None):
            # loadgen ramp: the admission-rate slope projects a queue
            # breach before the reactive threshold sees it
            drain = s.get("drain_rate_rps") or 0.0
            growth = s["admit_rate_rps"] - drain
            if growth > 0:
                projected = (s["queue_depth"]
                             + growth * self.predict_horizon_s
                             ) / max(s["live"], 1)
                if projected > self.queue_high:
                    ingest_reasons.append(
                        f"projected queue/replica {projected:.1f} > "
                        f"{self.queue_high:.1f} within "
                        f"{self.predict_horizon_s:.0f}s (admit "
                        f"{s['admit_rate_rps']:.2f}/s vs drain "
                        f"{drain:.2f}/s)")
        if s["kv_occupancy"] > self.kv_high:
            capacity_reasons.append(
                f"KV occupancy {s['kv_occupancy']:.2f} > "
                f"{self.kv_high:.2f}")
        tok = s.get("decode_per_token_s")
        if (roles and self.slo_per_token_s > 0 and busy
                and tok is not None and tok > self.slo_per_token_s):
            capacity_reasons.append(
                f"decode p99 per-token {tok * 1e3:.0f}ms > SLO "
                f"{self.slo_per_token_s * 1e3:.0f}ms")
        up_reasons = ingest_reasons + capacity_reasons
        if up_reasons:
            if roles:
                # capacity first: a decode class out of KV headroom
                # queues admissions no matter how fast prefill runs
                self.up_role = ("decode" if capacity_reasons
                                else "prefill")
            max_fleet = self._max_fleet()
            if committed >= max_fleet:
                cap = (f"chip budget "
                       f"{getattr(self.front, 'chip_budget', 0)} caps "
                       f"the fleet at {max_fleet}"
                       if max_fleet < self.max_replicas
                       else f"at max_replicas={self.max_replicas}")
                return "hold", f"{cap} ({'; '.join(up_reasons)})"
            return "up", "; ".join(up_reasons)
        # scale-down wants EVERY signal comfortable (hysteresis: the
        # down band sits well below the up band)
        calm = (
            s["queue_per_replica"] < self.queue_low
            and (self.slo_ttft_s <= 0 or not busy
                 or s["p99_ttft_s"] < 0.5 * self.slo_ttft_s)
            and s["kv_occupancy"] < 0.5 * self.kv_high
        )
        if calm and s["live"] > self.min_replicas:
            return "down", (
                f"queue/replica {s['queue_per_replica']:.1f} < "
                f"{self.queue_low:.1f} and SLO margin ample")
        return "hold", "within bands"

    def _max_fleet(self) -> int:
        """max_replicas, further capped by the front's chip budget:
        each replica spans chips_per_replica chips (its tensor-parallel
        degree), so a budget of B chips holds at most B // tp engines
        regardless of what --serving-max-replicas allows."""
        budget = int(getattr(self.front, "chip_budget", 0) or 0)
        if not budget:
            return self.max_replicas
        per = max(1, int(getattr(self.front, "chips_per_replica", 1)))
        return min(self.max_replicas, budget // per)

    # -- actuation -------------------------------------------------------
    def _pick_drain_target(self):
        """Least-loaded live replica — the cheapest one to retire.  In
        a roles fleet, never the last decode-capable one (a healthy
        prefill class cannot serve a single client request); with the
        decode class at its floor, an idle prefill replica drains
        instead (the fleet degrades to colocated re-prefill)."""
        live = self.front._live()
        if len(live) <= self.min_replicas:
            return None
        if any(r.role != "mixed" for r in live):
            serving = [r for r in live if r.role != "prefill"]
            if len(serving) <= 1:
                live = [r for r in live if r.role == "prefill"]
                if not live:
                    return None
        return min(live, key=lambda r: r.outstanding)

    def _record(self, action: str, reason: str, s: Dict) -> None:
        entry = {
            "t": s["t"],
            "action": action,
            "reason": reason,
            "replicas": s["fleet"],
            "live": s["live"],
            "queue_depth": s["queue_depth"],
            "p99_ttft_s": round(s["p99_ttft_s"], 4),
            "kv_occupancy": round(s["kv_occupancy"], 4),
        }
        if action == "up" and self.up_role is not None:
            entry["role"] = self.up_role
        self.history.append(entry)
        if action != "hold":
            self.last_decision = entry
            self.last_action_t = s["t"]
            self.log.info("autoscaler %s (fleet %d): %s",
                          action, s["fleet"], reason)
        if self.registry is not None:
            reg = self.registry
            reg.gauge("serving/autoscaler_replicas").set(s["fleet"])
            # the target this TICK decided — not target_replicas(),
            # which would re-run decide() AFTER last_action_t/_draining
            # were updated and always report the pre-action size
            cur = (s["live"] + s["draining"]
                   + s.get("restarting", 0))
            reg.gauge("serving/autoscaler_target").set(
                self._target_for(action, cur))
            reg.counter(f"serving/autoscaler_{action}").inc()
            if action != "hold":
                reg.event("serving/autoscaler_decision", **entry)

    def _target_for(self, action: str, cur: int) -> int:
        if action == "up":
            return min(cur + 1, self.max_replicas)
        if action == "down":
            return max(cur - 1, self.min_replicas)
        return max(min(cur, self.max_replicas), self.min_replicas)

    def target_replicas(self, s: Optional[Dict] = None) -> int:
        """The fleet size the policy is steering toward right now."""
        if s is None:
            s = self.observe()
        action, _ = self.decide(s)
        cur = s["live"] + s["draining"] + s.get("restarting", 0)
        return self._target_for(action, cur)

    def tick(self) -> Dict:
        """One control cycle: observe -> decide -> act.  Returns the
        history entry (action + signals) for this cycle."""
        self.ticks += 1
        self._sweep_drain()
        s = self.observe()
        self._maybe_rebalance(s)
        action, reason = self.decide(s)
        if action == "up":
            self._spawning = True  # visible while the build compiles
            try:
                self.front.add_replica(role=self.up_role or "mixed")
                self.scale_ups += 1
            except Exception as e:  # noqa: BLE001 — a failed spawn
                action, reason = "hold", f"spawn failed: {e}"
                self.spawn_failures += 1
                # _record only logs non-hold actions and only they set
                # the cooldown: without both, a persistent build
                # failure retries a full compile every tick, silently
                self.log.info("autoscaler scale-up failed: %s", e)
                self.last_action_t = s["t"]
                if self.registry is not None:
                    self.registry.counter(
                        "serving/autoscaler_spawn_failed").inc()
            finally:
                self._spawning = False
        elif action == "down":
            target = self._pick_drain_target()
            if target is not None and self.front.drain_replica(target):
                self._draining = (target, s["t"])
                self.scale_downs += 1
            else:
                action, reason = "hold", "no drainable replica"
        self._record(action, reason, s)
        return self.history[-1]

    def _maybe_rebalance(self, s: Dict) -> None:
        """KV-occupancy rebalance trigger (mid-decode handoff): when a
        live decode-capable replica's pool runs past `rebalance_kv`
        while a peer sits below half of it, pause the hot replica's
        longest-remaining generation onto the handoff path so it
        resumes on the cool one.  Its own cooldown (shared constant,
        separate clock) keeps one hot pool from shedding a sequence
        every tick."""
        if self.rebalance_kv <= 0:
            return
        front = self.front
        if not getattr(front, "handoff", False):
            return
        t = s["t"]
        if (self._last_rebalance_t is not None
                and t - self._last_rebalance_t < self.cooldown_s):
            return
        hot = cool = None
        for r in front._live():
            sched = r.scheduler
            if sched is None or r.role == "prefill":
                continue
            try:
                occ = sched.pool.occupancy()
            except Exception:  # noqa: BLE001 — a dying replica's pool
                continue       # must not kill the loop
            if occ > self.rebalance_kv and (hot is None
                                            or occ > hot[1]):
                hot = (r, occ)
            if occ < 0.5 * self.rebalance_kv and (cool is None
                                                  or occ < cool[1]):
                cool = (r, occ)
        if hot is None or cool is None or hot[0] is cool[0]:
            return
        if front.rebalance_replica(hot[0], max_sequences=1):
            self.rebalances += 1
            self._last_rebalance_t = t
            self.log.info(
                "autoscaler rebalance: replica %d KV occupancy %.2f > "
                "%.2f (coolest peer %.2f) — pausing 1 sequence for "
                "handoff", hot[0].replica_id, hot[1],
                self.rebalance_kv, cool[1])

    def _sweep_drain(self) -> None:
        """Resolve an in-flight drain: done, or wedged past the
        deadline -> bounded force_retire (in-flight requests requeue
        onto survivors through the front's settle hooks)."""
        if self._draining is None:
            return
        replica, t0 = self._draining
        if replica.state in ("retired", "dead", "closed"):
            self._draining = None
            return
        if self.time_fn() - t0 > self.drain_timeout_s:
            self.log.info(
                "autoscaler: drain of replica %d wedged past %.1fs — "
                "forcing retirement", replica.replica_id,
                self.drain_timeout_s)
            self.forced_retires += 1
            if self.registry is not None:
                self.registry.counter(
                    "serving/autoscaler_forced_retire").inc()
            replica.force_retire()
            self._draining = None

    # -- loop ------------------------------------------------------------
    def start(self) -> "ServingAutoscaler":
        """Run tick() every interval_s on a daemon thread."""
        if self._thread is not None:
            return self
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="serving-autoscaler")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the control loop must
                # outlive any single bad cycle (a dying replica's race
                # is the replica supervisor's problem, not ours)
                self.log.exception("autoscaler tick failed")

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None

    # -- surfaces --------------------------------------------------------
    def stats(self) -> Dict:
        """The /v2/stats "autoscaler" block."""
        front = self.front
        with front._cv:
            current = len(front.replicas)
            meshes = [
                {"id": r.replica_id,
                 "mesh_shape": dict(getattr(
                     getattr(r.scheduler, "model", None),
                     "mesh_shape", None) or {})}
                for r in front.replicas if r.scheduler is not None
            ]
        # single read: the loop thread clears _draining concurrently
        draining = self._draining
        per = max(1, int(getattr(front, "chips_per_replica", 1)))
        return {
            "current_replicas": current,
            "target_replicas": self.target_replicas(),
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "max_fleet": self._max_fleet(),
            "chips_per_replica": per,
            "chip_budget": int(getattr(front, "chip_budget", 0) or 0),
            "fleet_chips": current * per,
            "replica_meshes": meshes,
            "predictive": self.predictive,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "spawn_failures": self.spawn_failures,
            "forced_retires": self.forced_retires,
            "rebalances": self.rebalances,
            "rebalance_kv": self.rebalance_kv,
            "ticks": self.ticks,
            "drain_in_flight": (draining[0].replica_id
                                if draining is not None else None),
            "spawn_in_flight": self._spawning,
            "last_decision": self.last_decision,
        }
