"""Poisson open-loop load generator for the generation serving tier.

Open-loop means arrivals are scheduled by the CLOCK, not by
completions: requests are submitted at exponential inter-arrival gaps
(rate_rps) regardless of how far behind the server is, so queueing
delay shows up in the measured latencies instead of being hidden by a
closed loop's self-throttling — the methodology every serving paper
(Orca, vLLM) benches with.  Drives anything with the batcher contract
(`generate_async(prompt, max_new_tokens, temperature)` returning a
handle with `.wait(timeout)`), i.e. both GenerationBatcher (static)
and ContinuousScheduler (continuous), so bench.py compares the two on
identical arrival sequences (same seed -> same prompts, same gaps).

Reported SLOs:
  * TTFT: submit -> first generated token.  Continuous handles stamp
    `t_first_token` when the token is sampled; static handles deliver
    everything at completion, so TTFT degrades to completion time —
    which is exactly the static tier's real time-to-first-token.
  * per-token latency: generation time per token after the first.
  * sustained tokens/s: generated tokens / makespan.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, Optional

import numpy as np

from .batcher import percentile_summary


def _summary(vals) -> Dict[str, float]:
    return percentile_summary(vals, ps=(0.50, 0.99))


def sample_workload(rng: np.random.RandomState, n_requests: int,
                    vocab_size: int, prompt_len_range=(2, 12),
                    max_new_range=(2, 24), long_frac: float = 0.0,
                    long_max_new_range=(40, 56)):
    """A mixed-length workload: (prompt, max_new_tokens) pairs with
    uniform lengths — the heterogeneity that strands static batches.

    long_frac > 0 makes the reply lengths HEAVY-TAILED (the canonical
    serving distribution: most replies short, a tail of long ones):
    that fraction of requests draws max_new from long_max_new_range
    instead.  One long request in a static batch pads every short
    neighbor to its bucket; the continuous tier retires the short ones
    at their own length."""
    reqs = []
    for _ in range(n_requests):
        plen = int(rng.randint(prompt_len_range[0],
                               prompt_len_range[1] + 1))
        lo, hi = (long_max_new_range
                  if long_frac > 0.0 and rng.random_sample() < long_frac
                  else max_new_range)
        mnt = int(rng.randint(lo, hi + 1))
        prompt = rng.randint(0, vocab_size, plen).tolist()
        reqs.append((prompt, mnt))
    return reqs


def sample_shared_prefix_workload(rng: np.random.RandomState,
                                  n_requests: int, vocab_size: int,
                                  num_prefixes: int = 4,
                                  prefix_len: int = 32,
                                  tail_range=(1, 8),
                                  max_new_range=(2, 12)):
    """Seeded prefix-heavy workload: every request draws one of
    `num_prefixes` shared system prompts (prefix_len tokens) and
    appends a per-request UNIQUE tail — the system-prompt / few-shot
    template shape the KV prefix cache exists for.  Same seed -> same
    prefix pool, same request list, so a bench run and its baseline
    see byte-identical traffic.  Returns (requests, prefixes)."""
    if num_prefixes < 1:
        raise ValueError(f"num_prefixes must be >= 1, got {num_prefixes}")
    if prefix_len < 1:
        raise ValueError(f"prefix_len must be >= 1, got {prefix_len}")
    prefixes = [rng.randint(0, vocab_size, prefix_len).tolist()
                for _ in range(num_prefixes)]
    reqs = []
    for _ in range(n_requests):
        prefix = prefixes[int(rng.randint(num_prefixes))]
        tail = rng.randint(
            0, vocab_size,
            int(rng.randint(tail_range[0], tail_range[1] + 1))).tolist()
        mnt = int(rng.randint(max_new_range[0], max_new_range[1] + 1))
        reqs.append((prefix + tail, mnt))
    return reqs, prefixes


def sample_repetitive_workload(rng: np.random.RandomState,
                               n_requests: int, vocab_size: int,
                               num_templates: int = 4,
                               phrase_len: int = 6,
                               phrases_per_template: int = 3,
                               prompt_phrases_range=(3, 6),
                               max_new_range=(8, 24)):
    """Seeded workload with HIGH n-gram self-overlap: each prompt is a
    concatenation of phrases drawn from a tiny per-template phrase pool,
    so the same `phrase_len`-grams recur many times inside one prompt.
    That is the shape prompt-lookup speculative decoding feeds on — a
    model trained on this distribution keeps emitting token runs that
    already appear earlier in the request's own context, so the n-gram
    proposer's drafts keep getting accepted.  Same seed -> same phrase
    pools, same request list (the bench-vs-baseline replay contract).
    Returns (requests, templates) where templates[i] is the phrase pool
    request i drew from."""
    if num_templates < 1:
        raise ValueError(f"num_templates must be >= 1, got {num_templates}")
    if phrase_len < 2:
        raise ValueError(f"phrase_len must be >= 2, got {phrase_len}")
    if phrases_per_template < 1:
        raise ValueError(
            f"phrases_per_template must be >= 1, got {phrases_per_template}")
    pools = [[rng.randint(0, vocab_size, phrase_len).tolist()
              for _ in range(phrases_per_template)]
             for _ in range(num_templates)]
    reqs = []
    templates = []
    for _ in range(n_requests):
        t = int(rng.randint(num_templates))
        pool = pools[t]
        n_phrases = int(rng.randint(prompt_phrases_range[0],
                                    prompt_phrases_range[1] + 1))
        prompt = []
        for _ in range(n_phrases):
            prompt.extend(pool[int(rng.randint(len(pool)))])
        mnt = int(rng.randint(max_new_range[0], max_new_range[1] + 1))
        reqs.append((prompt, mnt))
        templates.append(t)
    return reqs, templates


def arrival_gaps(rng: np.random.RandomState, n: int, rate_rps: float,
                 pattern: str = "poisson", *,
                 ramp_to: Optional[float] = None,
                 burst_factor: float = 4.0,
                 period_s: float = 2.0) -> np.ndarray:
    """Seeded, replayable inter-arrival gaps for `n` requests — the
    same (seed, pattern, params) always yields the same trace, so a
    bench run and its baseline see identical load.

    Patterns (all open-loop: arrivals are exponential around a
    time-varying rate, scheduled by the clock, never by completions):

      * ``poisson`` — constant `rate_rps` (the PR 6 default);
      * ``ramp`` — rate climbs linearly from `rate_rps` to `ramp_to`
        (default 4x) across the trace: the steady-growth shape that
        should trigger exactly one scale-up wave, no flapping;
      * ``square`` — square-wave bursts: rate alternates between
        `rate_rps` and `rate_rps * burst_factor` every `period_s`
        seconds of generated load, the surge/calm cycle an autoscaler
        must follow up AND back down.
    """
    if n <= 0:
        return np.zeros(0)
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    if pattern == "poisson":
        return rng.exponential(1.0 / rate_rps, size=n)
    if pattern == "ramp":
        hi = float(ramp_to) if ramp_to is not None else 4.0 * rate_rps
        rates = np.linspace(rate_rps, hi, n)
        return rng.exponential(1.0, size=n) / rates
    if pattern == "square":
        if burst_factor <= 0:
            raise ValueError(
                f"burst_factor must be > 0, got {burst_factor}")
        if period_s <= 0:
            raise ValueError(f"period_s must be > 0, got {period_s}")
        gaps = np.empty(n)
        t = 0.0
        for i in range(n):
            phase = int(t / period_s) % 2  # 0 = calm, 1 = burst
            rate = rate_rps * (burst_factor if phase else 1.0)
            gaps[i] = rng.exponential(1.0 / rate)
            t += gaps[i]
        return gaps
    raise ValueError(
        f"arrival pattern must be poisson | ramp | square, "
        f"got {pattern!r}")


def run_loadgen(batcher, requests, rate_rps: float, seed: int = 0,
                temperature: float = 0.0, timeout_s: float = 120.0,
                on_submit: Optional[Callable] = None,
                detail: bool = False, record_tokens: bool = False,
                arrival: str = "poisson",
                ramp_to: Optional[float] = None,
                burst_factor: float = 4.0,
                period_s: float = 2.0) -> Dict:
    """Fire `requests` [(prompt, max_new_tokens), ...] at seeded
    open-loop arrivals (`arrival` = poisson | ramp | square, see
    arrival_gaps), wait for completion, report SLOs.

    Failed/timed-out requests are counted, excluded from latency
    summaries, and never crash the run (the server keeps them going;
    the loadgen just stops waiting).

    detail=True adds per-request `records` (submit_s relative to the
    run start, ok, ttft_s, done_s, and queue_depth_at_admit when the
    handle carries it — the front stamps its backlog at admission)
    covering failures too — the serving_resilience and autoscale bench
    legs bucket these around fault/burst windows."""
    rng = np.random.RandomState(seed)
    gaps = arrival_gaps(rng, len(requests), rate_rps, arrival,
                        ramp_to=ramp_to, burst_factor=burst_factor,
                        period_s=period_s)
    t0 = time.monotonic()
    next_at = t0
    handles = []
    results = []
    records = []
    failures = 0
    for idx, ((prompt, mnt), gap) in enumerate(zip(requests, gaps)):
        next_at += gap
        delay = next_at - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        try:
            h = batcher.generate_async(prompt, mnt, temperature)
        except Exception:
            # refused at admission (a replicated front sheds with 503
            # + Retry-After while zero replicas are live): a failure
            # for the report, never a crash — open-loop arrivals keep
            # firing at the clock
            failures += 1
            records.append({
                "idx": idx,
                "submit_s": round(time.monotonic() - t0, 4),
                "ok": False, "rejected": True,
            })
            continue
        handles.append((h, idx, len(prompt), mnt))
        if on_submit is not None:
            on_submit(h)
    # ONE deadline across all waits (the server.py /v2/generate
    # convention): a wedged engine costs ~timeout_s total, not
    # timeout_s per outstanding handle
    wait_deadline = time.monotonic() + timeout_s
    for h, idx, plen, mnt in handles:
        depth = getattr(h, "queue_depth_at_admit", None)
        try:
            toks = h.wait(max(0.0, wait_deadline - time.monotonic()))
        except Exception:
            failures += 1
            rec = {"idx": idx, "submit_s": round(h.t_submit - t0, 4),
                   "ok": False}
            if depth is not None:
                rec["queue_depth_at_admit"] = depth
            tr = getattr(h, "trace", None)
            if tr is not None:
                rec["trace_id"] = tr.trace_id
            records.append(rec)
            continue
        # every handle flavor stamps t_submit at generate_async time —
        # the loadgen's submit clock.  t_done/t_first_token exist only
        # on continuous handles; static handles deliver everything at
        # completion, so both degrade to the wait-return time.
        t_submit = h.t_submit
        t_done = getattr(h, "t_done", None) or time.monotonic()
        n_gen = getattr(h, "n_generated", 0) or max(
            0, len(toks) - plen)
        t_first = getattr(h, "t_first_token", None) or t_done
        results.append({
            "submit": t_submit,
            "ttft_s": t_first - t_submit,
            "done": t_done,
            "n_generated": n_gen,
            "gen_s": t_done - t_first,
        })
        rec = {"idx": idx, "submit_s": round(t_submit - t0, 4),
               "ok": True, "ttft_s": round(t_first - t_submit, 4),
               "done_s": round(t_done - t0, 4)}
        if depth is not None:
            rec["queue_depth_at_admit"] = depth
        hit = getattr(h, "prefix_hit_tokens", None)
        if hit is not None:
            # prompt tokens the KV prefix cache served (zero prefill
            # steps) — the serving_prefix bench leg buckets on these
            rec["prefix_hit_tokens"] = int(hit)
        tr = getattr(h, "trace", None)
        if tr is not None:
            # joins this record to its span tree in run_telemetry.jsonl
            # / trace.json (tools/trace_analyze.py keys on trace_id)
            rec["trace_id"] = tr.trace_id
        sd = getattr(h, "seed", None)
        if sd is not None:
            # the per-request sampling seed (front-minted): with it, a
            # temperature>0 completion in this record is replayable —
            # the resume path (serving/handoff.py) depends on exactly
            # this determinism
            rec["seed"] = int(sd)
        prop = getattr(h, "spec_proposed", None)
        if prop is not None:
            # draft tokens this request put through verification and
            # how many the target accepted — the serving_spec bench
            # leg derives per-request accept rates from these
            rec["spec_proposed"] = int(prop)
            rec["spec_accepted"] = int(
                getattr(h, "spec_accepted", 0) or 0)
        if record_tokens:
            # token-identity audits (the autoscale leg proves zero
            # requests were corrupted by a drain) need the completions
            rec["tokens"] = [int(t) for t in toks]
        records.append(rec)
    report = {
        "offered_rps": rate_rps,
        "arrival": arrival,
        "requests": len(requests),
        "completed": len(results),
        "failures": failures,
    }
    if detail:
        report["records"] = records
    if results:
        makespan = max(r["done"] for r in results) - t0
        total_tokens = sum(r["n_generated"] for r in results)
        per_token = [
            r["gen_s"] / (r["n_generated"] - 1)
            for r in results if r["n_generated"] > 1 and r["gen_s"] > 0
        ]
        report.update({
            "makespan_s": round(makespan, 3),
            "tokens_generated": total_tokens,
            "tokens_per_s": round(total_tokens / max(makespan, 1e-9), 2),
            "ttft": _summary([r["ttft_s"] for r in results]),
            "per_token": _summary(per_token),
        })
    return report
