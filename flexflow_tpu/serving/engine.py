"""Inference engine: static-strategy compiled forward with batch
buckets (reference triton/src: ONNX parse -> static LayerStrategy ->
Legion inference; here ONNX/torch/Keras all funnel through FFModel and
the engine jits its forward per power-of-two batch bucket)."""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..fftype import CompMode
from ..logger import serving_logger
from ..model import FFModel


def resolve_paged_formulation(paged_kernel: str, *,
                              logger=serving_logger) -> str:
    """Engine-build gate for the paged-attention read formulation
    (docs/SERVING.md "Fused paged attention"): validates the flag and
    the runtime (selecting the Pallas kernel on a pallas-less jax
    raises config.ConfigError HERE, at build time, never a deep
    ImportError mid-compile) and logs which formulation the engine
    will run — the operator-visible record of what the hot path is."""
    from ..config import resolve_paged_kernel

    kernel = resolve_paged_kernel(paged_kernel)
    logger.info(
        "paged attention formulation: %s (%s)", kernel,
        "fused Pallas kernel, block reads in place"
        if kernel == "pallas"
        else "dense block-gather, the bit-identity oracle")
    return kernel


def _value_info_shape(vi):
    """Static dims (None for symbolic) from a graph input, covering both
    the vendored protowire.ValueInfo and onnx's ValueInfoProto."""
    shape = getattr(vi, "shape", None)
    if shape is not None or not hasattr(vi, "type"):
        return shape
    dims = []
    for d in vi.type.tensor_type.shape.dim:
        dims.append(d.dim_value if d.dim_value > 0 else None)
    return dims or None


def _bucket(n: int, max_batch: int, multiple: int = 1) -> int:
    """Next power of two >= n, rounded up to `multiple` (the mesh's
    data-axis size — every bucket must shard evenly).  The cap is the
    largest multiple of `multiple` <= max_batch (at least `multiple`),
    so the invariant holds even when max_batch itself doesn't divide."""
    cap = max((max_batch // multiple) * multiple, multiple)
    b = 1
    while b < n:
        b <<= 1
    if b % multiple:
        b = ((b + multiple - 1) // multiple) * multiple
    return min(max(b, multiple), cap)


class InferenceEngine:
    """Wraps a compiled FFModel for inference: pads requests to the
    next power-of-two bucket, runs the jitted forward, strips padding.

    `from_onnx` mirrors the Triton backend's model source; any FFModel
    (hand-built, torch.fx- or Keras-imported) works via `__init__`.
    """

    def __init__(self, ff: FFModel, max_batch: int = 64):
        if ff.executor is None:
            raise ValueError("compile() the model before serving it")
        self.ff = ff
        self.max_batch = max_batch
        self._fwd = ff.executor.build_forward()
        self._input_names = [op.name for op in ff.layers.source_ops()]
        self.requests_served = 0

    @classmethod
    def from_onnx(cls, path: str, batch_size: int = 64, devices=None,
                  strategy=None, **kwargs) -> "InferenceEngine":
        from ..config import FFConfig
        from ..onnx_frontend.model import ONNXModel

        cfg = FFConfig(batch_size=batch_size)
        ff = FFModel(cfg)
        om = ONNXModel(path)
        tensors = []
        for vi in om.graph.input:
            if vi.name in om.initializers:
                continue
            shape = _value_info_shape(vi)
            if not shape or any(d is None for d in shape[1:]):
                raise ValueError(
                    f"ONNX input {vi.name!r} needs a static shape to "
                    f"serve (got {shape}); re-export with fixed dims"
                )
            tensors.append(
                ff.create_tensor([batch_size] + [int(d) for d in shape[1:]],
                                 name=vi.name)
            )
        om.apply(ff, tensors)
        ff.compile(comp_mode=CompMode.INFERENCE, strategy=strategy,
                   devices=devices)
        om.copy_weights(ff)
        return cls(ff, max_batch=batch_size, **kwargs)

    def chunk_cap(self) -> int:
        """Largest request slice one jitted forward takes: max_batch
        rounded down to the mesh's data-axis multiple (single source of
        the sharding invariant for infer() and the DynamicBatcher)."""
        dp = self.ff.mesh.shape.get("data", 1) if self.ff.mesh else 1
        return max((self.max_batch // dp) * dp, dp)

    # ------------------------------------------------------------------
    def infer(self, inputs: Dict[str, np.ndarray]) -> np.ndarray:
        """One batch (any size <= max_batch * k — larger requests are
        chunked); returns the sink output as numpy."""
        n = len(next(iter(inputs.values())))
        chunk_cap = self.chunk_cap()
        outs: List[np.ndarray] = []
        start = 0
        while start < n:
            take = min(chunk_cap, n - start)
            chunk = {k: v[start:start + take] for k, v in inputs.items()}
            outs.append(self._infer_bucketed(chunk, take))
            start += take
        self.requests_served += 1
        return np.concatenate(outs) if len(outs) > 1 else outs[0]

    def _infer_bucketed(self, chunk: Dict[str, np.ndarray], n: int) -> np.ndarray:
        return np.asarray(self.dispatch(chunk, n))[:n]

    def dispatch(self, chunk: Dict[str, np.ndarray], n: int):
        """ASYNC half of a bucketed forward: pad to the bucket, device_put,
        launch the jitted forward, and return the device array WITHOUT
        waiting — jax dispatch is asynchronous, so the caller can overlap
        assembling the next batch with this one's device time (the
        DynamicBatcher's pipeline).  `np.asarray(result)[:n]` completes it."""
        import jax

        dp = self.ff.mesh.shape.get("data", 1) if self.ff.mesh else 1
        b = _bucket(n, self.max_batch, multiple=dp)
        padded = {}
        for k, v in chunk.items():
            if len(v) < b:
                pad = np.zeros((b - len(v),) + v.shape[1:], v.dtype)
                v = np.concatenate([v, pad])
            padded[k] = v
        sh = self.ff.executor.input_shardings()
        put = {k: jax.device_put(v, sh[k]) for k, v in padded.items()}
        return self._fwd(self.ff._weights, self.ff._state, put)

    def input_names(self) -> Sequence[str]:
        return list(self._input_names)

    def input_specs(self) -> Dict[str, np.dtype]:
        """name -> numpy dtype of each model input (from the compiled
        tensor specs, so HTTP payloads need not guess)."""
        return {
            op.name: op.outputs[0].shape.dtype.np_dtype  # jnp: knows bf16
            for op in self.ff.layers.source_ops()
        }
