"""Minimal HTTP JSON inference endpoint (stdlib-only; the Triton
backend's HTTP surface analogue).

POST /v2/infer     {"inputs": {name: nested-list, ...}} -> {"outputs": [...]}
POST /v2/generate  {"prompt": [ids...]} or {"prompts": [[ids...], ...]},
                   optional "max_new_tokens" (int), "temperature" (float)
                   -> {"tokens": [[ids...], ...]}   (requires a
                   GenerationBatcher via serve_http(generator=...))
GET  /v2/health    -> {"status": "ok", "requests": N}
GET  /v2/stats     -> batch/request counters + latency percentiles
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np


def serve_http(batcher=None, host: str = "127.0.0.1", port: int = 8000,
               block: bool = True, generator=None):
    """Serve a DynamicBatcher (or bare InferenceEngine) and/or a
    GenerationBatcher over HTTP.  Returns the server object; when
    block=False it runs on a daemon thread (server.shutdown() stops
    it)."""
    if batcher is None and generator is None:
        raise ValueError("serve_http needs a batcher and/or a generator")

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send(self, code: int, payload: dict):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            src = batcher if batcher is not None else generator
            if self.path == "/v2/health":
                served = getattr(src, "batches_run",
                                 getattr(src, "requests_served", 0))
                self._send(200, {"status": "ok", "requests": served})
            elif self.path == "/v2/stats":
                stats = {
                    "batches_run": getattr(src, "batches_run", 0),
                    "requests_done": getattr(src, "requests_done", 0),
                }
                if hasattr(src, "latency_stats"):
                    stats["latency"] = src.latency_stats()
                if generator is not None and src is not generator:
                    stats["generate"] = {
                        "batches_run": generator.batches_run,
                        "requests_done": generator.requests_done,
                        "latency": generator.latency_stats(),
                    }
                self._send(200, stats)
            else:
                self._send(404, {"error": "not found"})

        def do_POST(self):
            try:
                length = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(length) or b"{}")
                if self.path == "/v2/infer" and batcher is not None:
                    specs = _input_specs(batcher)
                    inputs = {}
                    for k, v in req["inputs"].items():
                        if k in specs:
                            dt = specs[k]  # model-declared dtype wins
                        else:
                            dt = np.int32 if _is_int(v) else np.float32
                        inputs[k] = np.asarray(v, dtype=dt)
                    out = batcher.infer(inputs)
                    self._send(200, {"outputs": np.asarray(out).tolist()})
                elif self.path == "/v2/generate" and generator is not None:
                    prompts = req.get("prompts")
                    if prompts is None:
                        prompts = [req["prompt"]]
                    mnt = int(req.get("max_new_tokens", 16))
                    temp = float(req.get("temperature", 0.0))
                    handles = [
                        generator.generate_async(p, mnt, temp)
                        for p in prompts
                    ]  # rows of one POST coalesce into one scan
                    toks = [h.wait(120.0) for h in handles]
                    self._send(200, {"tokens": toks})
                else:
                    self._send(404, {"error": "not found"})
            except Exception as e:  # surface as a JSON error
                self._send(400, {"error": f"{type(e).__name__}: {e}"})

    server = ThreadingHTTPServer((host, port), Handler)
    if block:
        server.serve_forever()
    else:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
    return server


def _is_int(v) -> bool:
    x = v
    while isinstance(x, (list, tuple)) and x:
        x = x[0]
    return isinstance(x, int)


def _input_specs(batcher) -> dict:
    """Engine-declared input dtypes; a DynamicBatcher wraps the engine."""
    for obj in (batcher, getattr(batcher, "engine", None)):
        if obj is not None and hasattr(obj, "input_specs"):
            try:
                return obj.input_specs()
            except Exception:
                return {}
    return {}
