"""Minimal HTTP JSON inference endpoint (stdlib-only; the Triton
backend's HTTP surface analogue).

POST /v2/infer     {"inputs": {name: nested-list, ...}} -> {"outputs": [...]}
POST /v2/generate  {"prompt": [ids...]} or {"prompts": [[ids...], ...]},
                   optional "max_new_tokens" (int), "temperature"
                   (float), "timeout_s" (float, default 120; an
                   expired wait returns HTTP 503 — the request still
                   completes server-side), "deadline_s" (float: TTFT
                   SLO for a ServingFront's overload admission
                   control — a request whose predicted TTFT already
                   exceeds it is shed with 503 + Retry-After instead
                   of timing out inside the queue)
                   -> {"tokens": [[ids...], ...]}   (requires a
                   GenerationBatcher or ContinuousScheduler via
                   serve_http(generator=...))
GET  /v2/health    -> {"status": "ok"|"degraded", "requests": N}
                   ("degraded" when a batcher's worker thread has
                   died: the endpoint would accept requests that can
                   never complete.  A single engine's degraded rides
                   HTTP 503 so status-code-only probes drop the
                   backend too.  A ServingFront generator aggregates
                   per-replica liveness instead: ok (all live, 200),
                   degraded (some live — still serving, 200), down
                   (none live, 503), with a "replicas" detail list.
                   A replica mid-scale-down reports state "draining"
                   plus top-level replicas_draining/replicas_retired
                   counts — an INTENTIONAL exit that does not degrade
                   the front)
GET  /v2/stats     -> batch/request counters + latency percentiles
                   (+ a "continuous" block when the generator is a
                   ContinuousScheduler: queue depth, KV pool
                   occupancy/fragmentation, TTFT percentiles; a
                   ServingFront adds a per-replica block under
                   "replicas" and, when an autoscaler is attached, an
                   "autoscaler" block: current/target replicas,
                   min/max bounds, last scale decision + reason)

GET  /metrics      -> Prometheus text exposition (version 0.0.4) of
                   the metrics registry passed via
                   serve_http(registry=...): counters, gauges, and
                   histogram summaries whose _count samples carry
                   OpenMetrics exemplar annotations (the worst
                   sample's request trace_id per drain window — see
                   docs/OBSERVABILITY.md "Request tracing").  404
                   when no registry is attached.

Shed/exhausted-retry requests (front.ServiceUnavailable) return 503
with a Retry-After header computed from the front's MEASURED drain
rate (how long the current backlog takes to clear), not a constant.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np


def serve_http(batcher=None, host: str = "127.0.0.1", port: int = 8000,
               block: bool = True, generator=None, registry=None):
    """Serve a DynamicBatcher (or bare InferenceEngine) and/or a
    GenerationBatcher over HTTP.  Returns the server object; when
    block=False it runs on a daemon thread (server.shutdown() stops
    it).  With `registry` (obs.metrics.MetricsRegistry) set, GET
    /metrics renders it as Prometheus text exposition — counters,
    gauges, and histogram summaries with OpenMetrics exemplar
    annotations linking worst samples to request trace_ids
    (docs/OBSERVABILITY.md "Request tracing")."""
    if batcher is None and generator is None:
        raise ValueError("serve_http needs a batcher and/or a generator")

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send(self, code: int, payload: dict, headers=None):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _send_text(self, code: int, body: str, content_type: str):
            raw = body.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

        def do_GET(self):
            src = batcher if batcher is not None else generator
            if self.path == "/metrics":
                if registry is None:
                    self._send(404, {"error": "no metrics registry "
                                     "attached (serve_http registry=)"})
                    return
                from ..obs.metrics import to_prometheus

                # version=0.0.4 is the Prometheus text exposition
                # content type its scraper negotiates for
                self._send_text(
                    200, to_prometheus(registry),
                    "text/plain; version=0.0.4; charset=utf-8")
                return
            if self.path == "/v2/health":
                served = getattr(src, "batches_run",
                                 getattr(src, "requests_served", 0))
                front = next(
                    (obj for obj in (generator, batcher)
                     if obj is not None and hasattr(obj, "health")),
                    None,
                )
                if front is not None:
                    # replicated front (serving/front.py): per-replica
                    # liveness aggregates to ok | degraded | down.
                    # Degraded still SERVES (surviving replicas), so it
                    # rides 200 — only "down" (zero live replicas) gets
                    # the 503 that makes status-code-only probes drop
                    # the backend
                    payload = dict(front.health())
                    payload["requests"] = served
                    self._send(
                        503 if payload["status"] == "down" else 200,
                        payload,
                    )
                    return
                # a dead worker thread leaves the endpoint accepting
                # requests that only ever time out — report degraded
                # so health checks catch it (ISSUE 6 satellite)
                dead = [
                    obj for obj in (batcher, generator)
                    if obj is not None
                    and getattr(obj, "worker_alive", True) is False
                ]
                status = "degraded" if dead else "ok"
                # a single engine that degrades cannot serve at all, so
                # its degraded rides a 503 for status-code-only probes
                # (k8s, LBs), not just readers of the JSON body
                self._send(200 if not dead else 503,
                           {"status": status, "requests": served})
            elif self.path == "/v2/stats":
                stats = {
                    "batches_run": getattr(src, "batches_run", 0),
                    "requests_done": getattr(src, "requests_done", 0),
                }
                if hasattr(src, "latency_stats"):
                    stats["latency"] = src.latency_stats()
                if generator is not None and src is not generator:
                    stats["generate"] = {
                        "batches_run": generator.batches_run,
                        "requests_done": generator.requests_done,
                        "latency": generator.latency_stats(),
                    }
                if generator is not None and hasattr(generator, "stats"):
                    stats["continuous"] = generator.stats()
                self._send(200, stats)
            else:
                self._send(404, {"error": "not found"})

        def do_POST(self):
            try:
                length = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(length) or b"{}")
                if self.path == "/v2/infer" and batcher is not None:
                    specs = _input_specs(batcher)
                    inputs = {}
                    for k, v in req["inputs"].items():
                        if k in specs:
                            dt = specs[k]  # model-declared dtype wins
                        else:
                            dt = np.int32 if _is_int(v) else np.float32
                        inputs[k] = np.asarray(v, dtype=dt)
                    out = batcher.infer(inputs)
                    self._send(200, {"outputs": np.asarray(out).tolist()})
                elif self.path == "/v2/generate" and generator is not None:
                    prompts = req.get("prompts")
                    if prompts is None:
                        prompts = [req["prompt"]]
                    mnt = int(req.get("max_new_tokens", 16))
                    temp = float(req.get("temperature", 0.0))
                    timeout = float(req.get("timeout_s", 120.0))
                    if timeout <= 0:
                        raise ValueError(
                            f"timeout_s must be > 0, got {timeout}")
                    # per-request TTFT deadline for the front's
                    # overload admission control: a request the
                    # backlog already condemns to miss it is shed NOW
                    # (503 + Retry-After), not timed out in the queue
                    deadline = req.get("deadline_s")
                    kw = {}
                    if (deadline is not None
                            and hasattr(generator,
                                        "admission_deadline_s")):
                        kw["deadline_s"] = float(deadline)
                    handles = [
                        generator.generate_async(p, mnt, temp, **kw)
                        for p in prompts
                    ]  # rows of one POST coalesce into one scan
                    # ONE deadline for the whole request: sequential
                    # waits must not each restart the clock, or a
                    # multi-prompt POST could block prompts x timeout
                    deadline = time.monotonic() + timeout
                    toks = [
                        h.wait(max(0.0, deadline - time.monotonic()))
                        for h in handles
                    ]
                    self._send(200, {"tokens": toks})
                else:
                    self._send(404, {"error": "not found"})
            except TimeoutError as e:
                # the wait expired but the request is still decoding
                # server-side: 503 tells the client to back off/retry,
                # not that the request was malformed
                self._send(503, {"error": f"TimeoutError: {e}",
                                 "retriable": True})
            except (ValueError, KeyError, TypeError,
                    json.JSONDecodeError) as e:
                # malformed request (bad JSON, missing fields, lengths
                # out of range): the client's fault, not retriable
                self._send(400, {"error": f"{type(e).__name__}: {e}"})
            except Exception as e:
                retry_after = getattr(e, "retry_after_s", None)
                if retry_after is not None:
                    # load shed / replica-retries exhausted
                    # (front.ServiceUnavailable): 503 + Retry-After so
                    # well-behaved clients back off instead of
                    # hammering a front with zero live replicas
                    self._send(
                        503,
                        {"error": f"{type(e).__name__}: {e}",
                         "retriable": True},
                        headers={"Retry-After":
                                 str(max(1, int(round(retry_after))))},
                    )
                    return
                # engine fault (failed decode step, closed batcher):
                # the server's fault — 500 so clients/load balancers
                # retry instead of dropping a well-formed request
                self._send(500, {"error": f"{type(e).__name__}: {e}",
                                 "retriable": True})

    server = ThreadingHTTPServer((host, port), Handler)
    if block:
        server.serve_forever()
    else:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
    return server


def _is_int(v) -> bool:
    x = v
    while isinstance(x, (list, tuple)) and x:
        x = x[0]
    return isinstance(x, int)


def _input_specs(batcher) -> dict:
    """Engine-declared input dtypes; a DynamicBatcher wraps the engine."""
    for obj in (batcher, getattr(batcher, "engine", None)):
        if obj is not None and hasattr(obj, "input_specs"):
            try:
                return obj.input_specs()
            except Exception:
                return {}
    return {}
