"""Cross-replica KV block streaming — the transfer half of the
disaggregated prefill/decode fleet (serving/disagg.py, DistServe
arXiv:2401.09670 / Splitwise arXiv:2311.18677).

A migration ships a request's finished BLOCK-ALIGNED prefix from a
prefill-class replica's pool into a decode-class replica's pool so the
decode replica admits the request as a prefix-cache hit and never
re-runs the prompt.  The wire unit is the physical block: every
layer's [page, heads, d] k/v page for one block boundary, read
device->host ONCE on the exporting worker thread, crc32-stamped per
block, and content-keyed by the pool's rolling-hash prefix key — the
same key admission verifies against, so a torn or foreign payload can
never be admitted as shared content.

Fault model (inherited from store/blobstore.py's injection): the
fabric may throw (BLOB_TRANSIENT / BLOB_UNAVAILABLE), stall
(BLOB_LATENCY), or LAND A TRUNCATED OBJECT (BLOB_PARTIAL_UPLOAD —
the dangerous one: the put "succeeds").  Every failure mode degrades
to the same safe outcome: only per-block-crc-verified prefix blocks
are adopted (a verified PREFIX of a prefix is still a valid prefix);
everything else re-prefills on the decode replica, which writes
bit-identical bytes — output is token-identical either way, the
failure is visible in serving/kv_migration_failed.
"""
from __future__ import annotations

import json
import queue
import struct
import threading
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

_MAGIC = b"FFKV"
_VERSION = 1


class KVTransferError(Exception):
    """Torn, truncated, or foreign KV stream payload."""


def content_key(prompt: Sequence[int], n_blocks: int,
                page_size: int) -> str:
    """Content address of a block-aligned prefix: the pool's rolling
    hash of the first n_blocks pages (kv_pool's index key), so equal
    prefixes collide on the fabric by design (idempotent re-sends)."""
    from .kv_pool import _HASH_EMPTY, _hash_block

    h = _HASH_EMPTY
    for j in range(n_blocks):
        h = _hash_block(h, prompt[j * page_size:(j + 1) * page_size])
    return f"{h:016x}-{n_blocks}b{page_size}p"


def pack_kv_blocks(pages: Sequence[Sequence[int]],
                   blocks: Sequence[Dict[str, np.ndarray]],
                   page_size: int,
                   trace: Optional[Dict] = None) -> bytes:
    """Serialize exported blocks: a JSON header (schema + per-block
    token pages + per-block crc32 of the raw bytes) followed by each
    block's arrays concatenated in schema order.  The header carries
    every crc, so a truncated payload still verifies (and admits) the
    intact prefix blocks.  `trace` (a TraceContext.wire dict) rides
    the header so the adopting replica's spans join the originating
    request's trace tree (obs/reqtrace.py)."""
    if len(pages) != len(blocks):
        raise ValueError("pages/blocks length mismatch")
    schema = []
    if blocks:
        schema = [{"name": n, "shape": list(a.shape),
                   "dtype": str(a.dtype)}
                  for n, a in sorted(blocks[0].items())]
    payloads: List[bytes] = []
    crcs: List[int] = []
    for blk in blocks:
        raw = b"".join(np.ascontiguousarray(blk[s["name"]]).tobytes()
                       for s in schema)
        payloads.append(raw)
        crcs.append(zlib.crc32(raw))
    hdr = {
        "v": _VERSION,
        "page_size": int(page_size),
        "pages": [[int(t) for t in p] for p in pages],
        "schema": schema,
        "crcs": crcs,
        "block_bytes": [len(p) for p in payloads],
    }
    if trace:
        hdr["trace"] = trace
    header = json.dumps(hdr).encode("utf-8")
    return b"".join([_MAGIC, struct.pack("<I", len(header)), header]
                    + payloads)


def unpack_kv_blocks(data: bytes, prompt: Sequence[int]
                     ) -> Tuple[List[Dict[str, np.ndarray]], bool]:
    """Parse + verify a KV stream against the prompt it claims to
    serve.  Returns (verified_blocks, complete): only the prefix of
    blocks whose crc matches AND whose token page equals the prompt's
    page lands; the first torn block stops the walk (complete=False).
    A mangled header raises KVTransferError — nothing is adoptable."""
    if len(data) < 8 or data[:4] != _MAGIC:
        raise KVTransferError("bad magic: not a KV stream")
    (hlen,) = struct.unpack("<I", data[4:8])
    if len(data) < 8 + hlen:
        raise KVTransferError("truncated header")
    try:
        hdr = json.loads(data[8:8 + hlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise KVTransferError(f"mangled header: {e}") from e
    if hdr.get("v") != _VERSION:
        raise KVTransferError(f"version {hdr.get('v')} != {_VERSION}")
    page = int(hdr["page_size"])
    schema = hdr["schema"]
    out: List[Dict[str, np.ndarray]] = []
    complete = True
    off = 8 + hlen
    for j, (tokens, crc, nbytes) in enumerate(
            zip(hdr["pages"], hdr["crcs"], hdr["block_bytes"])):
        raw = data[off:off + nbytes]
        off += nbytes
        want = [int(t) for t in prompt[j * page:(j + 1) * page]]
        if (len(raw) != nbytes or zlib.crc32(raw) != crc
                or [int(t) for t in tokens] != want):
            complete = False
            break  # later blocks chain through this one: stop
        arrays: Dict[str, np.ndarray] = {}
        pos = 0
        for s in schema:
            n = int(np.prod(s["shape"])) * np.dtype(s["dtype"]).itemsize
            arrays[s["name"]] = np.frombuffer(
                raw[pos:pos + n], dtype=s["dtype"]).reshape(s["shape"])
            pos += n
        out.append(arrays)
    return out, complete


def frame_trace(data: bytes) -> Optional[Dict]:
    """The trace wire dict a KV frame header carries (None when absent
    or unparseable): the adopting side reads it off the RECEIVED bytes
    — proving the context actually propagated through the fabric —
    and joins the tree via ReqTracer.begin_remote.  Never raises: a
    torn frame just loses its trace linkage, not its safety (unpack
    still arbitrates adoption)."""
    try:
        if len(data) < 8 or data[:4] != _MAGIC:
            return None
        (hlen,) = struct.unpack("<I", data[4:8])
        hdr = json.loads(data[8:8 + hlen].decode("utf-8"))
        trace = hdr.get("trace")
        return trace if isinstance(trace, dict) else None
    except Exception:  # noqa: BLE001
        return None


# -- transfer fabrics -----------------------------------------------------
class KVTransferFabric:
    """One migration hop: ship `data` under `key`, return the bytes as
    the receiver sees them.  Implementations may throw (unreachable
    fabric) or return torn bytes (partial upload) — the unpack
    verification downstream is the only trust boundary."""

    kind = "abstract"

    def transfer(self, key: str, data: bytes) -> bytes:
        raise NotImplementedError

    def stats(self) -> Dict[str, int]:
        return {}


class InProcessFabric(KVTransferFabric):
    """Same-host handoff: the payload bytes move by reference.  Still
    packed/crc-verified like the cross-host path, so the code path the
    tests harden is the one production runs."""

    kind = "inproc"

    def __init__(self):
        self.transfers = 0
        self.bytes_moved = 0

    def transfer(self, key: str, data: bytes) -> bytes:
        self.transfers += 1
        self.bytes_moved += len(data)
        return data

    def stats(self) -> Dict[str, int]:
        return {"transfers": self.transfers,
                "bytes_moved": self.bytes_moved}


class BlobStoreFabric(KVTransferFabric):
    """Cross-host hop over the store tier (store/blobstore.py): put on
    the exporting side, get on the importing side, best-effort delete
    after.  Wrapping the store in FaultyBlobStore injects the full PR 9
    fault matrix into the stream — BLOB_PARTIAL_UPLOAD lands a
    truncated object that only the reader-side crc check catches."""

    kind = "blob"

    def __init__(self, store, prefix: str = "kvstream/"):
        self.store = store
        self.prefix = str(prefix)
        self.transfers = 0
        self.bytes_moved = 0

    def transfer(self, key: str, data: bytes) -> bytes:
        path = self.prefix + key
        self.store.put(path, data)
        got = self.store.get(path)
        try:
            self.store.delete(path)
        except Exception:  # noqa: BLE001 — cleanup is best-effort;
            pass           # a leaked object is re-keyed content
        self.transfers += 1
        self.bytes_moved += len(got)
        return got

    def stats(self) -> Dict[str, int]:
        return {"transfers": self.transfers,
                "bytes_moved": self.bytes_moved}


def resolve_kv_transfer(spec: str, store=None,
                        root: Optional[str] = None) -> KVTransferFabric:
    """Build-time gate for --kv-transfer (the engine.py resolve_*
    idiom): validate the spec and construct the fabric.  "blob"
    without an explicit store falls back to a LocalBlobStore under
    `root` (required then)."""
    spec = str(spec or "inproc").lower()
    if spec == "inproc":
        return InProcessFabric()
    if spec == "blob":
        if store is None:
            if root is None:
                raise ValueError(
                    "--kv-transfer blob needs a blob store (or a root "
                    "path for a LocalBlobStore)")
            from ..store.blobstore import LocalBlobStore

            store = LocalBlobStore(root)
        return BlobStoreFabric(store)
    raise ValueError(
        f"unknown kv transfer fabric {spec!r}: pick from "
        "['inproc', 'blob']")


class KVMigrator:
    """Asynchronous migration pipeline: pack -> transfer -> verify ->
    adopt+write on the importing replica's worker thread.

    The caller (serving/disagg.py's dispatcher) exports the blocks on
    the SOURCE worker thread (the only thread allowed to read the
    donated state) and hands the host arrays here; one migrator worker
    thread then runs the fabric hop off the decode path, and the
    device writes are marshalled onto the TARGET worker via
    run_on_worker so they serialize with its steps and admissions.

    `on_done(ok: bool)` fires exactly once per migration, success or
    any failure — the front requeues the request either way (a failed
    migration just means the decode replica re-prefills)."""

    def __init__(self, fabric: KVTransferFabric, registry=None,
                 logger=None, reqtrace=None):
        self.fabric = fabric
        self.registry = registry
        self.logger = logger
        # request tracer (obs/reqtrace.py): the importing side's
        # kv_adopt span joins the tree named by the frame header's
        # wire dict.  None (or disabled) skips all span work.
        self.reqtrace = (reqtrace if reqtrace is not None
                         and getattr(reqtrace, "enabled", True)
                         else None)
        self.started = 0
        self.completed = 0
        self.failed = 0
        self.bytes_streamed = 0
        self.blocks_streamed = 0
        self._jobs: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def migrate(self, *, prompt: Sequence[int],
                pages: Sequence[Sequence[int]],
                blocks: Sequence[Dict[str, np.ndarray]],
                page_size: int, target,
                on_done: Callable[[bool], None],
                wire: Optional[Dict] = None) -> None:
        """Queue one migration of `blocks` (host arrays exported from
        the source pool) into `target` (a ContinuousScheduler-shaped
        engine with .pool and .model).  `wire` (TraceContext.wire) is
        embedded in the frame header so the adopt joins the request's
        trace tree."""
        self.started += 1
        if self.registry is not None:
            self.registry.counter("serving/kv_migration_started").inc()
        self._jobs.put(("prefix", list(prompt), list(pages),
                        list(blocks), int(page_size), target, on_done,
                        wire))

    def migrate_live(self, *, tokens: Sequence[int],
                     pages: Sequence[Sequence[int]],
                     blocks: Sequence[Dict[str, np.ndarray]],
                     page_size: int, target,
                     on_done: Callable[[bool, Dict], None],
                     wire: Optional[Dict] = None) -> None:
        """Queue a mid-decode handoff: `tokens` is the paused
        sequence's WRITTEN prefix (prompt + generated), so the last
        page — and its exported block — may be partial.  Full pages
        adopt into the target's prefix cache exactly like migrate();
        the sub-page tail cannot be indexed, so its verified arrays
        come back through `on_done(ok, detail)` (detail["tail"]) for
        the resume admission to land in a fresh private block.
        detail["fault"] names the handoff fault kind (torn / header /
        fabric / capacity / dest_death) when ok is False — every kind
        degrades to replay-re-prefill on the destination."""
        self.started += 1
        if self.registry is not None:
            self.registry.counter("serving/kv_migration_started").inc()
        self._jobs.put(("live", list(tokens), list(pages),
                        list(blocks), int(page_size), target, on_done,
                        wire))

    def close(self) -> None:
        self._stop.set()
        self._jobs.put(None)
        self._worker.join(timeout=5.0)
        # drain jobs the worker never reached: every on_done must fire
        # exactly once or a front-side request waits forever
        while True:
            try:
                job = self._jobs.get_nowait()
            except queue.Empty:
                break
            if job is not None:
                self._fail(job[6], "migrator closed",
                           live=(job[0] == "live"))

    # -- internals --------------------------------------------------------
    def _fail(self, on_done, why: str, exc: Optional[Exception] = None,
              live: bool = False) -> None:
        self.failed += 1
        if self.registry is not None:
            self.registry.counter("serving/kv_migration_failed").inc()
        if self.logger is not None:
            self.logger.info("kv migration failed (%s): %s",
                                why, exc if exc is not None else "")
        try:
            if live:
                from .handoff import classify_handoff_fault

                on_done(False, {"fault": classify_handoff_fault(why, exc),
                                "reason": why, "tail": None,
                                "adopted_tokens": 0, "bytes": 0,
                                "blocks": 0})
            else:
                on_done(False)
        except Exception:  # noqa: BLE001 — completion hooks never kill
            pass           # the migrator worker

    def _loop(self) -> None:
        while not self._stop.is_set():
            job = self._jobs.get()
            if job is None:
                continue
            mode, toks, pages, blocks, page, target, on_done, wire = job
            live = mode == "live"
            try:
                key = content_key(toks, len(blocks), page)
                data = pack_kv_blocks(pages, blocks, page, trace=wire)
                got = self.fabric.transfer(key, data)
                verified, complete = unpack_kv_blocks(got, toks)
            except Exception as e:  # fabric down / torn header
                self._fail(on_done, "transfer", e, live=live)
                continue
            if not verified:
                self._fail(on_done, "no block verified", live=live)
                continue
            # the adopt span's link comes off the RECEIVED frame, not
            # the local wire variable: the propagation path under test
            # is the fabric itself
            if live:
                self._import_live(toks, verified, complete, len(got),
                                  page, target, on_done,
                                  frame_trace(got))
            else:
                self._import(toks, verified, complete, len(got),
                             target, on_done, frame_trace(got))

    def _import(self, prompt, verified, complete, nbytes, target,
                on_done, wire: Optional[Dict] = None) -> None:
        """Marshal the device writes onto the target's worker thread:
        adopt_prefix registers the blocks and the writes land before
        the worker's next admission, so no request can ever map a
        block whose bytes are still in flight."""
        def write():
            span = None
            if self.reqtrace is not None and wire is not None:
                # runs ON the adopting replica's worker thread: its
                # span lands on that replica's Perfetto track, linked
                # into the originating request's tree
                span = self.reqtrace.begin_remote(
                    wire, "kv_adopt",
                    pid=getattr(target, "_trace_pid", None),
                    blocks=len(verified))
            pairs = target.pool.adopt_prefix(prompt, len(verified))
            done = 0
            try:
                for j, blk in pairs:
                    target.model.import_block(blk, verified[j])
                    done += 1
            except Exception as e:
                # unwind the blocks whose bytes never landed — an
                # admission must never map them
                target.pool.drop_adopted(
                    [blk for _, blk in pairs[done:]])
                if span is not None:
                    span.end(ok=False, written=done)
                self._fail(on_done, "device write", e)
                if getattr(e, "fatal_to_engine", False):
                    raise
                return
            self.completed += 1
            self.bytes_streamed += nbytes
            self.blocks_streamed += len(verified)
            if self.registry is not None:
                reg = self.registry
                if complete:
                    reg.counter("serving/kv_migration_done").inc()
                else:
                    # a torn stream whose verified prefix still landed:
                    # the request re-prefills the remainder — count the
                    # failure AND the partial win
                    reg.counter("serving/kv_migration_failed").inc()
                    self.failed += 1
                reg.counter("serving/kv_migration_bytes").inc(nbytes)
                reg.counter("serving/kv_migration_blocks").inc(
                    len(verified))
            elif not complete:
                self.failed += 1
            if span is not None:
                span.end(ok=True, complete=bool(complete),
                         written=done, bytes=nbytes)
            try:
                on_done(bool(complete))
            except Exception:  # noqa: BLE001
                pass

        try:
            target.run_on_worker(
                write, on_dropped=lambda err: self._fail(
                    on_done, "target gone", err))
        except Exception as e:  # target closed
            self._fail(on_done, "target closed", e)

    def _import_live(self, toks, verified, complete, nbytes, page,
                     target, on_done, wire: Optional[Dict] = None
                     ) -> None:
        """The live-handoff import: full pages adopt into the target's
        prefix cache (the resume admission then hits them exactly like
        a migrated prompt); the verified partial tail block's arrays
        ride back in the completion detail instead — a sub-page tail
        has no stable content key, so only the resumed sequence itself
        may own it."""
        n_full = len(toks) // page

        def write():
            span = None
            if self.reqtrace is not None and wire is not None:
                span = self.reqtrace.begin_remote(
                    wire, "kv_adopt",
                    pid=getattr(target, "_trace_pid", None),
                    blocks=len(verified), live=True)
            full = verified[:n_full]
            pairs = target.pool.adopt_prefix(toks, len(full))
            done = 0
            try:
                for j, blk in pairs:
                    target.model.import_block(blk, full[j])
                    done += 1
            except Exception as e:
                target.pool.drop_adopted(
                    [blk for _, blk in pairs[done:]])
                if span is not None:
                    span.end(ok=False, written=done)
                self._fail(on_done, "device write", e, live=True)
                if getattr(e, "fatal_to_engine", False):
                    raise
                return
            # coverage as admission will see it: adopt_prefix stops
            # early when the pool has no reclaimable block (capacity)
            # — the resume then replays the unadopted remainder
            adopted = target.pool.cached_prefix_tokens(toks)
            tail = (verified[n_full]
                    if complete and len(verified) > n_full else None)
            ok = bool(complete) and adopted >= n_full * page
            fault = None if ok else (
                "capacity" if complete else "torn")
            self.completed += 1
            self.bytes_streamed += nbytes
            self.blocks_streamed += len(verified)
            if self.registry is not None:
                reg = self.registry
                if ok:
                    reg.counter("serving/kv_migration_done").inc()
                else:
                    reg.counter("serving/kv_migration_failed").inc()
                reg.counter("serving/kv_migration_bytes").inc(nbytes)
                reg.counter("serving/kv_migration_blocks").inc(
                    len(verified))
            if not ok:
                self.failed += 1
            if span is not None:
                span.end(ok=ok, complete=bool(complete),
                         written=done, bytes=nbytes)
            try:
                on_done(ok, {"fault": fault, "tail": tail,
                             "adopted_tokens": int(adopted),
                             "bytes": nbytes,
                             "blocks": len(verified)})
            except Exception:  # noqa: BLE001
                pass

        try:
            target.run_on_worker(
                write, on_dropped=lambda err: self._fail(
                    on_done, "target gone", err, live=True))
        except Exception as e:  # target closed
            self._fail(on_done, "target closed", e, live=True)

    def stats(self) -> Dict[str, int]:
        out = {
            "started": self.started,
            "completed": self.completed,
            "failed": self.failed,
            "bytes_streamed": self.bytes_streamed,
            "blocks_streamed": self.blocks_streamed,
            "fabric": self.fabric.kind,
        }
        out.update({f"fabric_{k}": v
                    for k, v in self.fabric.stats().items()})
        return out
