"""Dynamic request batching (the Triton scheduler role: coalesce
concurrent single requests into one device batch, bounded by
max_batch_size and a flush timeout)."""
from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional

import numpy as np


class _Pending:
    __slots__ = ("inputs", "event", "result", "error")

    def __init__(self, inputs):
        self.inputs = inputs
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[Exception] = None


class DynamicBatcher:
    """Background thread that drains the request queue, concatenates up
    to max_batch samples, runs the engine once, and scatters results."""

    def __init__(self, engine, max_batch: int = 32,
                 flush_timeout_s: float = 0.005):
        self.engine = engine
        self.max_batch = max_batch
        self.flush_timeout_s = flush_timeout_s
        self._queue: "queue.Queue[_Pending]" = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        self.batches_run = 0

    # -- client API -----------------------------------------------------
    def infer(self, inputs: Dict[str, np.ndarray],
              timeout: Optional[float] = 30.0) -> np.ndarray:
        """Blocking single/partial-batch request; thread-safe."""
        p = _Pending({k: np.asarray(v) for k, v in inputs.items()})
        self._queue.put(p)
        if not p.event.wait(timeout):
            raise TimeoutError("inference request timed out")
        if p.error is not None:
            raise p.error
        return p.result

    def close(self):
        self._stop.set()
        self._thread.join(timeout=5)
        # fail anything still queued so callers don't sit out their timeout
        while True:
            try:
                p = self._queue.get_nowait()
            except queue.Empty:
                break
            p.error = RuntimeError("DynamicBatcher closed")
            p.event.set()

    # -- worker ---------------------------------------------------------
    def _loop(self):
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                continue
            batch: List[_Pending] = [first]
            total = len(next(iter(first.inputs.values())))
            # absolute deadline from the FIRST request, so a steady
            # trickle can't defer the flush past the configured bound
            deadline = time.monotonic() + self.flush_timeout_s
            while total < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                batch.append(nxt)
                total += len(next(iter(nxt.inputs.values())))
            self._run(batch)

    def _run(self, batch: List[_Pending]):
        try:
            keys = list(batch[0].inputs.keys())
            joined = {
                k: np.concatenate([p.inputs[k] for p in batch]) for k in keys
            }
            out = self.engine.infer(joined)
            self.batches_run += 1
            start = 0
            for p in batch:
                n = len(next(iter(p.inputs.values())))
                p.result = out[start:start + n]
                start += n
                p.event.set()
        except Exception as e:
            for p in batch:
                p.error = e
                p.event.set()
