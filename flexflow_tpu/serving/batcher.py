"""Dynamic request batching (the Triton scheduler role: coalesce
concurrent single requests into one device batch, bounded by
max_batch_size and a flush timeout).

Two-stage pipeline: the ASSEMBLER thread drains the request queue,
concatenates up to max_batch samples, and *dispatches* the jitted
forward (jax dispatch is asynchronous, so this returns immediately);
the COMPLETER thread materializes results and scatters them back to
waiters.  While batch N computes on the device, batch N+1 is being
assembled and dispatched — device and host time overlap instead of
serializing, the same double-buffering the dataloader uses for
training.  Per-request latency (submit -> result ready) is tracked in a
ring buffer; `latency_stats()` reports p50/p95/p99.
"""
from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np


def percentile_summary(values, ps=(0.50, 0.95, 0.99)) -> Dict[str, float]:
    """n / p*_ms / mean_ms summary of latencies in SECONDS — the one
    percentile implementation (batchers' ring windows, the continuous
    scheduler's TTFT stats, and the loadgen report all use it)."""
    lats = sorted(values)
    if not lats:
        return {"n": 0}

    def pct(p):
        # nearest-rank: ceil(p*n)-th order statistic (int(p*n) is
        # upward-biased — p95 of a 20-sample window would always be
        # the max)
        import math

        i = min(len(lats) - 1, max(0, math.ceil(p * len(lats)) - 1))
        return lats[i] * 1e3

    out = {"n": len(lats)}
    for p in ps:
        out[f"p{int(round(p * 100))}_ms"] = round(pct(p), 3)
    out["mean_ms"] = round(sum(lats) / len(lats) * 1e3, 3)
    return out


def latency_percentiles(latencies, lock) -> Dict[str, float]:
    """p50/p95/p99/mean (ms) over a ring buffer (shared by the forward
    and generation batchers)."""
    with lock:  # appends race from the worker threads
        vals = list(latencies)
    return percentile_summary(vals)


class _Pending:
    __slots__ = ("inputs", "event", "result", "error", "t_submit")

    def __init__(self, inputs):
        self.inputs = inputs
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[Exception] = None
        self.t_submit = time.monotonic()

    # -- future-style API (infer_async) ---------------------------------
    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self.event.wait(timeout):
            raise TimeoutError("inference request timed out")
        if self.error is not None:
            raise self.error
        return self.result


class DynamicBatcher:
    """Assembler + completer threads around an InferenceEngine."""

    def __init__(self, engine, max_batch: int = 32,
                 flush_timeout_s: float = 0.005,
                 max_inflight: int = 2,
                 latency_window: int = 1024, registry=None):
        self.engine = engine
        self.max_batch = max_batch
        # obs.metrics registry: counters/latencies fold in as
        # serving/infer_* so they drain to run_telemetry.jsonl
        # (the /v2/stats JSON shape is unchanged)
        self.registry = registry
        self.flush_timeout_s = flush_timeout_s
        self._queue: "queue.Queue[_Pending]" = queue.Queue()
        # bounded: backpressure keeps at most `max_inflight` batches on
        # the device while the assembler keeps building the next one
        self._inflight: "queue.Queue" = queue.Queue(maxsize=max_inflight)
        self._stop = threading.Event()
        self._latencies = deque(maxlen=latency_window)
        self._lat_lock = threading.Lock()
        self._carry: Optional[_Pending] = None  # overflow from coalescing
        self._carry_lock = threading.Lock()  # close() vs assembler
        self.batches_run = 0
        self.requests_done = 0
        self._assembler = threading.Thread(target=self._assemble_loop,
                                           daemon=True)
        self._completer = threading.Thread(target=self._complete_loop,
                                           daemon=True)
        self._assembler.start()
        self._completer.start()

    # -- client API -----------------------------------------------------
    def infer(self, inputs: Dict[str, np.ndarray],
              timeout: Optional[float] = 30.0) -> np.ndarray:
        """Blocking single/partial-batch request; thread-safe."""
        return self.infer_async(inputs).wait(timeout)

    def infer_async(self, inputs: Dict[str, np.ndarray]) -> _Pending:
        """Non-blocking submit; returns a future-style handle with
        .wait(timeout).  Raises after close() — the assembler is gone
        and the request would otherwise wait out its full timeout."""
        if self._stop.is_set():
            raise RuntimeError("DynamicBatcher is closed")
        p = _Pending({k: np.asarray(v) for k, v in inputs.items()})
        self._queue.put(p)
        # enqueue-then-recheck: close() may have finished its final
        # drain between the check above and the put — fail the request
        # ourselves rather than park it for its full wait timeout
        # (idempotent if the drain also saw it)
        if self._stop.is_set():
            p.error = RuntimeError("DynamicBatcher is closed")
            p.event.set()
        return p

    @property
    def worker_alive(self) -> bool:
        """False once either pipeline thread has died — /v2/health
        reports "degraded" then (requests would only time out)."""
        return self._assembler.is_alive() and self._completer.is_alive()

    def latency_stats(self) -> Dict[str, float]:
        """p50/p95/p99/mean request latency (ms) over the ring window."""
        return latency_percentiles(self._latencies, self._lat_lock)

    def close(self):
        self._stop.set()

        def drain():
            with self._carry_lock:
                p, self._carry = self._carry, None
            if p is not None:
                p.error = RuntimeError("DynamicBatcher closed")
                p.event.set()
            for q in (self._queue, self._inflight):
                while True:
                    try:
                        item = q.get_nowait()
                    except queue.Empty:
                        break
                    pendings = [item] if isinstance(item, _Pending) \
                        else item[1]
                    for p in pendings:
                        p.error = RuntimeError("DynamicBatcher closed")
                        p.event.set()

        # a worker stuck in a cold-bucket compile can outlive the join
        # timeout and enqueue AFTER a one-shot drain — keep draining
        # until both threads are really gone (bounded), then once more
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and (
            self._assembler.is_alive() or self._completer.is_alive()
        ):
            drain()
            self._assembler.join(timeout=0.2)
            self._completer.join(timeout=0.2)
        drain()

    # -- assembler stage ------------------------------------------------
    def _assemble_loop(self):
        while not self._stop.is_set():
            with self._carry_lock:
                first, self._carry = self._carry, None
            if first is None:
                try:
                    first = self._queue.get(timeout=0.05)
                except queue.Empty:
                    continue
            batch: List[_Pending] = [first]
            total = len(next(iter(first.inputs.values())))
            # never coalesce past what one jitted forward can take, or
            # the dispatch degrades to the synchronous chunked path
            cap = min(self.max_batch, self.engine.chunk_cap())
            # absolute deadline from the FIRST request, so a steady
            # trickle can't defer the flush past the configured bound
            deadline = time.monotonic() + self.flush_timeout_s
            while total < cap:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                n = len(next(iter(nxt.inputs.values())))
                if total + n > cap:
                    with self._carry_lock:
                        if self._stop.is_set():
                            # close() already drained; fail it here
                            # rather than parking it forever
                            nxt.error = RuntimeError("DynamicBatcher closed")
                            nxt.event.set()
                        else:
                            self._carry = nxt  # overflow: heads next batch
                    break
                batch.append(nxt)
                total += n
            self._dispatch(batch)

    def _dispatch(self, batch: List[_Pending]):
        try:
            keys = list(batch[0].inputs.keys())
            joined = {
                k: np.concatenate([p.inputs[k] for p in batch]) for k in keys
            }
            n = len(next(iter(joined.values())))
            if n > self.engine.chunk_cap():
                # single oversize request: engine.infer chunks it
                # synchronously (coalescing never builds past the cap)
                self._scatter(batch, self.engine.infer(joined))
                return
            dev_out = self.engine.dispatch(joined, n)  # async launch
            self._inflight.put((dev_out, batch, n))  # blocks at capacity
        except Exception as e:
            for p in batch:
                p.error = e
                p.event.set()

    # -- completer stage ------------------------------------------------
    def _complete_loop(self):
        while not self._stop.is_set() or not self._inflight.empty():
            try:
                dev_out, batch, n = self._inflight.get(timeout=0.05)
            except queue.Empty:
                continue
            try:
                self._scatter(batch, np.asarray(dev_out)[:n])  # waits
            except Exception as e:
                for p in batch:
                    p.error = e
                    p.event.set()

    def _scatter(self, batch: List[_Pending], out: np.ndarray):
        """Slice a completed batch back to its waiters + account."""
        self.batches_run += 1
        start = 0
        now = time.monotonic()
        for p in batch:
            k = len(next(iter(p.inputs.values())))
            p.result = out[start:start + k]
            start += k
            with self._lat_lock:
                self._latencies.append(now - p.t_submit)
            self.requests_done += 1
            p.event.set()
        if self.registry is not None:
            reg = self.registry
            reg.counter("serving/infer_batches_run").inc()
            reg.counter("serving/infer_requests_done").inc(len(batch))
            for p in batch:
                reg.histogram("serving/infer_latency_ms").observe(
                    (now - p.t_submit) * 1e3)
