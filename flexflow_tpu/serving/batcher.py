"""Dynamic request batching (the Triton scheduler role: coalesce
concurrent single requests into one device batch, bounded by
max_batch_size and a flush timeout).

Two-stage pipeline: the ASSEMBLER thread drains the request queue,
concatenates up to max_batch samples, and *dispatches* the jitted
forward (jax dispatch is asynchronous, so this returns immediately);
the COMPLETER thread materializes results and scatters them back to
waiters.  While batch N computes on the device, batch N+1 is being
assembled and dispatched — device and host time overlap instead of
serializing, the same double-buffering the dataloader uses for
training.  Per-request latency (submit -> result ready) is tracked in a
ring buffer; `latency_stats()` reports p50/p95/p99.
"""
from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np


class _Pending:
    __slots__ = ("inputs", "event", "result", "error", "t_submit")

    def __init__(self, inputs):
        self.inputs = inputs
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[Exception] = None
        self.t_submit = time.monotonic()

    # -- future-style API (infer_async) ---------------------------------
    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self.event.wait(timeout):
            raise TimeoutError("inference request timed out")
        if self.error is not None:
            raise self.error
        return self.result


class DynamicBatcher:
    """Assembler + completer threads around an InferenceEngine."""

    def __init__(self, engine, max_batch: int = 32,
                 flush_timeout_s: float = 0.005,
                 max_inflight: int = 2,
                 latency_window: int = 1024):
        self.engine = engine
        self.max_batch = max_batch
        self.flush_timeout_s = flush_timeout_s
        self._queue: "queue.Queue[_Pending]" = queue.Queue()
        # bounded: backpressure keeps at most `max_inflight` batches on
        # the device while the assembler keeps building the next one
        self._inflight: "queue.Queue" = queue.Queue(maxsize=max_inflight)
        self._stop = threading.Event()
        self._latencies = deque(maxlen=latency_window)
        self.batches_run = 0
        self.requests_done = 0
        self._assembler = threading.Thread(target=self._assemble_loop,
                                           daemon=True)
        self._completer = threading.Thread(target=self._complete_loop,
                                           daemon=True)
        self._assembler.start()
        self._completer.start()

    # -- client API -----------------------------------------------------
    def infer(self, inputs: Dict[str, np.ndarray],
              timeout: Optional[float] = 30.0) -> np.ndarray:
        """Blocking single/partial-batch request; thread-safe."""
        return self.infer_async(inputs).wait(timeout)

    def infer_async(self, inputs: Dict[str, np.ndarray]) -> _Pending:
        """Non-blocking submit; returns a future-style handle with
        .wait(timeout)."""
        p = _Pending({k: np.asarray(v) for k, v in inputs.items()})
        self._queue.put(p)
        return p

    def latency_stats(self) -> Dict[str, float]:
        """p50/p95/p99/mean request latency (ms) over the ring window."""
        lats = sorted(self._latencies)
        if not lats:
            return {"n": 0}

        def pct(p):
            return lats[min(len(lats) - 1, int(p * len(lats)))] * 1e3

        return {
            "n": len(lats),
            "p50_ms": round(pct(0.50), 3),
            "p95_ms": round(pct(0.95), 3),
            "p99_ms": round(pct(0.99), 3),
            "mean_ms": round(sum(lats) / len(lats) * 1e3, 3),
        }

    def close(self):
        self._stop.set()
        self._assembler.join(timeout=5)
        self._completer.join(timeout=5)
        # fail anything still queued so callers don't sit out their timeout
        for q in (self._queue, self._inflight):
            while True:
                try:
                    item = q.get_nowait()
                except queue.Empty:
                    break
                pendings = [item] if isinstance(item, _Pending) \
                    else item[1]
                for p in pendings:
                    p.error = RuntimeError("DynamicBatcher closed")
                    p.event.set()

    # -- assembler stage ------------------------------------------------
    def _assemble_loop(self):
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                continue
            batch: List[_Pending] = [first]
            total = len(next(iter(first.inputs.values())))
            # absolute deadline from the FIRST request, so a steady
            # trickle can't defer the flush past the configured bound
            deadline = time.monotonic() + self.flush_timeout_s
            while total < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                batch.append(nxt)
                total += len(next(iter(nxt.inputs.values())))
            self._dispatch(batch)

    def _dispatch(self, batch: List[_Pending]):
        try:
            keys = list(batch[0].inputs.keys())
            joined = {
                k: np.concatenate([p.inputs[k] for p in batch]) for k in keys
            }
            n = len(next(iter(joined.values())))
            if n > self.engine.chunk_cap():
                # oversize request(s): engine.infer chunks synchronously
                out = self.engine.infer(joined)
                self.batches_run += 1
                start = 0
                now = time.monotonic()
                for p in batch:
                    k = len(next(iter(p.inputs.values())))
                    p.result = out[start:start + k]
                    start += k
                    self._latencies.append(now - p.t_submit)
                    self.requests_done += 1
                    p.event.set()
                return
            dev_out = self.engine.dispatch(joined, n)  # async launch
            self._inflight.put((dev_out, batch, n))  # blocks at capacity
        except Exception as e:
            for p in batch:
                p.error = e
                p.event.set()

    # -- completer stage ------------------------------------------------
    def _complete_loop(self):
        while not self._stop.is_set() or not self._inflight.empty():
            try:
                dev_out, batch, n = self._inflight.get(timeout=0.05)
            except queue.Empty:
                continue
            try:
                out = np.asarray(dev_out)[:n]  # waits for the device
                self.batches_run += 1
                start = 0
                now = time.monotonic()
                for p in batch:
                    k = len(next(iter(p.inputs.values())))
                    p.result = out[start:start + k]
                    start += k
                    self._latencies.append(now - p.t_submit)
                    self.requests_done += 1
                    p.event.set()
            except Exception as e:
                for p in batch:
                    p.error = e
                    p.event.set()
