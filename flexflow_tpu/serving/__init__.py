"""Inference serving (reference triton/ subtree: a Triton backend
running ONNX models on Legion with a static LayerStrategy,
triton/src/strategy.h:29-224, onnx_parser.cc).

TPU-native: `InferenceEngine` compiles a model's forward under a fixed
Strategy into bucketed jitted callables (static shapes per batch
bucket, so XLA compiles once per bucket); `DynamicBatcher` coalesces
concurrent requests up to max_batch/timeout — the Triton scheduler's
role; `serve_http` exposes a stdlib JSON endpoint.

Generation has two tiers (docs/SERVING.md): `GenerationBatcher` runs
STATIC batches (whole generations as one scan program, requests
coalesced up front) and `ContinuousScheduler` runs CONTINUOUS
(iteration-level) batches on a paged KV-cache pool — sequences are
admitted and retired at every decode step, so heterogeneous lengths
share device time and HBM instead of padding to the batch max.

`ServingFront` (docs/SERVING.md "Replicated front") puts N supervised
`ServingReplica`s — each a ContinuousScheduler under the resilience
primitives (fault injection, decode-step watchdog, budget-capped
restarts) — behind one admission queue: replica deaths requeue
in-flight requests onto survivors instead of failing the service.

`ServingAutoscaler` (docs/SERVING.md "Autoscaling & drain lifecycle")
makes the fleet size itself a measured, controlled variable: a control
loop over the front's queue-depth / p99-TTFT / KV-occupancy gauges
spawns replicas under load (warm through the strategy store) and
gracefully DRAINS the least-loaded one when calm — in-flight work runs
to completion token-identically before the engine retires and frees
its KV pool — with hysteresis bands, a cooldown, and
min/max-replica bounds so the loop cannot flap.

`DisaggServingFront` (docs/SERVING.md "Disaggregated fleet") splits
the replica classes — prefill passes on one, client decodes on the
other — and streams each request's finished KV blocks across replicas
through a `KVTransferFabric` (serving/kv_transfer.py), costing every
handoff against re-prefilling with the topology model's interconnect
terms.  Token-identical to the colocated fleet by construction.

Speculative decoding (docs/SERVING.md "Speculative decoding") rides the
chunk twin: a `Proposer` (`NGramProposer` mining the request's own
context, or `DraftModelProposer` running a smaller GPT on its own paged
engine) drafts k tokens per greedy slot, one multi-position verify
dispatch scores them, and the scheduler accepts the longest matching
prefix plus the corrected token — token-identical to plain decode at
temperature 0 by construction, with `AdaptiveK` shrinking k when
acceptance drops so the feature is never worse than baseline.
"""
from .autoscaler import ServingAutoscaler
from .batcher import DynamicBatcher
from .disagg import (DisaggServingFront, MigrationCostModel,
                     build_front, parse_serving_roles)
from .engine import InferenceEngine
from .front import FrontRequest, ServiceUnavailable, ServingFront
from .generation import GenerationBatcher, GenerationEngine
from .kv_pool import KVPool
from .kv_transfer import (BlobStoreFabric, InProcessFabric, KVMigrator,
                          KVTransferFabric, resolve_kv_transfer)
from .replica import ServingReplica, SupervisedDecodeModel
from .scheduler import ContinuousScheduler, PagedKVDecodeModel
from .server import serve_http
from .speculative import (AdaptiveK, DraftModelProposer, NGramProposer,
                          Proposer, build_proposer)

__all__ = ["InferenceEngine", "DynamicBatcher", "GenerationEngine",
           "GenerationBatcher", "ContinuousScheduler",
           "PagedKVDecodeModel", "KVPool", "serve_http",
           "ServingFront", "ServingReplica", "SupervisedDecodeModel",
           "FrontRequest", "ServiceUnavailable", "ServingAutoscaler",
           "DisaggServingFront", "MigrationCostModel", "build_front",
           "parse_serving_roles", "KVTransferFabric", "KVMigrator",
           "InProcessFabric", "BlobStoreFabric", "resolve_kv_transfer",
           "Proposer", "NGramProposer", "DraftModelProposer",
           "AdaptiveK", "build_proposer"]
