"""Inference serving (reference triton/ subtree: a Triton backend
running ONNX models on Legion with a static LayerStrategy,
triton/src/strategy.h:29-224, onnx_parser.cc).

TPU-native: `InferenceEngine` compiles a model's forward under a fixed
Strategy into bucketed jitted callables (static shapes per batch
bucket, so XLA compiles once per bucket); `DynamicBatcher` coalesces
concurrent requests up to max_batch/timeout — the Triton scheduler's
role; `serve_http` exposes a stdlib JSON endpoint.
"""
from .engine import InferenceEngine
from .batcher import DynamicBatcher
from .generation import GenerationBatcher, GenerationEngine
from .server import serve_http

__all__ = ["InferenceEngine", "DynamicBatcher", "GenerationEngine",
           "GenerationBatcher", "serve_http"]
