"""Paged KV-cache pool accounting (the vLLM PagedAttention design,
SOSP'23, on the host side).

The device holds per-layer block pools ([num_blocks, page, heads, d]
state arrays built by `make_gpt_decoder(kv_page_size=...)`); this
module is the single source of truth for WHICH physical block belongs
to WHICH sequence.  All layers allocate in lockstep (every layer's
cache has the same sequence structure), so one free list and one block
table per scheduler slot cover the whole model.

Accounting protocol (no mid-flight OOM by construction):

* **Admission reserves, extension allocates.**  `try_admit` checks the
  sequence's WORST-CASE block need (ceil((plen + max_new) / page))
  against unreserved capacity and either books it or refuses — a full
  pool queues requests, it never crashes mid-decode.  Physical blocks
  are then popped lazily by `extend` as the sequence actually grows
  (allocate-on-extend), so a short reply never pins its worst case and
  `used_blocks` tracks real occupancy.
* **Retire frees.**  `retire` returns every block (and the unused
  reservation) to the pool the moment a sequence finishes — early eos
  makes room for the next admission immediately.
* **Block 0 is scratch.**  Idle scheduler slots point their table at
  block 0; their per-step garbage writes land there and are never
  attendable (masked by seq_len 0), so scratch never needs zeroing.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

SCRATCH_BLOCK = 0


class PoolExhausted(Exception):
    """Internal invariant breach: extend() needed a block the
    admission reservation did not cover.  Seeing this means the
    accounting is wrong — callers must never trigger it."""


class KVPool:
    """Host-side block accounting for the paged decode twin.

    num_blocks counts the PHYSICAL pool including the scratch block;
    usable capacity is num_blocks - 1.  max_blocks_per_seq is the
    table width (decode_max_seq // page for the bit-identical gather).
    """

    def __init__(self, num_blocks: int, page_size: int,
                 max_blocks_per_seq: int):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks {num_blocks} < 2 (scratch + at least one "
                "usable block)")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if max_blocks_per_seq < 1:
            raise ValueError(
                f"max_blocks_per_seq must be >= 1, got "
                f"{max_blocks_per_seq}")
        self.num_blocks = int(num_blocks)
        self.page_size = int(page_size)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        # LIFO free list: recently-freed blocks are re-used first (their
        # pool rows are the likeliest to still be in cache)
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._tables: Dict[int, List[int]] = {}   # seq id -> block ids
        self._reserved: Dict[int, int] = {}       # seq id -> max blocks
        self.peak_used = 0
        # the scheduler worker mutates the pool while /v2/stats reads
        # it from HTTP threads — iteration over _tables must not race
        # a retire()'s pop
        self._lock = threading.Lock()

    # -- capacity ---------------------------------------------------------
    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1

    @property
    def used_blocks(self) -> int:
        return self.usable_blocks - len(self._free)

    @property
    def reserved_blocks(self) -> int:
        with self._lock:  # /v2/stats reads while the worker admits
            return sum(self._reserved.values())

    def blocks_for(self, tokens: int) -> int:
        """ceil(tokens / page): blocks a sequence of that length needs."""
        return max(1, -(-int(tokens) // self.page_size))

    # -- lifecycle --------------------------------------------------------
    def try_admit(self, seq_id: int, max_tokens: int) -> bool:
        """Reserve worst-case capacity for a new sequence.  False means
        the pool cannot guarantee the sequence will finish — the caller
        keeps it queued and retries after the next retirement."""
        if seq_id in self._reserved:
            raise ValueError(f"sequence {seq_id} already admitted")
        need = self.blocks_for(max_tokens)
        if need > self.max_blocks_per_seq:
            raise ValueError(
                f"sequence {seq_id} needs {need} blocks > table width "
                f"{self.max_blocks_per_seq} (prompt + max_new_tokens "
                f"exceed decode_max_seq)")
        with self._lock:  # raw sum: the lock is not reentrant
            if sum(self._reserved.values()) + need > self.usable_blocks:
                return False
            self._reserved[seq_id] = need
            self._tables[seq_id] = []
        return True

    def extend(self, seq_id: int, tokens: int) -> List[int]:
        """Grow seq_id's table to cover `tokens` total tokens; returns
        the block ids allocated by THIS call (allocate-on-extend)."""
        with self._lock:
            table = self._tables[seq_id]
            need = self.blocks_for(tokens)
            if need > self._reserved[seq_id]:
                raise PoolExhausted(
                    f"sequence {seq_id} grew to {need} blocks past its "
                    f"reservation of {self._reserved[seq_id]}")
            grown = []
            while len(table) < need:
                blk = self._free.pop()  # reservation guarantees non-empty
                table.append(blk)
                grown.append(blk)
            if self.used_blocks > self.peak_used:
                self.peak_used = self.used_blocks
            return grown

    def retire(self, seq_id: int) -> None:
        """Free every block and drop the reservation (free-on-retire)."""
        with self._lock:
            self._free.extend(self._tables.pop(seq_id))
            del self._reserved[seq_id]

    def live_sequences(self) -> List[int]:
        with self._lock:
            return list(self._tables)

    def table_of(self, seq_id: int) -> List[int]:
        with self._lock:
            return list(self._tables[seq_id])

    def table_row(self, seq_id: Optional[int]) -> np.ndarray:
        """[max_blocks_per_seq] int32 row for the device block table;
        unallocated (and idle-slot) entries point at scratch."""
        row = np.full(self.max_blocks_per_seq, SCRATCH_BLOCK, np.int32)
        if seq_id is not None:
            with self._lock:
                table = list(self._tables[seq_id])
            row[:len(table)] = table
        return row

    # -- telemetry --------------------------------------------------------
    def occupancy(self) -> float:
        """Fraction of usable blocks currently allocated."""
        return self.used_blocks / self.usable_blocks

    def fragmentation(self, seq_tokens: Dict[int, int]) -> float:
        """Internal fragmentation: fraction of allocated slots not
        holding a live token (waste in each sequence's last block).
        seq_tokens maps live seq id -> its current token count."""
        with self._lock:
            alloc = self.used_blocks * self.page_size
            if not alloc:
                return 0.0
            live = sum(min(seq_tokens.get(s, 0),
                           len(self._tables[s]) * self.page_size)
                       for s in self._tables)
        return 1.0 - live / alloc

    def check_invariants(self) -> None:
        """Every block is exactly one of: scratch, free, or in exactly
        one live table — and allocated == sum of live tables.  Raises
        AssertionError on leaks or double-frees (tested property)."""
        with self._lock:
            owned: List[int] = []
            for table in self._tables.values():
                owned.extend(table)
            assert len(owned) == len(set(owned)), "block in two tables"
            assert SCRATCH_BLOCK not in owned, "scratch block allocated"
            free = set(self._free)
            assert len(free) == len(self._free), "double-freed block"
            assert not (free & set(owned)), \
                "block both free and allocated"
            assert free | set(owned) | {SCRATCH_BLOCK} == \
                set(range(self.num_blocks)), "block leaked"
            assert self.used_blocks == len(owned)
            for sid, table in self._tables.items():
                assert len(table) <= self._reserved[sid], \
                    "over-reservation"
