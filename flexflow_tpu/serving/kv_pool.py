"""Paged KV-cache pool accounting (the vLLM PagedAttention design,
SOSP'23, on the host side) — now a PREFIX CACHE with copy-on-write
block sharing (the RadixAttention idea, SGLang arXiv:2312.07104).

The device holds per-layer block pools ([num_blocks, page, heads, d]
state arrays built by `make_gpt_decoder(kv_page_size=...)`); this
module is the single source of truth for WHICH physical block belongs
to WHICH sequence.  All layers allocate in lockstep (every layer's
cache has the same sequence structure), so one free list and one block
table per scheduler slot cover the whole model.

Accounting protocol (no mid-flight OOM by construction):

* **Admission reserves, extension allocates.**  `try_admit` checks the
  sequence's WORST-CASE block need (ceil((plen + max_new) / page))
  against unreserved capacity and either books it or refuses — a full
  pool queues requests, it never crashes mid-decode.  Physical blocks
  are then popped lazily by `extend` as the sequence actually grows
  (allocate-on-extend), so a short reply never pins its worst case and
  `used_blocks` tracks real occupancy.
* **Retire frees — into the prefix cache.**  `retire` drops every
  block's refcount the moment a sequence finishes.  Blocks whose
  content is indexed under a token-prefix key stay CACHED (refcount 0,
  LRU-evictable) instead of returning to the free list; everything
  else frees immediately.  Capacity pressure reclaims cached blocks
  on demand, so caching never refuses an admission the free list
  alone could have served.
* **Prefix sharing.**  The pool keys every FULL (block-aligned) token
  prefix it has seen — registered live as prompt blocks fill, and at
  retirement for the generated suffix — to the physical block holding
  that prefix's last page.  Keys are ROLLING HASHES extended one page
  per block boundary (O(plen) admission-key builds, not the exact-key
  O(plen^2/page)); every hit is verified exactly through the entry's
  parent chain + per-page bytes before any block is shared, so the
  collision-free story is unchanged (see _PrefixEntry).  `try_admit(prompt=...)` matches the
  longest indexed prefix of the new prompt and maps the request's
  table directly onto the shared physical blocks (refcount++), so
  those tokens skip prefill entirely.  Shared blocks are IMMUTABLE by
  construction: the scatter-at-own-position write path only ever
  targets positions past the shared region, except for a full-prompt
  hit, where the write at plen-1 re-lands in the last shared block —
  `ensure_writable` copy-on-writes that block (fresh private copy,
  refcount--) before the scheduler feeds the token, so no block with
  refcount > 1 (or an index entry) is ever written.
* **Block 0 is scratch.**  Idle scheduler slots point their table at
  block 0; their per-step garbage writes land there and are never
  attendable (masked by seq_len 0), so scratch never needs zeroing.

The pool tracks per-sequence token counts itself (`extend` sees every
growth), so `occupancy()`/`fragmentation()` cannot drift from the
tables under sharing — callers no longer pass scheduler-side counts.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

SCRATCH_BLOCK = 0

# rolling prefix hash (index keys): 61-bit Mersenne-prime modulus
# polynomial hash, extended one PAGE at a time so building every
# block-boundary key of a plen-token prompt costs O(plen) total
# instead of the exact-bytes key's O(plen^2/page).  Collisions cannot
# corrupt matches: every index hit is verified exactly (see
# _PrefixEntry) before any block is shared.
_HASH_MOD = (1 << 61) - 1
_HASH_BASE = 1_000_003
_HASH_EMPTY = 0


def _hash_block(h: int, tokens: Sequence[int]) -> int:
    """Extend the rolling prefix hash `h` over one page of tokens —
    O(page) per block boundary (the unit the linear-admission test
    counts)."""
    for t in tokens:
        h = (h * _HASH_BASE + int(t) + 1) % _HASH_MOD
    return h


def _page_bytes(tokens: Sequence[int]) -> bytes:
    """Exact int32 bytes of ONE page — the per-boundary verification
    payload (compact: entries store one page each, not the whole
    prefix)."""
    return np.asarray(tokens, np.int32).tobytes()


class _PrefixEntry:
    """One indexed block boundary: the physical block holding the
    prefix's last page, keyed by the rolling hash of the FULL prefix.

    The collision-free story of the old exact-bytes keys is preserved
    by construction, not by hash width: entries chain through `parent`
    (the entry for the one-page-shorter prefix, fixed at registration),
    and a match walk accepts boundary j only when (a) the hash hits,
    (b) the entry's parent IS the entry object verified at j-1, and
    (c) the entry's last-page bytes equal the prompt's page j exactly.
    By induction the accepted chain's content equals the prompt's
    prefix byte for byte — each comparison is O(page), so a full match
    of a plen-token prompt verifies in O(plen)."""

    __slots__ = ("key", "block", "parent", "page_bytes")

    def __init__(self, key: int, block: int,
                 parent: Optional["_PrefixEntry"],
                 page_bytes: bytes):
        self.key = key
        self.block = block
        self.parent = parent
        self.page_bytes = page_bytes


class PoolExhausted(Exception):
    """Internal invariant breach: extend() needed a block the
    admission reservation did not cover.  Seeing this means the
    accounting is wrong — callers must never trigger it."""


class KVPool:
    """Host-side block accounting for the paged decode twin.

    num_blocks counts the PHYSICAL pool including the scratch block;
    usable capacity is num_blocks - 1.  max_blocks_per_seq is the
    table width (decode_max_seq // page for the bit-identical gather).
    prefix_cache=False restores the PR 6 behavior exactly (no index,
    no refcount sharing, retire frees immediately).
    """

    def __init__(self, num_blocks: int, page_size: int,
                 max_blocks_per_seq: int, prefix_cache: bool = True):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks {num_blocks} < 2 (scratch + at least one "
                "usable block)")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if max_blocks_per_seq < 1:
            raise ValueError(
                f"max_blocks_per_seq must be >= 1, got "
                f"{max_blocks_per_seq}")
        self.num_blocks = int(num_blocks)
        self.page_size = int(page_size)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self.prefix_cache = bool(prefix_cache)
        # LIFO free list: recently-freed blocks are re-used first (their
        # pool rows are the likeliest to still be in cache)
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._tables: Dict[int, List[int]] = {}   # seq id -> block ids
        self._reserved: Dict[int, int] = {}       # seq id -> max PRIVATE
        self._ref: Dict[int, int] = {}            # block -> live tables
        # prefix index: rolling hash of a FULL block-aligned token
        # prefix -> its _PrefixEntry (block + exact per-page
        # verification chain); _block_key maps block -> hash for
        # eviction.  _chain tracks each live sequence's verified entry
        # chain so registration extends it in O(page) per boundary.
        self._index: Dict[int, _PrefixEntry] = {}
        self._block_key: Dict[int, int] = {}
        self._chain: Dict[int, List[_PrefixEntry]] = {}
        # refcount-0 indexed blocks, LRU order (oldest first)
        self._cached: "OrderedDict[int, None]" = OrderedDict()
        # per-seq sharing bookkeeping
        self._shared_of: Dict[int, Set[int]] = {}  # shared-mapped blocks
        self._shared_pin: Dict[int, int] = {}      # block -> sharing seqs
        self._hit_tokens: Dict[int, int] = {}      # matched at admission
        self._prompt: Dict[int, List[int]] = {}    # for live indexing
        self._indexed_upto: Dict[int, int] = {}    # blocks registered
        self._tokens_of: Dict[int, int] = {}       # current token count
        self.peak_used = 0
        self.peak_shared = 0
        self.prefix_hits = 0          # admissions with a non-empty match
        self.prefix_hit_tokens = 0    # total tokens served from cache
        self.prefix_evictions = 0     # cached blocks reclaimed (LRU)
        self.prefix_invalidations = 0  # blocks dropped by a state reset
        self.cow_copies = 0           # tail blocks copy-on-written
        self.prefix_imports = 0           # adopt_prefix calls that landed
        self.prefix_imported_blocks = 0   # blocks adopted from migrations
        # the scheduler worker mutates the pool while /v2/stats reads
        # it from HTTP threads — iteration over _tables must not race
        # a retire()'s pop
        self._lock = threading.Lock()

    # -- capacity ---------------------------------------------------------
    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1

    @property
    def used_blocks(self) -> int:
        """Physical blocks referenced by >= 1 live table — shared
        blocks counted ONCE.  Cached (refcount-0) blocks are
        reclaimable, so they are neither used nor free."""
        return self.usable_blocks - len(self._free) - len(self._cached)

    @property
    def cached_blocks(self) -> int:
        return len(self._cached)

    @property
    def shared_blocks(self) -> int:
        """Distinct physical blocks currently shared-mapped by at
        least one live sequence."""
        return len(self._shared_pin)

    @property
    def reserved_blocks(self) -> int:
        with self._lock:  # /v2/stats reads while the worker admits
            return sum(self._reserved.values())

    def blocks_for(self, tokens: int) -> int:
        """ceil(tokens / page): blocks a sequence of that length needs."""
        return max(1, -(-int(tokens) // self.page_size))

    # -- prefix index (internal; callers hold self._lock) ----------------
    def _match_prefix(self, prompt: Sequence[int]
                      ) -> Tuple[List[int], List["_PrefixEntry"]]:
        """Longest indexed block-aligned prefix of `prompt`, as the
        physical block chain plus the verified entries (walks
        progressively: every sub-prefix of a registered chain was
        registered with it).  O(plen) total: one _hash_block extension
        and one page-bytes compare per boundary — see _PrefixEntry for
        why this is exactly as collision-free as the byte keys."""
        page = self.page_size
        blocks: List[int] = []
        entries: List[_PrefixEntry] = []
        h = _HASH_EMPTY
        parent: Optional[_PrefixEntry] = None
        for j in range(1, len(prompt) // page + 1):
            seg = prompt[(j - 1) * page:j * page]
            h = _hash_block(h, seg)
            e = self._index.get(h)
            if e is None or e.parent is not parent \
                    or e.page_bytes != _page_bytes(seg):
                break
            blocks.append(e.block)
            entries.append(e)
            parent = e
        return blocks, entries

    def _register(self, seq_id: int, tokens: Sequence[int]) -> None:
        """Index every not-yet-registered FULL block of seq_id whose
        page is covered by `tokens` (the sequence's written prefix),
        extending the sequence's verified entry chain one page-hash at
        a time.  First key wins — when the prefix is already indexed
        (same bytes, verified), the existing entry is adopted into the
        chain and this sequence's duplicate block stays
        private-unindexed, freeing normally at retirement.  A FOREIGN
        hash hit (a different prefix colliding, or a chain broken by a
        mid-chain eviction + re-registration) stops indexing this
        sequence for good rather than ever sharing unverified bytes."""
        if not self.prefix_cache:
            return
        page = self.page_size
        table = self._tables[seq_id]
        chain = self._chain.setdefault(seq_id, [])
        b = self._indexed_upto.get(seq_id, 0)
        if b != len(chain):
            return  # invalidation sentinel / previously stopped chain
        while (b + 1) * page <= len(tokens) and b < len(table):
            seg = tokens[b * page:(b + 1) * page]
            h = _hash_block(chain[-1].key if chain else _HASH_EMPTY, seg)
            parent = chain[-1] if chain else None
            e = self._index.get(h)
            if e is not None:
                if e.parent is parent and e.page_bytes == _page_bytes(seg):
                    chain.append(e)
                    b += 1
                    continue
                b = self.max_blocks_per_seq + 1  # foreign: stop for good
                break
            blk = table[b]
            if blk in self._block_key:
                b = self.max_blocks_per_seq + 1
                break
            e = _PrefixEntry(h, blk, parent, _page_bytes(seg))
            self._index[h] = e
            self._block_key[blk] = h
            chain.append(e)
            b += 1
        self._indexed_upto[seq_id] = b

    def _evict_lru(self) -> None:
        blk, _ = self._cached.popitem(last=False)
        key = self._block_key.pop(blk)
        del self._index[key]
        self._free.append(blk)
        self.prefix_evictions += 1
        # longer-prefix entries chained through the evicted one are now
        # unreachable (the match walk stops at the missing parent);
        # their blocks remain LRU-evictable like any cached block

    def _pop_free(self) -> int:
        """A free physical block, reclaiming the LRU cached block under
        capacity pressure (the reservation discipline guarantees one of
        the two sources is non-empty)."""
        if not self._free:
            if not self._cached:
                raise PoolExhausted(
                    "no free or cached block available — the admission "
                    "accounting is wrong")
            self._evict_lru()
        return self._free.pop()

    def invalidate_prefix_cache(self) -> None:
        """Drop every index entry and free all cached blocks — called
        after a device-state reset (a failed step zeroes the pools, so
        cached bytes are garbage).  Live blocks keep their tables; any
        live index entries are dropped too (their content is suspect)."""
        with self._lock:
            for blk in list(self._cached):
                self._free.append(blk)
                # NOT prefix_evictions: that counter means capacity
                # pressure (operators size the pool from it) — a
                # fault-driven invalidation is its own signal
                self.prefix_invalidations += 1
            self._cached.clear()
            self._index.clear()
            self._block_key.clear()
            self._chain.clear()  # every entry object is dead now
            for sid in self._indexed_upto:
                # sentinel past any possible table: live survivors (if
                # any) never re-register their suspect content; new
                # sequences re-populate the index
                self._indexed_upto[sid] = self.max_blocks_per_seq + 1

    # -- lifecycle --------------------------------------------------------
    def try_admit(self, seq_id: int, max_tokens: int,
                  prompt: Optional[Sequence[int]] = None,
                  cow_ok: bool = True) -> bool:
        """Reserve worst-case capacity for a new sequence.  False means
        the pool cannot guarantee the sequence will finish — the caller
        keeps it queued and retries after the next retirement.

        With `prompt` given and the prefix cache on, the longest
        indexed block-aligned prefix is mapped straight into the new
        table (refcount++ per block) and `admit_hit_tokens` reports how
        many tokens skip prefill.  A FULL-prompt hit keeps its last
        shared block only when `cow_ok` (the engine can copy-on-write a
        device block); otherwise the match drops one block so the tail
        is re-prefilled privately."""
        if seq_id in self._reserved:
            raise ValueError(f"sequence {seq_id} already admitted")
        need = self.blocks_for(max_tokens)
        if need > self.max_blocks_per_seq:
            raise ValueError(
                f"sequence {seq_id} needs {need} blocks > table width "
                f"{self.max_blocks_per_seq} (prompt + max_new_tokens "
                f"exceed decode_max_seq)")
        with self._lock:  # raw sum: the lock is not reentrant
            matched: List[int] = []
            entries: List[_PrefixEntry] = []
            full_hit = False
            if self.prefix_cache and prompt is not None:
                matched, entries = self._match_prefix(prompt)
                full_hit = bool(matched) and \
                    len(matched) * self.page_size == len(prompt)
                if full_hit and not cow_ok:
                    matched.pop()  # tail re-prefilled privately instead
                    entries.pop()
                    full_hit = False
            # private worst case: blocks drawn from the free pool —
            # everything past the shared prefix, plus the COW copy of
            # the tail block on a full-prompt hit
            need_priv = need - len(matched) + (1 if full_hit else 0)
            # shared blocks are pinned (unevictable while mapped), so
            # they consume capacity alongside the reservations.  A
            # block both live-private elsewhere and shared here double
            # counts — conservative, never an undercount.
            pinned = set(self._shared_pin) | set(matched)
            if sum(self._reserved.values()) + need_priv + len(pinned) \
                    > self.usable_blocks:
                return False
            self._reserved[seq_id] = need_priv
            self._tables[seq_id] = list(matched)
            self._shared_of[seq_id] = set(matched)
            for blk in matched:
                self._cached.pop(blk, None)  # revive from the cache
                self._ref[blk] = self._ref.get(blk, 0) + 1
                self._shared_pin[blk] = self._shared_pin.get(blk, 0) + 1
            hit = len(matched) * self.page_size
            self._hit_tokens[seq_id] = hit
            self._prompt[seq_id] = (list(int(t) for t in prompt)
                                    if prompt is not None else [])
            self._indexed_upto[seq_id] = len(matched)
            self._chain[seq_id] = list(entries)
            self._tokens_of[seq_id] = hit
            if matched:
                self.prefix_hits += 1
                self.prefix_hit_tokens += hit
            if self.shared_blocks > self.peak_shared:
                self.peak_shared = self.shared_blocks
        return True

    def admit_hit_tokens(self, seq_id: int) -> int:
        """Tokens of seq_id's prompt served from the prefix cache at
        admission (block-aligned; the scheduler skips their prefill)."""
        with self._lock:
            return self._hit_tokens.get(seq_id, 0)

    def cached_prefix_tokens(self, prompt: Sequence[int]) -> int:
        """Read-only probe: tokens of `prompt` the cache would serve if
        admitted now (admission control discounts them — cached tokens
        cost zero prefill steps).  Does not touch LRU order."""
        if not self.prefix_cache:
            return 0
        with self._lock:
            return len(self._match_prefix(prompt)[0]) * self.page_size

    def ensure_writable(self, seq_id: int, pos: int
                        ) -> Optional[Tuple[int, int]]:
        """Copy-on-write guard for the scatter at position `pos`: if
        the target block is shared (refcount > 1) or its content is
        index-pinned, swap a fresh private copy into the table and
        return (src, dst) so the engine copies the device bytes.
        Returns None when the write is already safe.  Only a
        full-prompt hit can reach a shared tail block, but the guard is
        total: NO write path ever touches a block another table or the
        index still vouches for."""
        with self._lock:
            table = self._tables[seq_id]
            bi = pos // self.page_size
            if bi >= len(table):
                return None  # block not allocated yet: fresh by nature
            blk = table[bi]
            if self._ref.get(blk, 0) <= 1 and blk not in self._block_key:
                return None
            dst = self._pop_free()
            table[bi] = dst
            self._ref[dst] = 1
            self._ref[blk] -= 1
            if self._ref[blk] == 0:
                del self._ref[blk]
                if blk in self._block_key:
                    self._cached[blk] = None  # back to the LRU cache
                else:
                    self._free.append(blk)
            shared = self._shared_of[seq_id]
            if blk in shared:
                shared.discard(blk)
                n = self._shared_pin[blk] - 1
                if n:
                    self._shared_pin[blk] = n
                else:
                    del self._shared_pin[blk]
            self.cow_copies += 1
            if self.used_blocks > self.peak_used:
                self.peak_used = self.used_blocks
            return blk, dst

    def extend(self, seq_id: int, tokens: int,
               written: Optional[int] = None) -> List[int]:
        """Grow seq_id's table to cover `tokens` total tokens; returns
        the block ids allocated by THIS call (allocate-on-extend).
        `written` is how many tokens are already in the cache (defaults
        to tokens - 1, the one-token decode step's invariant; chunked
        prefill passes its own) — every full PROMPT block it covers is
        registered in the prefix index."""
        with self._lock:
            table = self._tables[seq_id]
            need = self.blocks_for(tokens)
            shared = len(self._shared_of[seq_id])
            if need - shared > self._reserved[seq_id]:
                raise PoolExhausted(
                    f"sequence {seq_id} grew to {need - shared} private "
                    f"blocks past its reservation of "
                    f"{self._reserved[seq_id]}")
            grown = []
            while len(table) < need:
                blk = self._pop_free()
                self._ref[blk] = 1
                table.append(blk)
                grown.append(blk)
            done = (int(tokens) - 1) if written is None else int(written)
            self._tokens_of[seq_id] = max(
                self._tokens_of.get(seq_id, 0), done)
            prompt = self._prompt.get(seq_id) or []
            if prompt and done > 0:
                self._register(seq_id, prompt[:min(done, len(prompt))])
            if self.used_blocks > self.peak_used:
                self.peak_used = self.used_blocks
            return grown

    def note_written(self, seq_id: int, tokens: int) -> None:
        """Advance seq_id's written-token watermark — the scheduler
        calls this after every step that lands tokens (per-row decode
        advance and the chunked-prefill path), so freshly filled
        prompt blocks join the prefix index immediately and
        fragmentation stays truthful between block boundaries.  Hot
        path: the registration sweep only runs when a NEW full prompt
        block is actually covered."""
        with self._lock:
            if seq_id not in self._tables:
                return
            n = int(tokens)
            if n > self._tokens_of.get(seq_id, 0):
                self._tokens_of[seq_id] = n
            prompt = self._prompt.get(seq_id) or []
            if prompt:
                covered = min(n, len(prompt)) // self.page_size
                if self._indexed_upto.get(seq_id, 0) < covered:
                    self._register(seq_id, prompt[:min(n, len(prompt))])

    def retire(self, seq_id: int,
               tokens: Optional[Sequence[int]] = None) -> None:
        """Drop the sequence: refcount-- on every block.  Blocks whose
        content is indexed stay CACHED (refcount 0, LRU-evictable);
        the rest free immediately.  `tokens` — the sequence's full
        written token list (prompt + generated prefix) — lets the
        generated suffix's full blocks join the prefix index too (k/v
        bytes are a pure function of the token prefix, so a future
        prompt extending this completion hits them)."""
        with self._lock:
            if self.prefix_cache and tokens is not None \
                    and seq_id in self._tables:
                self._register(seq_id, list(int(t) for t in tokens))
            table = self._tables.pop(seq_id)
            for blk in self._shared_of.pop(seq_id, ()):
                n = self._shared_pin.get(blk, 0) - 1
                if n > 0:
                    self._shared_pin[blk] = n
                else:
                    self._shared_pin.pop(blk, None)
            for blk in table:
                self._ref[blk] -= 1
                if self._ref[blk] == 0:
                    del self._ref[blk]
                    if blk in self._block_key:
                        # most-recently-retired = most-recently-used
                        self._cached[blk] = None
                        self._cached.move_to_end(blk)
                    else:
                        self._free.append(blk)
            del self._reserved[seq_id]
            self._hit_tokens.pop(seq_id, None)
            self._prompt.pop(seq_id, None)
            self._indexed_upto.pop(seq_id, None)
            self._chain.pop(seq_id, None)
            self._tokens_of.pop(seq_id, None)

    def rollback(self, seq_id: int, tokens: int
                 ) -> Optional[Tuple[int, int]]:
        """Truncate a LIVE sequence's written positions to a watermark
        of `tokens` — the speculative-decoding reject path and the KV
        import-fallback unwind.  Blocks past the watermark leave the
        table (refcount--, freed or re-cached like retirement); index
        entries this sequence registered for boundaries the watermark
        no longer covers are unregistered, so a future prompt can never
        match content that is about to be overwritten.  The kept
        partial tail block is made writable: if another table or a
        surviving index entry still vouches for it, it is copy-on-
        written and the (src, dst) device copy is returned for the
        engine to perform; otherwise None.  The admission reservation
        is untouched (worst case was booked up front), so the sequence
        can re-extend to its original ceiling."""
        with self._lock:
            if seq_id not in self._tables:
                raise ValueError(f"sequence {seq_id} not admitted")
            tokens = int(tokens)
            shared_tok = len(self._shared_of[seq_id]) * self.page_size
            if tokens < shared_tok:
                raise ValueError(
                    f"rollback to {tokens} would cut into the shared-"
                    f"mapped prefix ({shared_tok} tokens) of sequence "
                    f"{seq_id}")
            if tokens > self._tokens_of.get(seq_id, 0):
                raise ValueError(
                    f"rollback watermark {tokens} is past sequence "
                    f"{seq_id}'s written count "
                    f"{self._tokens_of.get(seq_id, 0)}")
            page = self.page_size
            table = self._tables[seq_id]
            keep = -(-tokens // page)  # ceil; 0 tokens keeps no blocks
            new_indexed = tokens // page
            # unregister OUR chain entries past the new watermark (an
            # adopted entry — another sequence's block — stays: its
            # content is still globally valid)
            chain = self._chain.get(seq_id, [])
            own = set(table) - self._shared_of[seq_id]
            for e in chain[new_indexed:]:
                if e.block in own and self._index.get(e.key) is e:
                    del self._index[e.key]
                    self._block_key.pop(e.block, None)
                    self.prefix_invalidations += 1
            del chain[new_indexed:]
            if self._indexed_upto.get(seq_id, 0) <= \
                    self.max_blocks_per_seq:
                self._indexed_upto[seq_id] = new_indexed
            # drop the uncovered blocks (shared region is below the
            # watermark by the guard above, so these are all private)
            for blk in reversed(table[keep:]):
                self._ref[blk] -= 1
                if self._ref[blk] == 0:
                    del self._ref[blk]
                    if blk in self._block_key:
                        self._cached[blk] = None
                        self._cached.move_to_end(blk)
                    else:
                        self._free.append(blk)
            del table[keep:]
            self._tokens_of[seq_id] = tokens
            # the kept partial tail block will be rewritten at
            # positions >= tokens — copy-on-write it if anything else
            # still vouches for its content
            copy = None
            if tokens % page and keep <= len(table) and keep >= 1:
                blk = table[keep - 1]
                if self._ref.get(blk, 0) > 1 or blk in self._block_key:
                    dst = self._pop_free()
                    table[keep - 1] = dst
                    self._ref[dst] = 1
                    self._ref[blk] -= 1
                    if self._ref[blk] == 0:
                        del self._ref[blk]
                        if blk in self._block_key:
                            self._cached[blk] = None
                        else:
                            self._free.append(blk)
                    shared = self._shared_of[seq_id]
                    if blk in shared:
                        shared.discard(blk)
                        n = self._shared_pin[blk] - 1
                        if n:
                            self._shared_pin[blk] = n
                        else:
                            del self._shared_pin[blk]
                    self.cow_copies += 1
                    copy = (blk, dst)
            if self.used_blocks > self.peak_used:
                self.peak_used = self.used_blocks
            return copy

    # -- KV block export / import (cross-replica migration) ---------------
    def export_prefix(self, prompt: Sequence[int]
                      ) -> Tuple[List[int], List[List[int]]]:
        """(blocks, pages) for the longest indexed block-aligned prefix
        of `prompt`: the physical block ids whose device bytes a
        migration should stream, plus the token page each one holds.
        Verified through the entry chain exactly like admission — a
        hash collision can never export foreign bytes.  Caller must be
        on the scheduler worker thread (the only mutator), so the ids
        stay valid until the device read completes."""
        if not self.prefix_cache:
            return [], []
        page = self.page_size
        with self._lock:
            blocks, _ = self._match_prefix(prompt)
            pages = [list(int(t) for t in prompt[j * page:(j + 1) * page])
                     for j in range(len(blocks))]
            return blocks, pages

    def export_live(self, seq_id: int, tokens: Sequence[int]
                    ) -> Tuple[List[int], List[List[int]]]:
        """(blocks, pages) for a LIVE sequence's written KV state —
        prompt *and* generated blocks, including the partial tail page
        (a mid-decode handoff ships the whole generation, not just the
        indexed prefix).  `tokens` is the written token prefix the
        caller is snapshotting; it must not exceed the sequence's
        written watermark (exporting unwritten device bytes would
        stream garbage).  The last page may be sub-page; the adopter
        lands full pages through adopt_prefix and the tail directly
        into the resumed sequence's private block.  Caller must be on
        the scheduler worker thread (the only mutator), so the ids
        stay valid until the device read completes."""
        page = self.page_size
        with self._lock:
            table = self._tables.get(seq_id)
            if table is None:
                raise KeyError(f"sequence {seq_id} is not live")
            n = len(tokens)
            written = self._tokens_of.get(seq_id, 0)
            if n > written:
                raise ValueError(
                    f"cannot export {n} tokens of sequence {seq_id}: "
                    f"only {written} are written")
            nb = -(-n // page)  # ceil: the tail page may be partial
            blocks = list(table[:nb])
            pages = [list(int(t) for t in tokens[j * page:(j + 1) * page])
                     for j in range(nb)]
            return blocks, pages

    def adopt_prefix(self, prompt: Sequence[int], n_blocks: int
                     ) -> List[Tuple[int, int]]:
        """Admit a migrated prefix into THIS pool as shared cached
        blocks: walk the first `n_blocks` block-aligned pages of
        `prompt`, reusing any boundary already indexed (identical
        bytes — the device content is a pure function of the token
        prefix) and allocating a fresh refcount-0 cached block for each
        missing one.  Returns the (boundary, block) pairs whose device
        bytes the caller must write BEFORE the next admission runs —
        both happen on the scheduler worker thread, so no request can
        map a block whose bytes have not landed.  Stops early (partial
        adoption is still a prefix, so still valid) on a foreign hash
        hit or when the pool has no reclaimable block left."""
        if not self.prefix_cache:
            return []
        page = self.page_size
        pairs: List[Tuple[int, int]] = []
        with self._lock:
            h = _HASH_EMPTY
            parent: Optional[_PrefixEntry] = None
            chain_blocks: set = set()  # this adoption's own blocks
            for j in range(min(int(n_blocks), len(prompt) // page)):
                seg = prompt[j * page:(j + 1) * page]
                h = _hash_block(h, seg)
                e = self._index.get(h)
                if e is not None:
                    if e.parent is not parent \
                            or e.page_bytes != _page_bytes(seg):
                        break  # foreign collision: never share unverified
                    if e.block in self._cached:
                        self._cached.move_to_end(e.block)  # keep chain hot
                    chain_blocks.add(e.block)
                    parent = e
                    continue
                if not self._free and all(
                        b in chain_blocks for b in self._cached):
                    # the only evictable blocks are this chain's own
                    # (LRU would cannibalize a boundary we just
                    # adopted): partial adoption, still a valid prefix
                    break
                blk = self._pop_free()
                chain_blocks.add(blk)
                e = _PrefixEntry(h, blk, parent, _page_bytes(seg))
                self._index[h] = e
                self._block_key[blk] = h
                self._cached[blk] = None
                self._cached.move_to_end(blk)
                pairs.append((j, blk))
                parent = e
            self.prefix_imported_blocks += len(pairs)
            if pairs:
                self.prefix_imports += 1
        return pairs

    def drop_adopted(self, blocks: Sequence[int]) -> None:
        """Unwind adopt_prefix after a failed device write: unregister
        the entries and free the blocks, so no admission can ever map a
        block whose bytes never landed."""
        with self._lock:
            for blk in blocks:
                if blk in self._cached:
                    del self._cached[blk]
                    key = self._block_key.pop(blk, None)
                    if key is not None:
                        self._index.pop(key, None)
                    self._free.append(blk)

    def live_sequences(self) -> List[int]:
        with self._lock:
            return list(self._tables)

    def table_of(self, seq_id: int) -> List[int]:
        with self._lock:
            return list(self._tables[seq_id])

    def table_row(self, seq_id: Optional[int]) -> np.ndarray:
        """[max_blocks_per_seq] int32 row for the device block table;
        unallocated (and idle-slot) entries point at scratch."""
        row = np.full(self.max_blocks_per_seq, SCRATCH_BLOCK, np.int32)
        if seq_id is not None:
            with self._lock:
                table = list(self._tables[seq_id])
            row[:len(table)] = table
        return row

    # -- telemetry --------------------------------------------------------
    def occupancy(self) -> float:
        """Fraction of usable blocks held by live sequences (shared
        blocks counted once; cached blocks are reclaimable and do not
        count)."""
        return self.used_blocks / self.usable_blocks

    def fragmentation(self) -> float:
        """Internal fragmentation: fraction of live-allocated slots not
        holding a written token.  Computed from the pool's OWN
        per-sequence token counts (tracked by extend), so it cannot
        drift from the tables — shared full blocks never waste; only
        each sequence's private tail can."""
        with self._lock:
            alloc = self.used_blocks * self.page_size
            if not alloc:
                return 0.0
            waste = 0
            for sid, table in self._tables.items():
                shared = len(self._shared_of.get(sid, ()))
                priv_alloc = (len(table) - shared) * self.page_size
                priv_tokens = max(
                    0, self._tokens_of.get(sid, 0)
                    - shared * self.page_size)
                waste += max(0, priv_alloc - min(priv_tokens, priv_alloc))
        return waste / alloc

    def prefix_stats(self) -> Dict[str, int]:
        """Prefix-cache telemetry block for /v2/stats and the bench."""
        with self._lock:
            return {
                "hits": self.prefix_hits,
                "hit_tokens": self.prefix_hit_tokens,
                "shared_blocks": len(self._shared_pin),
                "cached_blocks": len(self._cached),
                "evictions": self.prefix_evictions,
                "invalidations": self.prefix_invalidations,
                "cow_copies": self.cow_copies,
                "imports": self.prefix_imports,
                "imported_blocks": self.prefix_imported_blocks,
                "peak_shared_blocks": self.peak_shared,
            }

    def check_invariants(self) -> None:
        """Every block is exactly one of: scratch, free, cached
        (refcount 0 + indexed), or live — and every physical block's
        refcount equals the number of live tables referencing it, with
        cached blocks disjoint from free blocks.  Raises AssertionError
        on leaks, double-frees, or refcount drift (tested property)."""
        with self._lock:
            refcount: Dict[int, int] = {}
            for table in self._tables.values():
                seen = set()
                for blk in table:
                    assert blk not in seen, "block twice in one table"
                    seen.add(blk)
                    refcount[blk] = refcount.get(blk, 0) + 1
            assert SCRATCH_BLOCK not in refcount, "scratch block allocated"
            assert refcount == self._ref, (
                f"refcount drift: tables say {refcount}, "
                f"pool says {self._ref}")
            free = set(self._free)
            cached = set(self._cached)
            assert len(free) == len(self._free), "double-freed block"
            assert not (free & set(refcount)), \
                "block both free and allocated"
            assert not (cached & free), "cached block also free"
            assert not (cached & set(refcount)), \
                "cached block has live references"
            assert free | cached | set(refcount) | {SCRATCH_BLOCK} == \
                set(range(self.num_blocks)), "block leaked"
            assert self.used_blocks == len(refcount)
            for blk in cached:
                assert blk in self._block_key, "cached block unindexed"
            for key, entry in self._index.items():
                assert entry.key == key, "entry keyed under wrong hash"
                assert self._block_key.get(entry.block) == key, \
                    "index/block_key mismatch"
                assert entry.block not in free, \
                    "indexed block on the free list"
                assert len(entry.page_bytes) == 4 * self.page_size, \
                    "entry verification payload is not one page"
            for sid, table in self._tables.items():
                shared = self._shared_of.get(sid, set())
                assert shared <= set(table), "shared block not in table"
                assert len(table) - len(shared) <= self._reserved[sid], \
                    "over-reservation"
            pin: Dict[int, int] = {}
            for shared in self._shared_of.values():
                for blk in shared:
                    pin[blk] = pin.get(blk, 0) + 1
            assert pin == self._shared_pin, "shared-pin drift"
