"""Durable checkpoint offload: mirror verified steps to object storage.

PR 5 made local checkpoints verified and off the critical path; this
module gives them a second, host-loss-surviving tier.  A
`CheckpointOffloader` watches the local manager publish verified steps
and mirrors each one to a `RemoteCheckpointStore` on a background
thread (the same single-writer FIFO machinery as
`async_writer.AsyncCheckpointWriter`), re-verifies the per-leaf crc32
manifest against the REMOTELY READ bytes, and only then advances a
crash-safe `REMOTE_LATEST` pointer — the verify-then-advance protocol
of `checkpoint.py`'s `_LatestPointer`, rebuilt on blob-store
primitives.

Remote layout (under the blob store's `ckpt/` prefix):

    ckpt/step_00000004/state.npz      # the local step dir, mirrored
    ckpt/step_00000004/meta.json
    ckpt/step_00000004/manifest.json
    ckpt/REMOTE_LATEST                # JSON {"step": N}; advanced only
                                      # after remote re-verification,
                                      # via generation-conditional put

Failure policy (docs/RESILIENCE.md "Durable offload & host-loss
recovery"):

  * transient errors retry under a jittered-backoff `RetryPolicy`
    budget on the uploader thread — training never waits;
  * a partial/truncated upload fails the remote crc re-verification:
    `REMOTE_LATEST` stays on the previous verified step and the torn
    remote step is deleted (quarantined-as-a-miss, the exact local
    guarantee);
  * an unavailability window that outlives the retry budget degrades
    the run to local-only durability with a counter
    (`offload_unavailable`) — the mirror is an upgrade, never a stall;
  * a full uploader queue SKIPS the cadence point (counter) instead of
    blocking the step loop: each queued job pins a full checkpoint's
    bytes, and the local tier already holds the step.

Restore walks local -> remote per checkpoint (checkpoint.py); a brand
new host with an empty directory recovers from `REMOTE_LATEST` alone.
"""
from __future__ import annotations

import io
import json
import logging
import re
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..checkpoint import _leaf_crc
from ..store.blobstore import (
    BlobNotFound,
    BlobPreconditionFailed,
    BlobStore,
    BlobStoreError,
    BlobUnavailableError,
    rmtree_blob_prefix,
)
from .async_writer import AsyncCheckpointWriter
from .faults import CheckpointWriteFault, FaultPlan
from .retry import RetryPolicy

_log = logging.getLogger("flexflow_tpu.offload")

#: blob names mirrored per step, in upload order (manifest last: a
#: reader that sees the manifest knows the data blobs were put first)
STEP_FILES = ("state.npz", "meta.json", "manifest.json")
REMOTE_LATEST = "REMOTE_LATEST"

_STEP_KEY_RE = re.compile(r"step_(\d{8})/manifest\.json$")


class RemoteVerifyError(RuntimeError):
    """A mirrored step's remotely-read bytes do not match its manifest."""


#: delta-mirror chain bound: after this many consecutive delta steps the
#: next mirror re-uploads every leaf, so a restore never chases more
#: than MAX_DELTA_CHAIN base fetches and prune's base-retention set
#: stays small
MAX_DELTA_CHAIN = 4


class UploadReport:
    """What upload_step actually moved: the per-leaf delta accounting
    (docs/RESILIENCE.md "Delta mirror")."""

    __slots__ = ("leaves_skipped", "bytes_uploaded", "manifest")

    def __init__(self, leaves_skipped: int, bytes_uploaded: int,
                 manifest: Dict):
        self.leaves_skipped = leaves_skipped
        self.bytes_uploaded = bytes_uploaded
        self.manifest = manifest


class RemoteCheckpointStore:
    """The remote half of the two-tier checkpoint protocol: step
    mirrors + the REMOTE_LATEST pointer, on any BlobStore."""

    def __init__(self, blob: BlobStore, prefix: str = "ckpt/"):
        if prefix and not prefix.endswith("/"):
            prefix += "/"
        self.blob = blob
        self.prefix = prefix

    # -- layout ---------------------------------------------------------
    def _step_prefix(self, step: int) -> str:
        return f"{self.prefix}step_{step:08d}/"

    def _latest_key(self) -> str:
        return f"{self.prefix}{REMOTE_LATEST}"

    def list_steps(self) -> List[int]:
        """Steps with a manifest blob present, ascending.  The manifest
        is uploaded LAST, so its presence implies the data blobs were
        put (their integrity is still only promised by verify)."""
        out = []
        for key in self.blob.list(self.prefix):
            m = _STEP_KEY_RE.search(key)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    # -- REMOTE_LATEST pointer ------------------------------------------
    def read_latest(self) -> Optional[int]:
        try:
            return int(json.loads(self.blob.get(self._latest_key()))["step"])
        except (BlobNotFound, BlobStoreError, ValueError, KeyError,
                TypeError):
            return None

    def latest_verified_step(self) -> Optional[int]:
        """The newest step REMOTE_LATEST committed to, None when the
        pointer is absent or dangling (its step's blobs were pruned or
        never fully landed)."""
        step = self.read_latest()
        if step is None or not self.blob.exists(
            self._step_prefix(step) + "manifest.json"
        ):
            return None
        return step

    def advance_latest(self, step: int, force: bool = False) -> None:
        """Monotonic, lost-update-safe pointer advance: re-reads the
        current generation and writes conditionally, so two uploaders
        racing (e.g. an emergency save racing the background mirror)
        can never regress the pointer."""
        for _ in range(8):
            info = self.blob.stat(self._latest_key())
            gen = info.generation if info is not None else 0
            cur = self.read_latest() if info is not None else None
            if not force and cur is not None and cur >= step:
                return
            payload = json.dumps({"step": int(step)}).encode()
            try:
                self.blob.put(self._latest_key(), payload,
                              if_generation_match=gen)
                return
            except BlobPreconditionFailed:
                continue  # racer advanced it; re-read and re-decide
        raise BlobStoreError(
            f"REMOTE_LATEST contended past retry bound at step {step}"
        )

    # -- upload / verify -------------------------------------------------
    def _delta_files(self, step: int, files: Dict[str, bytes],
                     base_step: int, base_manifest: Dict,
                     ) -> Tuple[Dict[str, bytes], int]:
        """Rewrite one step's upload payload as a per-leaf delta against
        an already-mirrored base: leaves whose manifest crc32 matches
        the base's are dropped from state.npz and annotated in the
        manifest with {"base_step": N} — restore/verify resolve them
        through the base (download_step reassembles the full npz).
        Returns (files', leaves_skipped); returns the input unchanged
        when nothing is skippable or the delta chain is at its bound."""
        try:
            manifest = json.loads(files["manifest.json"])
            base_leaves = base_manifest.get("leaves", {})
            base_depth = int(base_manifest.get("delta_depth", 0))
        except (ValueError, TypeError, AttributeError):
            return files, 0
        if base_depth >= MAX_DELTA_CHAIN:
            return files, 0  # re-anchor: full upload bounds the chain
        leaves = manifest.get("leaves")
        if not isinstance(leaves, dict):
            return files, 0
        unchanged = [
            k for k, spec in leaves.items()
            if isinstance(base_leaves.get(k), dict)
            and base_leaves[k].get("crc32") == spec.get("crc32")
        ]
        if not unchanged:
            return files, 0
        try:
            with np.load(io.BytesIO(files["state.npz"])) as data:
                kept = {
                    k: data[k] for k in data.files if k not in set(unchanged)
                }
        except Exception:  # torn local npz: upload as-is, verify catches it
            return files, 0
        for k in unchanged:
            leaves[k] = dict(leaves[k])
            # FLATTEN the chain: point at the step that actually HOLDS
            # the bytes (the base's own base when the base is itself a
            # delta for this leaf) — restore fetches exactly one extra
            # step per leaf and prune's retention set stays at the
            # anchor steps, not every intermediate delta
            leaves[k]["base_step"] = int(
                base_leaves[k].get("base_step", base_step)
            )
        manifest["delta_depth"] = base_depth + 1
        buf = io.BytesIO()
        np.savez(buf, **kept)
        out = dict(files)
        out["state.npz"] = buf.getvalue()
        out["manifest.json"] = json.dumps(manifest).encode()
        return out, len(unchanged)

    def upload_step(self, step: int, files: Dict[str, bytes],
                    base_step: Optional[int] = None,
                    base_manifest: Optional[Dict] = None) -> UploadReport:
        """Mirror one verified local step: put data blobs, manifest
        last, then re-download and crc-verify before advancing
        REMOTE_LATEST.  A verification failure quarantines the remote
        step (deletes its blobs) and raises RemoteVerifyError — the
        pointer never advances onto unverified bytes.

        `base_step`/`base_manifest` (the previously mirrored step, as
        the offloader tracks it) turn the upload into a per-leaf DELTA:
        leaves whose crc32 is unchanged since the base are not
        re-uploaded — ZeRO-3-sized mirrors stop re-sending frozen
        embeddings and unchanged buffers every cadence point."""
        missing = [n for n in STEP_FILES if n not in files]
        if missing:
            raise ValueError(f"upload_step missing files {missing}")
        skipped = 0
        if base_step is not None and base_manifest and base_step != step:
            files, skipped = self._delta_files(
                step, files, base_step, base_manifest
            )
        prefix = self._step_prefix(step)
        for name in STEP_FILES:
            self.blob.put(prefix + name, files[name])
        try:
            manifest = self.verify_step(step)
        except RemoteVerifyError:
            removed = rmtree_blob_prefix(self.blob, prefix)
            _log.warning(
                "remote step %d failed crc verification; quarantined "
                "(%d blobs removed), REMOTE_LATEST unchanged", step, removed,
            )
            raise
        self.advance_latest(step)
        return UploadReport(
            leaves_skipped=skipped,
            bytes_uploaded=sum(len(b) for b in files.values()),
            manifest=manifest,
        )

    def verify_step(self, step: int) -> Dict:
        """Download one remote step and check every leaf against its
        manifest crc32 (the read side of verify-then-advance).  Returns
        the parsed manifest; raises RemoteVerifyError on any mismatch,
        truncation, or unparseable piece."""
        prefix = self._step_prefix(step)
        try:
            manifest = json.loads(self.blob.get(prefix + "manifest.json"))
            json.loads(self.blob.get(prefix + "meta.json"))  # must parse
            state = self.blob.get(prefix + "state.npz")
        except BlobUnavailableError:
            raise  # transient: caller's retry budget owns this
        except (BlobStoreError, ValueError) as e:
            raise RemoteVerifyError(
                f"remote step {step} unreadable: {e}"
            ) from e
        base_manifests: Dict[int, Dict] = {}
        try:
            with np.load(io.BytesIO(state)) as data:
                leaves = manifest.get("leaves")
                if not isinstance(leaves, dict):
                    raise RemoteVerifyError(
                        f"remote step {step}: manifest has no leaves"
                    )
                for key, spec in leaves.items():
                    base = spec.get("base_step")
                    if base is not None:
                        # delta leaf: its bytes live in the base step's
                        # mirror — verify the base vouches for the SAME
                        # crc (the base's own verify covered the bytes)
                        base = int(base)
                        bm = base_manifests.get(base)
                        if bm is None:
                            try:
                                bm = json.loads(self.blob.get(
                                    self._step_prefix(base)
                                    + "manifest.json"
                                ))
                            except BlobUnavailableError:
                                raise
                            except (BlobStoreError, ValueError) as e:
                                raise RemoteVerifyError(
                                    f"remote step {step}: delta base "
                                    f"{base} unreadable: {e}"
                                ) from e
                            base_manifests[base] = bm
                        bspec = bm.get("leaves", {}).get(key)
                        if (not isinstance(bspec, dict)
                                or bspec.get("crc32") != spec["crc32"]):
                            raise RemoteVerifyError(
                                f"remote step {step}: delta leaf {key!r} "
                                f"not vouched for by base step {base}"
                            )
                        continue
                    if key not in data.files:
                        raise RemoteVerifyError(
                            f"remote step {step}: leaf {key!r} in manifest "
                            "but not in state.npz"
                        )
                    crc = _leaf_crc(data[key])
                    if crc != spec["crc32"]:
                        raise RemoteVerifyError(
                            f"remote step {step}: leaf {key!r} crc32 "
                            f"{crc:#010x} != manifest {spec['crc32']:#010x}"
                        )
                # restore rejects leaves the manifest can't vouch for —
                # blessing them here would green-light a step that
                # cannot actually restore
                for key in data.files:
                    if key not in leaves:
                        raise RemoteVerifyError(
                            f"remote step {step}: leaf {key!r} in "
                            "state.npz but missing from the manifest "
                            "(unverifiable)"
                        )
        except RemoteVerifyError:
            raise
        except BlobUnavailableError:
            raise  # delta-base fetch blip: transient, NOT corruption —
            # wrapping it would quarantine a perfectly good step
        except Exception as e:  # torn npz, zip errors, bad dtypes
            raise RemoteVerifyError(
                f"remote step {step} undecodable: {e}"
            ) from e
        return manifest

    def download_step(self, step: int) -> Dict[str, bytes]:
        """The three step blobs as bytes (restore's materialize source);
        raises BlobNotFound/BlobStoreError straight through.

        Delta mirrors are REASSEMBLED here: leaves the manifest marks
        `base_step` are fetched from their base step's state.npz
        (chasing chains through each base's own manifest), and the
        returned payload is a SELF-CONTAINED full step — the local
        materialize path writes ordinary, annotation-free files."""
        prefix = self._step_prefix(step)
        files = {name: self.blob.get(prefix + name) for name in STEP_FILES}
        try:
            manifest = json.loads(files["manifest.json"])
            leaves = manifest.get("leaves", {})
        except (ValueError, TypeError):
            return files  # unparseable: hand back raw, restore verifies
        if not any(
            isinstance(s, dict) and s.get("base_step") is not None
            for s in leaves.values()
        ):
            return files
        with np.load(io.BytesIO(files["state.npz"])) as data:
            arrays = {k: data[k] for k in data.files}
        npz_cache: Dict[int, Dict[str, np.ndarray]] = {}
        manifest_cache: Dict[int, Dict] = {int(step): manifest}

        def _load_base(s: int):
            if s not in npz_cache:
                p = self._step_prefix(s)
                with np.load(io.BytesIO(self.blob.get(p + "state.npz"))) as d:
                    npz_cache[s] = {k: d[k] for k in d.files}
                manifest_cache[s] = json.loads(
                    self.blob.get(p + "manifest.json")
                )
            return npz_cache[s], manifest_cache[s]

        for key, spec in leaves.items():
            base = spec.get("base_step") if isinstance(spec, dict) else None
            seen = set()
            while base is not None:
                if base in seen:  # defensive: a cyclic chain is corrupt
                    raise BlobStoreError(
                        f"delta chain cycle at step {base} leaf {key!r}"
                    )
                seen.add(base)
                arrs, bman = _load_base(int(base))
                if key in arrs:
                    arrays[key] = arrs[key]
                    base = None
                else:
                    bspec = bman.get("leaves", {}).get(key, {})
                    base = bspec.get("base_step")
                    if base is None:
                        raise BlobStoreError(
                            f"delta leaf {key!r} unresolvable from its "
                            "base chain"
                        )
        for spec in leaves.values():
            if isinstance(spec, dict):
                spec.pop("base_step", None)
        manifest.pop("delta_depth", None)
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        files["state.npz"] = buf.getvalue()
        files["manifest.json"] = json.dumps(manifest).encode()
        return files

    def delete_step(self, step: int) -> int:
        return rmtree_blob_prefix(self.blob, self._step_prefix(step))

    def _base_steps_of(self, step: int) -> List[int]:
        """Base steps a (possibly delta) mirrored step references.
        Store/parse failures PROPAGATE — treating an unreadable
        manifest as 'no bases' would let prune delete a base a kept
        delta still resolves leaves through (prune aborts instead)."""
        try:
            raw = self.blob.get(self._step_prefix(step) + "manifest.json")
        except BlobNotFound:
            return []  # dangling step: nothing it can reference
        manifest = json.loads(raw)
        return sorted({
            int(s["base_step"])
            for s in manifest.get("leaves", {}).values()
            if isinstance(s, dict) and s.get("base_step") is not None
        })

    def prune(self, keep: int) -> int:
        """Keep the `keep` newest mirrored steps; never delete the step
        REMOTE_LATEST names (the remote durability floor, mirroring the
        local manager's never-prune-the-verified-step rule) — NOR any
        base step a kept delta mirror still resolves leaves through
        (transitively: deleting a delta's base would orphan its
        unre-uploaded leaves)."""
        steps = self.list_steps()
        keep_set = set(steps[-max(1, keep):])
        latest = self.read_latest()
        if latest is not None:
            keep_set.add(latest)
        try:
            frontier = list(keep_set)
            while frontier:
                nxt = []
                for s in frontier:
                    for b in self._base_steps_of(s):
                        if b not in keep_set:
                            keep_set.add(b)
                            nxt.append(b)
                frontier = nxt
        except (BlobStoreError, ValueError, TypeError) as e:
            # can't prove which bases are still referenced: deleting
            # anything could orphan a kept delta's leaves — skip this
            # prune round, the next cadence point retries
            _log.warning("remote prune skipped: delta bases "
                         "unresolvable (%s)", e)
            return 0
        removed = 0
        for s in steps:
            if s not in keep_set:
                removed += self.delete_step(s)
        return removed


class CheckpointOffloader:
    """Background mirror of verified local checkpoints to a
    RemoteCheckpointStore.

    `maybe_submit(step, files)` is called by the local checkpoint
    manager right after a step publishes (on the async writer thread
    for wait=False saves — already off the critical path).  It honors
    the `every` cadence, never blocks (a full queue skips with a
    counter), and hands the upload to one daemon uploader thread that
    retries transients under `retry`'s jittered-backoff budget and
    degrades to local-only durability past it."""

    MAX_PENDING_UPLOADS = 2

    def __init__(
        self,
        remote: RemoteCheckpointStore,
        *,
        every: int = 1,
        keep: int = 3,
        retry: Optional[RetryPolicy] = None,
        fault_plan=None,
        registry=None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if every < 1:
            raise ValueError(f"offload cadence must be >= 1, got {every}")
        if keep < 1:
            raise ValueError(f"remote keep must be >= 1, got {keep}")
        self.remote = remote
        self.every = every
        self.keep = keep
        self.retry = retry or RetryPolicy(max_restarts=3, base_backoff=0.05)
        self.fault_plan = fault_plan or FaultPlan()
        self.registry = registry
        self.sleep = sleep
        self._writer = AsyncCheckpointWriter(name="ckpt-offload")
        if registry is not None:
            gauge = registry.gauge("resilience/offload_queue_depth")
            self._writer.depth_cb = gauge.set
        self._submitted = 0  # verified local publishes seen (cadence clock)
        self._last_queued: Optional[int] = None
        # last step that completed upload + remote verification (written
        # on the uploader thread; int read is atomic enough for dedupe)
        self._mirrored: Optional[int] = None
        # ...and its REMOTE manifest — the delta-mirror base: the next
        # upload skips leaves whose crc32 this manifest already vouches
        # for (docs/RESILIENCE.md "Delta mirror")
        self._mirrored_manifest: Optional[Dict] = None
        self.counters: Dict[str, float] = {
            "offload_uploads": 0,      # steps durably mirrored + verified
            "offload_failures": 0,     # uploads abandoned past the budget
            "offload_retries": 0,      # transient-attempt retries
            "offload_skipped": 0,      # cadence points dropped (full queue)
            "offload_verify_failures": 0,  # remote crc misses (quarantined)
            "offload_unavailable": 0,  # degraded-to-local-only events
            "offload_bytes": 0,        # payload bytes durably uploaded
            "offload_leaves_skipped": 0,  # delta-mirror leaves not re-sent
        }

    # -- metrics --------------------------------------------------------
    def _count(self, name: str, n: float = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n
        if self.registry is not None:
            self.registry.counter(f"resilience/{name}").inc(n)

    # -- submission (manager-facing) ------------------------------------
    def maybe_submit(self, step: int, files: Dict[str, bytes],
                     force: bool = False) -> bool:
        """Queue one verified local step for mirroring.  Returns True
        when the job was queued; False when skipped (off-cadence, or
        the uploader is saturated — the step loop must never wait on
        the mirror).  `force` bypasses cadence and (best-effort) queue
        limits — emergency saves use it."""
        if force:
            # an emergency re-submit skips only when the step is KNOWN
            # durably mirrored — a queued-but-abandoned upload (outage
            # past the budget) must get its second chance
            if step == self._mirrored:
                return False
        elif step == self._last_queued:
            return False  # already queued (a restore-replay re-save)
        self._submitted += 1
        if not force and (self._submitted - 1) % self.every:
            return False
        if not force and self._writer.queue_depth >= self.MAX_PENDING_UPLOADS:
            self._count("offload_skipped")
            _log.warning(
                "offload queue saturated (%d pending): skipping step %d "
                "(local tier still holds it)",
                self._writer.queue_depth, step,
            )
            return False
        self._writer.submit(step, lambda: self._upload_job(step, files))
        self._last_queued = step
        return True

    @property
    def queue_depth(self) -> int:
        return self._writer.queue_depth

    # -- uploader thread --------------------------------------------------
    def _upload_job(self, step: int, files: Dict[str, bytes]) -> None:
        if step == self._mirrored:
            # duplicate job: an emergency force-submit raced the
            # cadence upload of the same step and that one has already
            # landed verified — don't burn the grace window re-uploading
            # (and double-counting) the identical payload
            return
        attempts = 0
        t0 = time.perf_counter()
        while True:
            try:
                # injected uploader-path CheckpointWriteFault (payload
                # target="remote"): fires once, then the retry succeeds
                self.fault_plan.check_offload(step)
                report = self.remote.upload_step(
                    step, files,
                    base_step=self._mirrored,
                    base_manifest=self._mirrored_manifest,
                )
            except Exception as e:  # noqa: BLE001 — classified below
                transient = isinstance(
                    e, (BlobUnavailableError, RemoteVerifyError,
                        CheckpointWriteFault, OSError)
                )
                if isinstance(e, RemoteVerifyError):
                    self._count("offload_verify_failures")
                if not transient:
                    self._count("offload_failures")
                    _log.warning(
                        "offload of step %d failed permanently: %s", step, e,
                    )
                    return
                attempts += 1
                if not self.retry.admits(attempts):
                    # past the budget: degrade to local-only durability —
                    # the run keeps training, the mirror catches up at
                    # the next cadence point if the store comes back
                    self._count("offload_failures")
                    if isinstance(e, BlobUnavailableError):
                        self._count("offload_unavailable")
                    _log.warning(
                        "offload of step %d abandoned after %d attempts "
                        "(%s); continuing with local-only durability",
                        step, attempts, e,
                    )
                    return
                self._count("offload_retries")
                self.sleep(self.retry.backoff(attempts))
                continue
            break
        self._count("offload_uploads")
        self._count("offload_bytes", report.bytes_uploaded)
        if report.leaves_skipped:
            self._count("offload_leaves_skipped", report.leaves_skipped)
        self._mirrored_manifest = report.manifest
        self._mirrored = step
        if self.registry is not None:
            self.registry.histogram("resilience/offload_upload_ms").observe(
                (time.perf_counter() - t0) * 1e3
            )
        try:
            self.remote.prune(self.keep)
        except BlobStoreError as e:
            _log.info("remote prune after step %d failed: %s", step, e)

    # -- lifecycle -------------------------------------------------------
    def drain(self) -> List:
        """Block until queued uploads finish (or are abandoned within
        their budgets).  Upload failures are already folded into
        counters — the returned list covers only uploader-thread
        crashes (a bug, not a store failure)."""
        return self._writer.drain()

    def close(self) -> None:
        self._writer.close()


def offloader_from_config(cfg, *, blob: Optional[BlobStore] = None,
                          fault_plan=None, registry=None,
                          sleep: Callable[[float], None] = time.sleep,
                          ) -> Optional[CheckpointOffloader]:
    """Build the run's CheckpointOffloader from FFConfig
    (remote_store/offload_every/remote_keep), or None when no remote
    tier is configured.  `blob` overrides the URI resolution (tests
    inject FaultyBlobStore here); an unusable remote root degrades to
    offload-off with a log line — durability tiers are upgrades, never
    crash sources."""
    uri = getattr(cfg, "remote_store", None)
    if blob is None:
        if not uri or str(uri).strip().lower() == "none":
            return None
        from ..store.blobstore import blobstore_from_uri

        try:
            blob = blobstore_from_uri(uri)
        except (OSError, ValueError, NotImplementedError) as e:
            _log.warning(
                "remote store %r unusable (%s); continuing without the "
                "offload tier", uri, e,
            )
            return None
    remote = RemoteCheckpointStore(blob)
    return CheckpointOffloader(
        remote,
        every=max(1, int(getattr(cfg, "offload_every", 1))),
        keep=max(1, int(getattr(cfg, "remote_keep", 3))),
        retry=RetryPolicy(
            max_restarts=getattr(cfg, "max_restarts", 3),
            base_backoff=getattr(cfg, "retry_backoff", 0.1),
            seed=getattr(cfg, "seed", 0),
        ),
        fault_plan=fault_plan,
        registry=registry,
        sleep=sleep,
    )


__all__ = [
    "MAX_DELTA_CHAIN",
    "REMOTE_LATEST",
    "STEP_FILES",
    "CheckpointOffloader",
    "RemoteCheckpointStore",
    "RemoteVerifyError",
    "UploadReport",
    "offloader_from_config",
]
