"""Deterministic fault injection for the training supervisor.

The reference leans on Legion for fault handling (task replay +
checkpointable regions); this rebuild targets the TPU reality instead:
preemptible slices, host drops, transient step failures, and device
loss shrinking the visible mesh.  A `FaultPlan` is a seeded, replayable
schedule of such failures so every recovery path in
`resilience/supervisor.py` is testable on a CPU mesh in tier-1 — no
real hardware has to die to exercise the restore/re-search machinery.

Fault matrix (see docs/RESILIENCE.md):

  kind              raised as             supervisor reaction
  ----------------  --------------------  ----------------------------
  step_exception    StepFault             restore latest + retry
  host_preemption   PreemptionFault       restore latest + retry
  checkpoint_write  CheckpointWriteFault  count, keep training
  device_loss       DeviceLossFault       re-search surviving mesh,
                                          recompile, reshard-restore
  hung_step         HungStepFault         device-loss-style: re-search
                                          + recompile the full mesh,
                                          reshard-restore (the injected
                                          twin of a real watchdog
                                          HungStepTimeout)
  nan_loss          (batch poisoned)      per FFConfig.nan_policy

Object-store fault matrix (store/blobstore.py FaultyBlobStore consumes
these; the training loop never sees them directly — the offload tier
retries/degrades, docs/RESILIENCE.md "Durable offload"):

  kind                 effect                    offloader reaction
  -------------------  ------------------------  ----------------------
  blob_transient       one op raises             retry under the
                       BlobUnavailableError      backoff budget
  blob_partial_upload  one put lands truncated   remote crc verify
                       bytes                     fails; REMOTE_LATEST
                                                 stays; step quarantined
  blob_latency         one op sleeps delay_s     absorbed off the
                                                 critical path
  blob_unavailable     `ops` consecutive ops     degrade to local-only
                       raise                     with a counter

For blob kinds, `Fault.step` is the FaultyBlobStore *operation index*
(fire at or after the Nth blob op), not a training step — an upload's
op count is deterministic, so seeded plans replay exactly.
"""
from __future__ import annotations

import dataclasses
import enum
import json
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np


class FaultKind(str, enum.Enum):
    STEP_EXCEPTION = "step_exception"
    HOST_PREEMPTION = "host_preemption"
    CHECKPOINT_WRITE = "checkpoint_write"
    DEVICE_LOSS = "device_loss"
    # a wedged collective: the step's device sync never returns.  The
    # injected form raises at the step boundary so the supervisor's
    # hung-step classification (resilience/watchdog.py) is exercisable
    # without a real hang or a real timeout wait
    HUNG_STEP = "hung_step"
    # transient data corruption: the step's float inputs become NaN for
    # exactly one step, driving the loss non-finite (exercises
    # FFConfig.nan_policy end to end without faking metrics)
    NAN_LOSS = "nan_loss"
    # -- object-store faults (consumed by store.blobstore.FaultyBlobStore,
    #    never raised into the training loop; step = blob op index) ------
    BLOB_TRANSIENT = "blob_transient"
    BLOB_PARTIAL_UPLOAD = "blob_partial_upload"
    BLOB_LATENCY = "blob_latency"
    BLOB_UNAVAILABLE = "blob_unavailable"


#: FaultKinds handled by FaultyBlobStore rather than the supervisor
BLOB_FAULT_KINDS = frozenset({
    FaultKind.BLOB_TRANSIENT,
    FaultKind.BLOB_PARTIAL_UPLOAD,
    FaultKind.BLOB_LATENCY,
    FaultKind.BLOB_UNAVAILABLE,
})


class InjectedFault(RuntimeError):
    """Base of all injected failures (never raised by real code paths)."""

    kind: FaultKind

    def __init__(self, step: int, **payload):
        self.step = step
        self.payload = payload
        extra = f" {payload}" if payload else ""
        super().__init__(f"injected {self.kind.value} at step {step}{extra}")


class StepFault(InjectedFault):
    kind = FaultKind.STEP_EXCEPTION


class PreemptionFault(InjectedFault):
    kind = FaultKind.HOST_PREEMPTION


class CheckpointWriteFault(InjectedFault):
    kind = FaultKind.CHECKPOINT_WRITE


class DeviceLossFault(InjectedFault):
    kind = FaultKind.DEVICE_LOSS

    def __init__(self, step: int, survivors: int):
        super().__init__(step, survivors=survivors)
        self.survivors = int(survivors)


class HungStepFault(InjectedFault):
    kind = FaultKind.HUNG_STEP


_EXC_FOR_KIND = {
    FaultKind.STEP_EXCEPTION: StepFault,
    FaultKind.HOST_PREEMPTION: PreemptionFault,
    FaultKind.DEVICE_LOSS: DeviceLossFault,
    FaultKind.HUNG_STEP: HungStepFault,
}


@dataclasses.dataclass
class Fault:
    """One scheduled failure.  `step` is the supervisor step index the
    fault targets; `payload` carries kind-specific data (device_loss:
    {"survivors": n}).  A fault fires at most once — after a restore
    rewinds the step counter past it, replay does NOT re-fail (the
    transient is gone), which is exactly what makes recovery testable."""

    step: int
    kind: FaultKind
    payload: Dict = dataclasses.field(default_factory=dict)
    fired: bool = False


class FaultPlan:
    """A deterministic, seeded schedule of injected failures."""

    def __init__(self, faults: Iterable[Fault] = ()):
        self.faults: List[Fault] = [
            f if isinstance(f, Fault) else Fault(**f) for f in faults
        ]

    # -- constructors ---------------------------------------------------
    @classmethod
    def single(cls, step: int, kind: FaultKind, **payload) -> "FaultPlan":
        return cls([Fault(step=step, kind=FaultKind(kind), payload=payload)])

    @classmethod
    def seeded(
        cls,
        seed: int,
        num_steps: int,
        kinds: Sequence[FaultKind] = (FaultKind.STEP_EXCEPTION,),
        count: int = 1,
        survivors: Optional[int] = None,
    ) -> "FaultPlan":
        """`count` faults at rng-chosen distinct steps in [1, num_steps).
        Same seed -> same plan, so a failing recovery run replays
        exactly.  device_loss faults require `survivors`."""
        if num_steps < 2:
            raise ValueError("need num_steps >= 2 to place faults")
        rng = np.random.RandomState(seed)
        count = min(count, num_steps - 1)
        steps = sorted(
            int(s) for s in rng.choice(
                np.arange(1, num_steps), size=count, replace=False
            )
        )
        faults = []
        for s in steps:
            kind = FaultKind(kinds[int(rng.randint(len(kinds)))])
            payload = {}
            if kind == FaultKind.DEVICE_LOSS:
                if survivors is None:
                    raise ValueError("device_loss faults need survivors=")
                payload["survivors"] = int(survivors)
            faults.append(Fault(step=s, kind=kind, payload=payload))
        return cls(faults)

    # -- injection points (called by the supervisor) --------------------
    def check_step(self, step: int) -> None:
        """Raise the scheduled failure for this exact step, once."""
        for f in self.faults:
            if f.fired or f.step != step or f.kind not in _EXC_FOR_KIND:
                continue
            f.fired = True
            raise _EXC_FOR_KIND[f.kind](step, **f.payload)

    def corrupt_batch(self, step: int, inputs: Dict[str, np.ndarray]):
        """Apply a one-shot nan_loss fault: poison every float input of
        this step's batch with NaN (a transient bad-data / bit-flip
        stand-in).  Returns the (possibly replaced) inputs dict."""
        for f in self.faults:
            if f.fired or f.step != step or f.kind != FaultKind.NAN_LOSS:
                continue
            f.fired = True
            return {
                k: (
                    np.full_like(v, np.nan)
                    if np.issubdtype(np.asarray(v).dtype, np.floating)
                    else v
                )
                for k, v in inputs.items()
            }
        return inputs

    def check_checkpoint(self, step: int) -> None:
        """Fail the first LOCAL checkpoint save attempted at or after
        the fault's step (cadence rarely lands exactly on it), once.
        Faults with payload target="remote" belong to the uploader path
        (check_offload) and are skipped here."""
        for f in self.faults:
            if f.fired or f.kind != FaultKind.CHECKPOINT_WRITE or step < f.step:
                continue
            if f.payload.get("target") == "remote":
                continue
            f.fired = True
            raise CheckpointWriteFault(step)

    def check_offload(self, step: int) -> None:
        """The uploader-path twin of check_checkpoint: fail the first
        remote mirror attempt at or after the fault's step, once.  Only
        CHECKPOINT_WRITE faults with payload target="remote" fire here —
        a plan can break the local write, the upload, or both
        independently."""
        for f in self.faults:
            if f.fired or f.kind != FaultKind.CHECKPOINT_WRITE or step < f.step:
                continue
            if f.payload.get("target") != "remote":
                continue
            f.fired = True
            raise CheckpointWriteFault(step, target="remote")

    def blob_faults(self) -> List[Fault]:
        """The plan's object-store faults (consumed by
        store.blobstore.FaultyBlobStore; the supervisor's own injection
        points ignore these kinds)."""
        return [f for f in self.faults if f.kind in BLOB_FAULT_KINDS]

    # -- introspection / replay -----------------------------------------
    def remaining(self) -> List[Fault]:
        return [f for f in self.faults if not f.fired]

    def to_json(self) -> str:
        return json.dumps(
            [
                {"step": f.step, "kind": f.kind.value, "payload": f.payload}
                for f in self.faults
            ]
        )

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        return cls(
            Fault(step=d["step"], kind=FaultKind(d["kind"]),
                  payload=dict(d.get("payload", {})))
            for d in json.loads(text)
        )
