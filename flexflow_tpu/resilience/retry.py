"""Retry/backoff supervision policy.

Jittered exponential backoff with a hard restart budget.  The jitter is
deterministic per (seed, attempt) — a supervisor run is replayable
end-to-end, which matters when a recovery path itself is the thing
under test (FaultPlan and RetryPolicy share the "seeded everything"
discipline of the search code).
"""
from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class RetryPolicy:
    """max_restarts: total restore-and-retry attempts a run may spend
    before the supervisor gives up and re-raises (the restart budget).
    backoff(attempt) grows base_backoff * multiplier**(attempt-1),
    capped at max_backoff, with ±jitter fractional noise so a fleet of
    preempted workers doesn't stampede the checkpoint store in sync."""

    max_restarts: int = 3
    base_backoff: float = 0.1
    multiplier: float = 2.0
    max_backoff: float = 30.0
    jitter: float = 0.1
    seed: int = 0

    def __post_init__(self):
        if self.max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got {self.max_restarts}")
        if self.base_backoff < 0 or self.max_backoff < 0:
            raise ValueError("backoff times must be >= 0")
        if not (0.0 <= self.jitter < 1.0):
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")
        if self.multiplier < 1.0:
            raise ValueError(f"multiplier must be >= 1, got {self.multiplier}")

    def admits(self, restarts: int) -> bool:
        """True while the `restarts`-th restart is within budget."""
        return restarts <= self.max_restarts

    def backoff(self, attempt: int) -> float:
        """Delay in seconds before the `attempt`-th retry (1-based)."""
        attempt = max(1, int(attempt))
        base = min(
            self.max_backoff,
            self.base_backoff * self.multiplier ** (attempt - 1),
        )
        if self.jitter == 0.0 or base == 0.0:
            return base
        rng = np.random.RandomState(
            (self.seed * 1_000_003 + attempt) % (2 ** 32)
        )
        return float(base * (1.0 + self.jitter * rng.uniform(-1.0, 1.0)))
