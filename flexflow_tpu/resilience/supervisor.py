"""TrainingSupervisor: "a failure happened, recover and keep training".

Composes three pieces the repo already had in isolation — checkpoints
that reshard on restore (checkpoint.py), `FFModel.recompile` strategy
swaps (recompile.py), and the strategy searches (pcg/search.py) — into
a supervised training loop:

  * periodic checkpoints at a configurable step cadence (plus an anchor
    at step 0, so the very first failure has a restore target), written
    synchronously or — with `checkpoint_async` — as async verified
    saves that stall the accelerator only for the host snapshot;
  * on a transient failure (injected step exception / host preemption,
    or a non-finite loss under nan_policy="restore"), restore the
    latest checkpoint and retry under a jittered-backoff RetryPolicy
    with a hard restart budget;
  * on device loss, re-run the strategy search (unity or MCMC per
    FFConfig, data-parallel fallback) on the SURVIVING mesh in the
    spirit of P²'s re-placement, `recompile()` onto the shrunken
    device set, and carry weights/optimizer state over via the
    checkpoint's reshard-on-restore — training continues at full
    remaining-hardware speed under a freshly searched strategy;
  * on a hung step — a per-step device sync exceeding `step_timeout`
    (watchdog.py), or an injected `HungStepFault` — classify it as a
    device-loss-style fault on the FULL current mesh: re-search,
    recompile (which resets the wedged collective state), and
    reshard-restore;
  * on SIGTERM/SIGINT (the standard TPU preemption notice), finish the
    in-flight step, write an emergency checkpoint at the step boundary,
    drain the async writer, and return a restorable report instead of
    dying checkpoint-less (`run(..., resume=True)` picks the next
    process up from it).

The loop is step-indexed and deterministic: batch `i` of a run is
always rows [i*bs, (i+1)*bs) modulo the dataset (no shuffle), and the
training RNG is checkpointed, so a crashed-and-restored run replays to
weights BIT-IDENTICAL to an uninterrupted run at the same step count on
the same mesh (tests/test_resilience.py enforces this).
"""
from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
from typing import Callable, Dict, List, Optional

import jax
import numpy as np

from ..checkpoint import CheckpointVerifyError
from ..executor import NonFiniteLossError, check_step_health
from ..logger import resilience_logger
from ..obs.metrics import emit_counters, registry_of
from ..obs.trace import tracer_of
from .faults import (
    CheckpointWriteFault,
    DeviceLossFault,
    FaultPlan,
    HungStepFault,
    PreemptionFault,
    StepFault,
)
from .retry import RetryPolicy
from .watchdog import HungStepTimeout, StepWatchdog

# failures the supervisor treats as restore-and-retry transients
TRANSIENT_FAULTS = (StepFault, PreemptionFault)
# failures classified as "the mesh wedged": recover by re-search +
# recompile of the full current mesh + reshard-restore
HUNG_FAULTS = (HungStepFault, HungStepTimeout)
# signals treated as a preemption notice (the TPU runtime sends SIGTERM
# ahead of reclaiming a preemptible slice; SIGINT covers operators)
GRACE_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class RestartBudgetExhausted(RuntimeError):
    """Raised when failures outrun RetryPolicy.max_restarts."""


@dataclasses.dataclass
class SupervisorReport:
    """What a supervised run did: the step it reached, the per-step
    losses actually recorded, and the counters dict (also logged via
    RecursiveLogger.counters for bench runs to scrape).  `preempted`
    carries the signal name when the run stopped early on a
    SIGTERM/SIGINT emergency checkpoint (resume with
    `run(..., resume=True)`)."""

    final_step: int
    losses: List[float]
    counters: Dict[str, float]
    preempted: Optional[str] = None


class TrainingSupervisor:
    """Wraps a compiled FFModel's training loop with checkpointing,
    retry/backoff recovery, preemption grace, a hung-step watchdog,
    and elastic re-search on device loss.

    Knobs default from the model's FFConfig (checkpoint_every,
    checkpoint_keep, checkpoint_async, step_timeout, preempt_grace,
    max_restarts, retry_backoff, nan_policy); the keyword arguments
    override per-supervisor.  `sleep` is injectable so tests don't
    actually wait out backoffs; `search_fn(ff, n)` overrides the
    strategy re-search on device loss.
    """

    def __init__(
        self,
        ff,
        directory: str,
        *,
        checkpoint_every: Optional[int] = None,
        keep: Optional[int] = None,
        retry: Optional[RetryPolicy] = None,
        fault_plan: Optional[FaultPlan] = None,
        nan_policy: Optional[str] = None,
        search_fn: Optional[Callable] = None,
        backend: str = "local",
        async_save: Optional[bool] = None,
        step_timeout: Optional[float] = None,
        preempt_grace: Optional[bool] = None,
        offloader=None,
        blob_store=None,
        run_id: Optional[str] = None,
        sleep: Callable[[float], None] = time.sleep,
        logger=resilience_logger,
    ):
        from ..config import NAN_POLICIES

        cfg = ff.config
        self.ff = ff
        self.checkpoint_every = (
            cfg.checkpoint_every if checkpoint_every is None else checkpoint_every
        )
        self.retry = retry or RetryPolicy(
            max_restarts=cfg.max_restarts,
            base_backoff=cfg.retry_backoff,
            seed=cfg.seed,
        )
        self.fault_plan = fault_plan or FaultPlan()
        self.nan_policy = cfg.nan_policy if nan_policy is None else nan_policy
        if self.nan_policy not in NAN_POLICIES:
            raise ValueError(
                f"nan_policy must be one of {NAN_POLICIES}, got {self.nan_policy!r}"
            )
        self.search_fn = search_fn
        self.sleep = sleep
        self.log = logger
        self.async_save = (
            getattr(cfg, "checkpoint_async", False)
            if async_save is None else bool(async_save)
        )
        self.watchdog = StepWatchdog(
            getattr(cfg, "step_timeout", 0.0)
            if step_timeout is None else step_timeout
        )
        self.preempt_grace = (
            getattr(cfg, "preempt_grace", True)
            if preempt_grace is None else bool(preempt_grace)
        )
        self._preempt: Optional[str] = None
        # durable offload tier (resilience/offload.py): mirrors every
        # verified local checkpoint to object storage off the critical
        # path.  Tests inject a pre-built offloader (or a faulty blob
        # store); production resolves FFConfig.remote_store.
        self.offloader = offloader
        if self.offloader is None:
            from .offload import offloader_from_config

            self.offloader = offloader_from_config(
                cfg, blob=blob_store, fault_plan=self.fault_plan,
                registry=registry_of(ff), sleep=sleep,
            )
        # names the cross-host preemption-barrier rendezvous in the blob
        # store; every worker of one run must agree on it
        self._run_id_defaulted = run_id is None
        self.run_id = run_id or os.path.basename(
            os.path.abspath(directory)
        ) or "run"
        self.barrier_timeout = float(getattr(cfg, "barrier_timeout", 30.0))
        keep = cfg.checkpoint_keep if keep is None else keep
        if backend == "orbax":
            from ..checkpoint import CheckpointManager

            self.manager = CheckpointManager(
                directory, max_to_keep=keep,
                remote=(self.offloader.remote
                        if self.offloader is not None else None),
            )
        elif backend == "local":
            from ..checkpoint import LocalCheckpointManager

            self.manager = LocalCheckpointManager(
                directory, max_to_keep=keep, offloader=self.offloader,
            )
        else:
            raise ValueError(f"unknown checkpoint backend {backend!r}")
        self.counters: Dict[str, float] = {
            "steps_run": 0,        # train_step invocations, replays included
            "restarts": 0,         # restore events (transient + device loss)
            "retries": 0,          # transient-failure retry attempts
            "lost_steps": 0,       # steps of progress replayed after restores
            "skipped_steps": 0,    # batches dropped under nan_policy=skip_step
            "checkpoints": 0,
            "checkpoint_failures": 0,
            "checkpoint_time_s": 0.0,
            "checkpoint_time_last_s": 0.0,
            "device_losses": 0,
            "hung_steps": 0,       # watchdog timeouts + injected hangs
            "emergency_saves": 0,  # preemption-grace checkpoints
            "re_searches": 0,
            "re_search_store_hits": 0,  # elastic re-searches answered
                                        # by the strategy store
        }

    # -- deterministic batching -----------------------------------------
    def _x_map(self, x) -> Dict[str, np.ndarray]:
        input_ops = self.ff.layers.source_ops()
        if isinstance(x, dict):
            return dict(x)
        if isinstance(x, (list, tuple)):
            return {op.name: arr for op, arr in zip(input_ops, x)}
        return {input_ops[0].name: x}

    @staticmethod
    def _batch(x_map, y, step: int, batch_size: int, num_batches: int):
        i = step % num_batches
        sl = slice(i * batch_size, (i + 1) * batch_size)
        return {k: v[sl] for k, v in x_map.items()}, y[sl]

    # -- checkpoint / restore -------------------------------------------
    def _save_checkpoint(self, step: int, wait: Optional[bool] = None) -> None:
        self.fault_plan.check_checkpoint(step)
        if wait is None:
            wait = not self.async_save
        t0 = time.perf_counter()
        self.manager.save(self.ff, step, wait=wait)
        # async mode: this is the step-boundary STALL (snapshot +
        # enqueue), not the full write — the flush overlaps training
        dt = time.perf_counter() - t0
        self.counters["checkpoints"] += 1
        self.counters["checkpoint_time_s"] += dt
        self.counters["checkpoint_time_last_s"] = dt

    def _save_checkpoint_survivable(self, step: int,
                                    wait: Optional[bool] = None) -> None:
        """A failed periodic save — injected or real (disk full, NFS
        blip, a write-time crc verification miss) — costs that save,
        never the run: count it and keep training; the next cadence
        point writes a fresh one."""
        try:
            self._save_checkpoint(step, wait=wait)
        except (CheckpointWriteFault, CheckpointVerifyError, OSError) as e:
            self.counters["checkpoint_failures"] += 1
            self.log.info("checkpoint save failed at step %d: %s", step, e)

    def _drain_writer(self) -> None:
        """Wait out pending async saves; fold their failures into the
        checkpoint counters (an async write failure surfaces here, not
        at the save() call that queued it)."""
        for failed_step, err in self.manager.drain():
            self.counters["checkpoint_failures"] += 1
            self.log.info(
                "async checkpoint save failed at step %d: %s", failed_step, err
            )

    def _drain_offloader(self) -> None:
        """Wait out pending remote mirrors.  Upload failures were
        already folded into the offloader's counters by its budget
        logic; anything returned here is an uploader-thread crash."""
        if self.offloader is None:
            return
        for failed_step, err in self.offloader.drain():
            self.counters["checkpoint_failures"] += 1
            self.log.info(
                "offload uploader crashed at step %d: %s", failed_step, err
            )

    def _restore_latest(self, step: int) -> int:
        # a pending async save may be the newest durable state — let it
        # land (or fail) before picking the restore target
        self._drain_writer()
        with tracer_of(self.ff).span("restart", cat="resilience",
                                     failed_step=step):
            restored = int(self.manager.restore(self.ff))
        self.counters["restarts"] += 1
        self.counters["lost_steps"] += max(0, step - restored)
        self.log.info(
            "restored step %d after failure at step %d", restored, step
        )
        return restored

    # -- recovery paths --------------------------------------------------
    def _retry_transient(self, err, step: int, restarts: int) -> int:
        self.counters["retries"] += 1
        if not self.retry.admits(restarts):
            raise RestartBudgetExhausted(
                f"restart budget ({self.retry.max_restarts}) exhausted at "
                f"step {step}: {err}"
            ) from err
        self.sleep(self.retry.backoff(restarts))
        return self._restore_latest(step)

    def _search_strategy(self, num_devices: int):
        if self.search_fn is not None:
            return self.search_fn(self.ff, num_devices)
        cfg = self.ff.config
        if cfg.search_budget > 0 and not cfg.only_data_parallel:
            # elastic fast path: the strategy store may already hold a
            # searched plan for this degraded mesh (a previous loss at
            # the same survivor count, or a pre-seeded fleet store) —
            # cached_search consults it before paying a full re-search
            # and publishes on a miss so the NEXT loss is instant
            from ..pcg.search import mcmc_search, unity_search
            from ..store import cached_search

            def _run():
                if cfg.search_algo == "mcmc":
                    s = mcmc_search(self.ff, num_devices)
                else:
                    s = unity_search(self.ff, num_devices)
                # same pre-publish provenance stamp as FFModel.compile's
                # search path: a store entry restored on another host
                # must carry the catalog identity its rewrite trace was
                # searched with (rewrite.rules_for_replay pins the hash)
                self.ff._stamp_catalog(s)
                return s

            # pipeline winners restore fine since checkpoint.py learned
            # the per-op <-> __pipeline__ stacked layout mapping
            # (_adapt_saved_layout), so the former pipeline-exclusion
            # re-run is gone: whatever the search picks, reshard-restore
            # carries the trained state onto it
            strategy = cached_search(self.ff, num_devices, _run)
            if (getattr(strategy, "search_stats", None) or {}).get(
                "store_hit"
            ):
                self.counters["re_search_store_hits"] += 1
            return strategy
        from ..strategy import data_parallel_strategy

        return data_parallel_strategy(num_devices)

    def _elastic_restart(self, survivors: List, step: int, reason: str) -> int:
        """Re-search placement for `survivors`, recompile onto them,
        and reshard-restore the latest checkpoint so trained state
        carries over to the rebuilt executor."""
        with tracer_of(self.ff).span("re_search", cat="resilience",
                                     survivors=len(survivors), reason=reason):
            strategy = self._search_strategy(len(survivors))
        self.counters["re_searches"] += 1
        # recompile rebuilds the executor (fresh shardings, fresh
        # collective state); the checkpoint restore then overwrites the
        # carried state with the last durable state, resharded onto it
        self.ff.recompile(
            strategy=strategy, devices=survivors[: strategy.total_devices]
        )
        return self._restore_latest(step)

    def _recover_device_loss(self, fault: DeviceLossFault, step: int) -> int:
        """Elastic recovery: re-search placement for the surviving
        topology, recompile onto it, and reshard-restore the latest
        checkpoint so trained state carries over to the new mesh."""
        survivors = list(self.ff.mesh.devices.flat)[: fault.survivors]
        if not survivors:
            raise RuntimeError(f"device loss left no survivors: {fault}")
        self.counters["device_losses"] += 1
        self.log.info(
            "device loss at step %d: %d devices survive, re-searching",
            step, len(survivors),
        )
        return self._elastic_restart(survivors, step, reason="device_loss")

    def _recover_hung_step(self, err, step: int, restarts: int) -> int:
        """A hung step (watchdog timeout or injected HungStepFault) is
        a device-loss-style fault with the FULL mesh surviving: the
        devices are still there, the collective state is wedged, and
        recompile + reshard-restore resets it.  Counts against the
        restart budget — a mesh that hangs on every recovery attempt
        must eventually fail loudly, not loop forever."""
        self.counters["hung_steps"] += 1
        if not self.retry.admits(restarts):
            raise RestartBudgetExhausted(
                f"restart budget ({self.retry.max_restarts}) exhausted at "
                f"hung step {step}: {err}"
            ) from err
        self.log.info("hung step %d (%s): recompiling the full mesh", step, err)
        survivors = list(self.ff.mesh.devices.flat)
        return self._elastic_restart(survivors, step, reason="hung_step")

    # -- preemption grace -------------------------------------------------
    def _on_grace_signal(self, signum, frame) -> None:
        self._preempt = signal.Signals(signum).name
        # signal-handler context: only set the flag and note it — the
        # heavy work happens at the next step boundary on the main path
        self.log.info(
            "%s received: emergency checkpoint at the next step boundary",
            self._preempt,
        )

    def _install_grace_handlers(self) -> Dict:
        """SIGTERM/SIGINT -> request an emergency save at the next step
        boundary.  Returns the displaced handlers (restored on exit);
        empty when not on the main thread (signal.signal would raise)."""
        if not self.preempt_grace:
            return {}
        if threading.current_thread() is not threading.main_thread():
            return {}
        installed = {}
        for sig in GRACE_SIGNALS:
            try:
                installed[sig] = signal.signal(sig, self._on_grace_signal)
            except (ValueError, OSError):  # exotic embeddings
                break
        return installed

    def _preempt_rendezvous(self, step: int) -> int:
        """Agree with the run's other workers on ONE emergency step
        (blob-store preemption barrier, max of posts).  The run loop
        keeps stepping a lagging host FORWARD to the returned step
        before the emergency save, so every host commits the SAME
        state.  Without a remote tier (or on any barrier failure) the
        host's own step stands."""
        if self.offloader is None:
            return step
        from ..distributed import preemption_barrier

        try:
            import jax

            if self._run_id_defaulted and jax.process_count() > 1:
                # the default run_id is the checkpoint dir's basename:
                # hosts with differing per-host paths would rendezvous
                # under DIFFERENT prefixes and each poll a quorum of one
                self.log.warning(
                    "preemption-barrier run_id defaulted to %r from the "
                    "checkpoint directory — pass TrainingSupervisor("
                    "run_id=...) with one fleet-wide value if per-host "
                    "paths differ", self.run_id,
                )
            agreed = int(preemption_barrier(
                self.offloader.remote.blob, self.run_id, step,
                timeout_s=self.barrier_timeout,
                sleep=self.sleep,
            ))
        except Exception as e:  # noqa: BLE001 — never block the save
            self.log.info("preemption barrier failed (%s); saving "
                          "without cross-host agreement", e)
            return step
        if agreed != step:
            self.log.info(
                "preemption barrier agreed on step %d (this host is at "
                "%d): running forward to it before the emergency save",
                agreed, step,
            )
        return agreed

    def _emergency_stop(self, step: int) -> None:
        """The preemption deadline is unknown — synchronously write one
        final checkpoint at this step boundary, drain the async writer,
        and leave the directory restorable.  With a remote tier
        configured the step was already barrier-agreed by the run loop
        (_preempt_rendezvous); the emergency step is force-mirrored
        regardless of cadence."""
        registry = registry_of(self.ff)
        with tracer_of(self.ff).span("emergency_checkpoint", cat="resilience",
                                     step=step, reason=self._preempt):
            # drain FIRST: a queued async save may still be flushing on
            # the writer thread, and the sync emergency write must not
            # race it on the step dir / LATEST pointer
            self._drain_writer()
            self._save_checkpoint_survivable(step, wait=True)
        if self.offloader is not None and hasattr(self.manager,
                                                  "offload_step"):
            # the last checkpoint before the host disappears is exactly
            # the one the remote tier exists for
            self.manager.offload_step(step)
        self.counters["emergency_saves"] += 1
        if registry is not None:
            registry.counter("resilience/ckpt_emergency_saves").inc()
        self.log.info(
            "emergency checkpoint at step %d after %s; exiting restorable",
            step, self._preempt,
        )

    # -- the supervised loop ----------------------------------------------
    def run(self, x, y, num_steps: int, batch_size: Optional[int] = None,
            resume: bool = False) -> SupervisorReport:
        """Train for `num_steps` supervised steps over (x, y).

        resume=True restores the newest verified checkpoint in the
        directory (if any) and continues from its step — the companion
        of the preemption-grace exit, for the replacement process."""
        ff = self.ff
        assert ff._step_fn is not None, "call compile() first"
        batch_size = batch_size or ff.config.batch_size
        x_map = self._x_map(x)
        num_batches = len(y) // batch_size
        if num_batches < 1:
            raise ValueError(
                f"need at least one batch: {len(y)} samples < "
                f"batch_size {batch_size}"
            )
        # keyed by step so restores truncate exactly (a skipped step
        # records nothing, so a plain list would drift out of phase)
        loss_by_step: Dict[int, float] = {}
        step = 0
        restarts = 0
        preempt_target: Optional[int] = None
        self._preempt = None
        if self.offloader is not None:
            # stale rendezvous posts from the incarnation this run is
            # resuming FROM must never satisfy a future quorum
            from ..distributed import clear_preemption_barrier

            clear_preemption_barrier(self.offloader.remote.blob,
                                     self.run_id)
        if resume and self.manager.any_restorable():
            # any_restorable consults BOTH tiers: a fresh host with an
            # empty directory resumes from the remote mirror
            step = int(self.manager.restore(ff))
            self.log.info("resumed from checkpoint step %d", step)
        else:
            self._save_checkpoint_survivable(0)  # anchor: first failure has a target
        displaced = self._install_grace_handlers()
        try:
            while step < num_steps:
                if self._preempt is not None:
                    # rendezvous ONCE, then keep stepping until this
                    # host reaches the fleet-agreed emergency step (the
                    # max posted — laggards run forward, nobody rewinds)
                    if preempt_target is None:
                        preempt_target = self._preempt_rendezvous(step)
                    if step >= preempt_target:
                        break
                try:
                    self.fault_plan.check_step(step)
                    inputs, labels = self._batch(
                        x_map, y, step, batch_size, num_batches
                    )
                    inputs = self.fault_plan.corrupt_batch(step, inputs)
                    snap = self._snapshot() if self.nan_policy == "skip_step" else None
                    m = ff.train_step(inputs, labels)
                    self.counters["steps_run"] += 1
                    # the per-step device sync, under the hung-step
                    # watchdog: a wedged collective raises
                    # HungStepTimeout here instead of blocking forever
                    loss_val = self.watchdog.sync(
                        lambda: float(np.asarray(m["loss"])), step=step
                    )
                    try:
                        check_step_health({"loss": loss_val}, step=step,
                                          nan_policy=self.nan_policy)
                    except NonFiniteLossError:
                        if self.nan_policy != "skip_step":
                            raise  # "raise" propagates; "restore" caught below
                        # full step rollback (weights/opt/state/rng), then
                        # move past the poisoned batch
                        self._rollback(snap)
                        self.counters["skipped_steps"] += 1
                        loss_val = None
                    if loss_val is not None:
                        loss_by_step[step] = loss_val
                    step += 1
                    if self.checkpoint_every > 0 and step % self.checkpoint_every == 0:
                        self._save_checkpoint_survivable(step)
                except DeviceLossFault as f:
                    step = self._recover_device_loss(f, step)
                    loss_by_step = {s: v for s, v in loss_by_step.items() if s < step}
                except HUNG_FAULTS as e:
                    restarts += 1
                    step = self._recover_hung_step(e, step, restarts)
                    loss_by_step = {s: v for s, v in loss_by_step.items() if s < step}
                except TRANSIENT_FAULTS + (NonFiniteLossError,) as e:
                    if isinstance(e, NonFiniteLossError) and self.nan_policy == "raise":
                        raise
                    restarts += 1
                    step = self._retry_transient(e, step, restarts)
                    # replayed steps re-record their losses
                    loss_by_step = {s: v for s, v in loss_by_step.items() if s < step}
            if self._preempt is not None:
                # AFTER the loop, not at its top: a signal during the
                # final step must still get its boundary checkpoint —
                # report.preempted promises a restorable directory
                if preempt_target is None:
                    # the signal landed during the final step, so the
                    # loop exited before the top-of-loop rendezvous
                    # ran.  Post anyway: peers block on num_hosts posts
                    # and would otherwise stall to the deadline and
                    # commit a divergent step.  This host completed
                    # every step, so the agreed max cannot exceed it.
                    self._preempt_rendezvous(step)
                self._emergency_stop(step)
        finally:
            for sig, handler in displaced.items():
                signal.signal(sig, handler)
            # every exit path — clean, preempted, budget-exhausted —
            # waits out the async writer AND the remote mirror: queued
            # saves/uploads must land (or be counted failed/abandoned)
            # before the process can go away
            self._drain_writer()
            self._drain_offloader()
        # same "supervisor: k=v ..." log line as before, now also folded
        # into the run's metrics registry (-> run_telemetry.jsonl)
        tel = getattr(self.ff, "telemetry", None)
        emit_counters(
            self.log, "supervisor", self.counters,
            registry=tel.metrics if tel is not None else None,
            group="resilience",
        )
        if tel is not None and tel.enabled:
            tel.flush()
        # the report carries the mirror's counters too (offload_*) —
        # they already live in the registry as real Counters, so they
        # ride the report dict only, not the gauge fold above
        counters = dict(self.counters)
        if self.offloader is not None:
            counters.update(self.offloader.counters)
        return SupervisorReport(
            final_step=step,
            losses=[loss_by_step[s] for s in sorted(loss_by_step)],
            counters=counters,
            preempted=self._preempt,
        )

    # -- nan handling -----------------------------------------------------
    def _snapshot(self):
        """Host copies of the full train state.  The step function
        donates its weight/opt/state buffers (build_step
        donate_argnums), so pre-step device arrays are dead after the
        step — only a host copy can roll one back."""
        ff = self.ff
        return (
            jax.tree.map(np.asarray, ff._weights),
            jax.tree.map(np.asarray, ff._opt_state),
            jax.tree.map(np.asarray, ff._state),
            ff._rng,
        )

    def _rollback(self, snap) -> None:
        from ..model import device_put_like

        w, opt, st, rng = snap
        ff = self.ff
        ff.set_weights(w)
        ff._opt_state = device_put_like(opt, ff._opt_state)
        ff._state = device_put_like(st, ff._state)
        ff._rng = rng
