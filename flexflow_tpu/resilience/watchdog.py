"""Hung-step watchdog: bound the per-step device sync with a timeout.

A wedged collective (ICI link flap, a peer host dropping out of a
multi-slice ring) does not raise — the host-side sync simply never
returns, and an unsupervised run hangs forever where a crash would
have triggered recovery.  `StepWatchdog.sync` runs the blocking device
read on a persistent worker thread and gives up after `timeout_s`,
raising `HungStepTimeout`; the supervisor classifies that like a
device-loss fault and routes it into the existing restart / elastic
re-search path (recompiling the executor is what resets the wedged
collective state).

One worker thread serves every step, so the hot path pays a queue
put/event wait, not a thread spawn.  On timeout the wedged worker is
abandoned (it is a daemon thread blocked on the dead sync — it costs
one stack and exits if the sync ever unwedges) and the next sync gets a
fresh worker.  A disabled watchdog (timeout_s == 0, the default) calls
the function inline: no thread, no overhead.
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Optional


class HungStepTimeout(RuntimeError):
    """The per-step device sync exceeded the watchdog timeout.

    Raised by real watchdog expiry — the injected twin is
    `resilience.faults.HungStepFault`; the supervisor treats both as
    the same device-loss-style fault."""

    def __init__(self, step: Optional[int], timeout_s: float):
        self.step = step
        self.timeout_s = timeout_s
        where = f" at step {step}" if step is not None else ""
        super().__init__(
            f"device sync{where} exceeded the {timeout_s:g}s step "
            "watchdog — treating the step as hung"
        )


_STOP = object()


class StepWatchdog:
    """Runs blocking device syncs with a hang deadline."""

    def __init__(self, timeout_s: float = 0.0):
        if timeout_s < 0:
            raise ValueError(f"timeout_s must be >= 0, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self._worker: Optional[threading.Thread] = None
        self._requests: Optional["queue.Queue"] = None

    @property
    def enabled(self) -> bool:
        return self.timeout_s > 0

    @staticmethod
    def _serve(requests: "queue.Queue") -> None:
        while True:
            item = requests.get()
            if item is _STOP:
                return
            fn, box, done = item
            try:
                box["result"] = fn()
            except BaseException as e:  # noqa: BLE001 — re-raised by sync()
                box["error"] = e
            finally:
                done.set()

    def _ensure_worker(self) -> "queue.Queue":
        if self._worker is None or not self._worker.is_alive():
            self._requests = queue.Queue()
            self._worker = threading.Thread(
                target=self._serve, args=(self._requests,),
                daemon=True, name="step-watchdog",
            )
            self._worker.start()
        return self._requests

    def sync(self, fn: Callable[[], Any], step: Optional[int] = None) -> Any:
        """Run `fn` (a blocking device read); raise HungStepTimeout if
        it does not return within `timeout_s`.  Exceptions from `fn`
        propagate unchanged; a disabled watchdog calls `fn` inline."""
        if not self.enabled:
            return fn()
        requests = self._ensure_worker()
        box: dict = {}
        done = threading.Event()
        requests.put((fn, box, done))
        if not done.wait(self.timeout_s):
            # abandon the wedged worker: queue a stop so it exits if the
            # sync ever returns, and spawn fresh on the next call.  Each
            # request carries its own box/event, so a late completion
            # cannot cross-talk with a newer sync.
            requests.put(_STOP)
            self._worker = None
            raise HungStepTimeout(step, self.timeout_s)
        if "error" in box:
            raise box["error"]
        return box["result"]
