"""Resilience subsystem: deterministic fault injection (faults.py),
retry/backoff supervision (retry.py), and a training supervisor that
composes checkpoints, recompile, and the strategy search into elastic
recovery on a degraded mesh (supervisor.py).  See docs/RESILIENCE.md.
"""
from .faults import (
    CheckpointWriteFault,
    DeviceLossFault,
    Fault,
    FaultKind,
    FaultPlan,
    InjectedFault,
    PreemptionFault,
    StepFault,
)
from .retry import RetryPolicy
from .supervisor import (
    RestartBudgetExhausted,
    SupervisorReport,
    TrainingSupervisor,
)

__all__ = [
    "CheckpointWriteFault",
    "DeviceLossFault",
    "Fault",
    "FaultKind",
    "FaultPlan",
    "InjectedFault",
    "PreemptionFault",
    "StepFault",
    "RetryPolicy",
    "RestartBudgetExhausted",
    "SupervisorReport",
    "TrainingSupervisor",
]
