"""Resilience subsystem: deterministic fault injection (faults.py),
retry/backoff supervision (retry.py), off-critical-path checkpoint
writes (async_writer.py), a hung-step watchdog (watchdog.py), and a
training supervisor that composes checkpoints, recompile, preemption
grace, and the strategy search into elastic recovery on a degraded
mesh (supervisor.py).  See docs/RESILIENCE.md.
"""
from .async_writer import AsyncCheckpointWriter
from .faults import (
    BLOB_FAULT_KINDS,
    CheckpointWriteFault,
    DeviceLossFault,
    Fault,
    FaultKind,
    FaultPlan,
    HungStepFault,
    InjectedFault,
    PreemptionFault,
    StepFault,
)
from .offload import (
    CheckpointOffloader,
    RemoteCheckpointStore,
    RemoteVerifyError,
    offloader_from_config,
)
from .retry import RetryPolicy
from .supervisor import (
    RestartBudgetExhausted,
    SupervisorReport,
    TrainingSupervisor,
)
from .watchdog import HungStepTimeout, StepWatchdog

__all__ = [
    "AsyncCheckpointWriter",
    "BLOB_FAULT_KINDS",
    "CheckpointOffloader",
    "CheckpointWriteFault",
    "DeviceLossFault",
    "RemoteCheckpointStore",
    "RemoteVerifyError",
    "offloader_from_config",
    "Fault",
    "FaultKind",
    "FaultPlan",
    "HungStepFault",
    "HungStepTimeout",
    "InjectedFault",
    "PreemptionFault",
    "StepFault",
    "StepWatchdog",
    "RetryPolicy",
    "RestartBudgetExhausted",
    "SupervisorReport",
    "TrainingSupervisor",
]
