"""Background checkpoint writer: serialize/fsync/publish off the step path.

The checkpoint managers' `save(wait=False)` path snapshots device
arrays to host (the only accelerator stall), then hands the serialized
write — npz encode, fsync, checksum verification, atomic publish — to
this single-threaded writer.  Training resumes immediately; durability
work overlaps the next steps' device time.

Contract:

  * jobs run FIFO on one daemon thread, so step N's checkpoint always
    publishes before step N+1's (the `latest` pointer never regresses);
  * a failing job (disk full, verification mismatch) never kills the
    writer or the training loop — the exception is logged, recorded,
    and surfaced at the next `drain()` so the supervisor can fold it
    into its `checkpoint_failures` counter;
  * `drain()` blocks until every submitted job has finished — the
    supervisor calls it before any restore (a pending newer checkpoint
    must land first) and on every exit path, `fit` drains
    checkpoint-manager callbacks in its `finally`, and the preemption
    grace handler drains before the process exits.
"""
from __future__ import annotations

import logging
import queue
import threading
from typing import Callable, List, Optional, Tuple

_log = logging.getLogger("flexflow_tpu.checkpoint")

_SENTINEL = object()


class AsyncCheckpointWriter:
    """One daemon thread draining a FIFO queue of checkpoint write jobs."""

    def __init__(self, name: str = "ckpt-writer"):
        self.name = name
        self._q: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._failures: List[Tuple[int, Exception]] = []
        # observability hook: called with the queue depth on every
        # submit/complete (the manager points it at the run's
        # resilience/ckpt_queue_depth gauge)
        self.depth_cb: Optional[Callable[[int], None]] = None

    # -- lifecycle -------------------------------------------------------
    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread = threading.Thread(
            target=self._run, name=self.name, daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._q.get()
            try:
                if item is _SENTINEL:
                    return
                step, fn = item
                try:
                    fn()
                except Exception as e:  # noqa: BLE001 — job errors must not
                    # kill the writer; they surface at drain()
                    _log.warning(
                        "async checkpoint write for step %d failed: %s",
                        step, e,
                    )
                    with self._lock:
                        self._failures.append((step, e))
            finally:
                self._q.task_done()
                self._notify_depth()

    def _notify_depth(self) -> None:
        cb = self.depth_cb
        if cb is not None:
            try:
                cb(self._q.unfinished_tasks)
            except Exception:  # pragma: no cover — never break on telemetry
                pass

    # -- API -------------------------------------------------------------
    def submit(self, step: int, fn: Callable[[], None]) -> None:
        """Queue one write job (already-snapshotted state captured in
        `fn`); returns immediately."""
        self._ensure_thread()
        self._q.put((step, fn))
        self._notify_depth()

    @property
    def queue_depth(self) -> int:
        return self._q.unfinished_tasks

    def wait(self) -> None:
        """Block until every submitted job has run, leaving accumulated
        failures in place (backpressure callers must not consume what
        the owner's drain() is meant to report)."""
        if self._thread is not None:
            self._q.join()

    def drain(self) -> List[Tuple[int, Exception]]:
        """Block until every submitted job has run; return (and clear)
        the failures accumulated since the last drain."""
        self.wait()
        with self._lock:
            failures, self._failures = self._failures, []
        return failures

    def close(self) -> List[Tuple[int, Exception]]:
        """Drain, stop the thread, and return outstanding failures.
        Safe to call twice; a closed writer restarts lazily on the next
        submit()."""
        failures = self.drain()
        if self._thread is not None and self._thread.is_alive():
            self._q.put(_SENTINEL)
            self._thread.join(timeout=10.0)
        self._thread = None
        return failures
