"""Checkpoint / resume.

The reference has no real checkpoint format — weights round-trip through
numpy by hand (parallel_tensor.cc:650-750) and SURVEY §5 flags
checkpoint/resume as a gap to close fresh.  TPU-native answer: orbax for
sharded async-capable saves of the full training state (weights,
optimizer state, op state, step, rng), plus the strategy JSON and a
config snapshot so `restore` can rebuild byte-identical training on a
fresh process — including onto a *different* mesh (orbax resharding on
restore handles the re-layout).
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np


def _meta(ff, step: int) -> Dict[str, Any]:
    return {
        "step": step,
        "version": 1,
        "strategy": ff.strategy.to_json() if ff.strategy is not None else None,
        "batch_size": ff.config.batch_size,
        "num_devices": ff.config.num_devices,
    }


class CheckpointManager:
    """Orbax-backed manager bound to a compiled FFModel.

    save/restore the full train state; `max_to_keep` rotates old steps.
    Restore reshards to the model's *current* executor shardings, so a
    checkpoint taken on one mesh resumes on another.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )
        self._ocp = ocp

    # -- save -----------------------------------------------------------
    def save(self, ff, step: int, wait: bool = True):
        """Persist weights + optimizer state + op state + rng + strategy."""
        ocp = self._ocp
        state = {
            "weights": ff._weights,
            "opt_state": ff._opt_state,
            "op_state": ff._state,
            "rng": jax.random.key_data(ff._rng),
        }
        self._mgr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                meta=ocp.args.JsonSave(_meta(ff, step)),
            ),
        )
        if wait:
            self._mgr.wait_until_finished()

    # -- restore --------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return list(self._mgr.all_steps())

    def restore(self, ff, step: Optional[int] = None) -> int:
        """Load a step (default: latest) into a compiled FFModel,
        resharding every leaf to the current executor's shardings.
        Returns the restored step."""
        ocp = self._ocp
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")

        target = {
            "weights": ff._weights,
            "opt_state": ff._opt_state,
            "op_state": ff._state,
            "rng": jax.random.key_data(ff._rng),
        }
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype,
                sharding=getattr(x, "sharding", None),
            ),
            target,
        )
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract),
                meta=ocp.args.JsonRestore(),
            ),
        )
        state = restored["state"]
        ff._weights = state["weights"]
        ff._opt_state = state["opt_state"]
        ff._state = state["op_state"]
        ff._rng = jax.random.wrap_key_data(state["rng"])
        # restored cache_pos may be mid-sequence; rebuild the host-side
        # decode guard from the device value (ADVICE r4)
        if hasattr(ff, "sync_decode_pos"):
            ff.sync_decode_pos()
        return int(step)

    def restore_meta(self, step: Optional[int] = None) -> Dict[str, Any]:
        ocp = self._ocp
        if step is None:
            step = self._mgr.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints in {self.directory}")
        restored = self._mgr.restore(
            step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
        )
        return dict(restored["meta"])

    def close(self):
        self._mgr.close()


# -- plain numpy weight files (reference-parity path) -------------------

def save_weights_npz(ff, path: str):
    """Weights-only flat .npz (the reference's manual numpy round-trip,
    flexflow_cffi.py Tensor get_weights)."""
    flat = {}
    for op_name, wdict in ff.get_weights().items():
        for wname, arr in wdict.items():
            flat[f"{op_name}/{wname}"] = np.asarray(arr)
    np.savez(path, **flat)


def load_weights_npz(ff, path: str):
    data = np.load(path)
    nested: Dict[str, Dict[str, np.ndarray]] = {}
    for key in data.files:
        op_name, wname = key.rsplit("/", 1)
        nested.setdefault(op_name, {})[wname] = data[key]
    ff.set_weights(nested)


class ModelCheckpoint:
    """Keras-style callback saving every epoch via CheckpointManager."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.manager = CheckpointManager(directory, max_to_keep=max_to_keep)

    def on_train_begin(self, ffmodel):
        pass

    def on_epoch_end(self, ffmodel, epoch: int, metrics):
        self.manager.save(ffmodel, epoch)

    def on_train_end(self, ffmodel):
        self.manager.close()
