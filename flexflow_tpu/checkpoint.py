"""Checkpoint / resume with verified, off-critical-path saves.

The reference has no real checkpoint format — weights round-trip through
numpy by hand (parallel_tensor.cc:650-750) and SURVEY §5 flags
checkpoint/resume as a gap to close fresh.  TPU-native answer: sharded
saves of the full training state (weights, optimizer state, op state,
step, rng) plus the strategy JSON and a config snapshot, so `restore`
can rebuild byte-identical training on a fresh process — including onto
a *different* mesh (every leaf reshards onto the current executor's
shardings on restore).

Durability layer (docs/RESILIENCE.md "Async checkpointing"):

  * **async saves** — `save(..., wait=False)` snapshots device arrays
    to host (the only accelerator stall) and hands serialization,
    fsync, verification and atomic publish to a background
    `resilience.async_writer.AsyncCheckpointWriter`; `wait=True` keeps
    fully synchronous semantics.  `drain()` blocks until pending
    writes land (the supervisor drains before restores and on exit).
  * **integrity manifest** — each local checkpoint carries a per-leaf
    crc32 manifest (`manifest.json`); a save only publishes, and the
    `LATEST` pointer only advances, after the written bytes re-read and
    verify.  Restore re-verifies every leaf and falls back past
    corrupt/unverifiable steps to the newest intact one.
  * **layout validation** — restoring a checkpoint whose saved state
    tree does not match the current run (different model / optimizer /
    op-state structure) raises `CheckpointCompatibilityError` naming
    every mismatched leaf, instead of a cryptic reshape/resharding
    traceback.  Mesh-size and weight-update-sharding layout changes
    remain *compatible* by design — reshard-on-restore handles them.
  * **pipeline layout mapping** — a checkpoint saved under a per-op
    strategy restores onto a pipeline (`__pipeline__` stacked) executor
    and vice versa: restore routes the weight and optimizer-slot trees
    through `FFModel._adapt_weight_layout` before spec validation, so
    the supervisor's elastic re-search may pick pipeline winners
    mid-run (the former `re_search_pipeline_excluded` gate is gone).
  * **remote tier** — with a configured offload tier
    (`resilience/offload.py`, FFConfig.remote_store), every verified
    local publish is mirrored to object storage off the critical path,
    and restore walks local -> remote PER CHECKPOINT: a corrupt local
    step falls back to its verified remote mirror (downloaded,
    crc-verified, materialized locally) before giving up progress to
    an older step; a brand-new empty host restores entirely from the
    remote tier.
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import re
import shutil
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from .obs.metrics import registry_of
from .obs.trace import tracer_of

_log = logging.getLogger("flexflow_tpu.checkpoint")

MANIFEST_VERSION = 1
_LATEST_FILE = "LATEST"


class CheckpointVerifyError(RuntimeError):
    """A checkpoint's bytes do not match its integrity manifest."""


class CheckpointCompatibilityError(RuntimeError):
    """The checkpoint's state tree is incompatible with the current run.

    Raised instead of a cryptic KeyError/reshape traceback when the
    saved leaves (names, shapes, dtypes) don't match the compiled
    model's — e.g. a different architecture, optimizer, or op-state
    layout.  Mesh-size / ZeRO-1-layout differences never raise this:
    restore reshards onto the current shardings by contract."""

    def __init__(self, step: int, mismatches: List[str],
                 meta: Optional[Dict] = None):
        self.step = step
        self.mismatches = list(mismatches)
        meta = meta or {}
        context = (
            f" (saved with num_devices={meta.get('num_devices')}, "
            f"zero_stage={meta.get('zero_stage')}, "
            f"wus_axis={meta.get('wus_axis')})" if meta else ""
        )
        shown = "; ".join(self.mismatches[:8])
        more = (f"; ... {len(self.mismatches) - 8} more"
                if len(self.mismatches) > 8 else "")
        super().__init__(
            f"checkpoint step {step} is incompatible with the current "
            f"run{context}: {shown}{more}"
        )


def _meta(ff, step: int) -> Dict[str, Any]:
    return {
        "step": step,
        "version": 1,
        "strategy": ff.strategy.to_json() if ff.strategy is not None else None,
        "batch_size": ff.config.batch_size,
        "num_devices": ff.config.num_devices,
        # ZeRO ladder layout marker: restore reshards every leaf onto
        # the CURRENT executor's shardings either way (any stage <->
        # any stage — incl. stage-3 scattered master weights — and
        # elastic meshes all round-trip, since leaves are saved as
        # GLOBAL arrays); recorded so tooling can see which layout
        # produced the artifact.  zero_stage is the EFFECTIVE stage
        # the executor ran (search-chosen stages included).
        "zero_stage": int(
            getattr(getattr(ff, "executor", None), "zero_stage",
                    getattr(ff.config, "zero_stage", 0)) or 0
        ),
        "weight_update_sharding": bool(
            getattr(ff.config, "weight_update_sharding", False)
        ),
        "wus_axis": getattr(ff.config, "wus_axis", None),
    }


def _leaf_crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).view(np.uint8).reshape(-1))


def _build_manifest(step: int, flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    leaves = {
        key: {
            "crc32": _leaf_crc(arr),
            "bytes": int(arr.nbytes),
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
        }
        for key, arr in flat.items()
    }
    return {
        "manifest_version": MANIFEST_VERSION,
        "step": step,
        "total_bytes": sum(v["bytes"] for v in leaves.values()),
        "leaves": leaves,
    }


def _write_json_fsync(path: str, obj: Dict) -> None:
    with open(path, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover — platform without dir fds
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class _LatestPointer:
    """Crash-safe `LATEST` pointer file: names the newest checkpoint
    step that passed integrity verification.  Advanced only after a
    save verifies and publishes, so a reader that trusts the pointer
    never lands on a torn or unverified write."""

    def __init__(self, directory: str):
        self.directory = directory
        self.path = os.path.join(directory, _LATEST_FILE)

    def read(self) -> Optional[int]:
        try:
            with open(self.path) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    def advance(self, step: int, force: bool = False) -> None:
        cur = self.read()
        if not force and cur is not None and cur >= step:
            return
        # thread-unique tmp name: the writer thread and a synchronous
        # caller (emergency save) must not clobber each other's staging
        import threading

        tmp = f"{self.path}.tmp-{os.getpid()}-{threading.get_ident()}"
        with open(tmp, "w") as f:
            f.write(str(step))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        _fsync_dir(self.directory)


class CheckpointManager:
    """Orbax-backed manager bound to a compiled FFModel.

    save/restore the full train state; `max_to_keep` rotates old steps.
    Restore reshards to the model's *current* executor shardings, so a
    checkpoint taken on one mesh resumes on another.  `wait=False`
    returns after orbax's host snapshot (serialization continues in
    orbax's background machinery); `drain()` blocks until pending saves
    land and only then advances the `LATEST` pointer.  Integrity inside
    a step is orbax's commit protocol; the per-leaf crc32 manifest is a
    LocalCheckpointManager feature."""

    def __init__(self, directory: str, max_to_keep: int = 3, remote=None):
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )
        self._ocp = ocp
        self._latest = _LatestPointer(self.directory)
        # remote tier (resilience/offload.py RemoteCheckpointStore):
        # restore-side fallback only — the mirror's flat-npz format is
        # backend-agnostic, so an orbax run can recover from a mirror a
        # LocalCheckpointManager uploaded (uploading is the local
        # manager's job; orbax's own commit layout is not mirrored)
        self.remote = remote
        # wait=False (step, submit_time, registry) not yet drained
        self._pending: List[Tuple[int, float, Any]] = []

    # -- save -----------------------------------------------------------
    def save(self, ff, step: int, wait: bool = True):
        """Persist weights + optimizer state + op state + rng + strategy.

        wait=True blocks until the checkpoint is durable (and advances
        the LATEST pointer); wait=False returns after the host snapshot
        and defers durability to orbax's writer — call drain() before
        relying on the step being restorable."""
        ocp = self._ocp
        state = {
            "weights": ff._weights,
            "opt_state": ff._opt_state,
            "op_state": ff._state,
            "rng": jax.random.key_data(ff._rng),
        }
        meta = _meta(ff, step)
        meta["leaf_specs"] = _tree_specs(state)
        tracer = tracer_of(ff)
        registry = registry_of(ff)
        t0 = time.perf_counter()
        with tracer.span("checkpoint_write", cat="checkpoint", step=step,
                         backend="orbax", mode="sync" if wait else "async"):
            with tracer.span("snapshot", cat="checkpoint", step=step):
                self._mgr.save(
                    step,
                    args=ocp.args.Composite(
                        state=ocp.args.StandardSave(state),
                        meta=ocp.args.JsonSave(meta),
                    ),
                )
            if wait:
                with tracer.span("flush", cat="checkpoint", step=step):
                    self._mgr.wait_until_finished()
                self._latest.advance(step)
                if registry is not None:
                    registry.histogram(
                        "resilience/ckpt_write_latency_s"
                    ).observe(time.perf_counter() - t0)
            else:
                # latency for async saves is observed at drain() — the
                # save-call duration here is snapshot-only and would
                # understate the metric's documented submit->durable
                # semantics ~30x
                self._pending.append((step, t0, registry))

    def drain(self) -> List[Tuple[int, Exception]]:
        """Block until every pending async save lands; advance the
        LATEST pointer past them and record their submit->durable
        latency.  Returns the (step, error) failures — an orbax wait
        failure is attributed to all pending steps."""
        if not self._pending:
            return []
        pending, self._pending = self._pending, []
        try:
            self._mgr.wait_until_finished()
        except Exception as e:  # noqa: BLE001 — surface, don't crash
            steps = [s for s, _, _ in pending]
            _log.warning("async orbax save(s) %s failed: %s", steps, e)
            return [(s, e) for s in steps]
        now = time.perf_counter()
        for step, t0, registry in pending:
            if registry is not None:
                registry.histogram(
                    "resilience/ckpt_write_latency_s"
                ).observe(now - t0)
        self._latest.advance(max(s for s, _, _ in pending))
        return []

    # -- restore --------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def latest_verified_step(self) -> Optional[int]:
        """The newest step the LATEST pointer has committed to, None if
        absent or stale — orbax's max_to_keep rotation can delete a
        pointed-at step whose successors were never drained."""
        step = self._latest.read()
        if step is None or step not in set(self._mgr.all_steps()):
            return None
        return step

    def any_restorable(self) -> bool:
        """True when either the orbax directory or the remote mirror
        tier holds at least one restorable checkpoint."""
        if self.latest_step() is not None:
            return True
        if self.remote is None:
            return False
        try:
            return bool(self.remote.list_steps())
        except Exception:  # noqa: BLE001 — unreachable mirror
            return False

    def all_steps(self):
        return list(self._mgr.all_steps())

    def _mirrored_steps(self) -> set:
        """Steps the remote tier can serve (empty on any store failure —
        the caller then surfaces its local error instead)."""
        if self.remote is None:
            return set()
        try:
            return set(self.remote.list_steps())
        except Exception:  # noqa: BLE001 — unreachable mirror
            return set()

    def restore(self, ff, step: Optional[int] = None) -> int:
        """Load a step (default: latest) into a compiled FFModel,
        resharding every leaf to the current executor's shardings.
        Returns the restored step.

        With step=None a corrupt/partial/incompatible latest checkpoint
        is skipped and the previous one restored instead (the crash
        that truncated the write is usually the crash being recovered
        from); an explicitly requested step stays strict.  With a
        remote tier configured, steps the local directory cannot serve
        fall back to their verified remote mirrors."""
        if step is not None:
            try:
                return self._restore_step(ff, step)
            except CheckpointCompatibilityError as compat_err:
                # UNLIKE the npz manager (where both tiers share one
                # verify-adapt path) the orbax local restore cannot
                # adapt per-op <-> __pipeline__ layouts, but the flat-npz
                # mirror restore can — try it before giving up
                if self.remote is None or step not in self._mirrored_steps():
                    raise
                try:
                    return self._restore_remote_step(ff, step)
                except Exception:  # noqa: BLE001
                    raise compat_err  # the actionable report, not blob noise
            except Exception:
                if self.remote is None:
                    raise
                if step not in self._mirrored_steps():
                    raise  # surface the local failure, not BlobNotFound
                return self._restore_remote_step(ff, step)
        steps = sorted(self._mgr.all_steps(), reverse=True)
        remote_steps: List[int] = []
        if self.remote is not None:
            try:
                remote_steps = sorted(self.remote.list_steps(), reverse=True)
            except Exception as e:  # noqa: BLE001 — any store failure
                _log.warning(
                    "remote checkpoint tier unlistable (%s); restoring "
                    "from the local tier only", e,
                )
        if not steps and not remote_steps:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        last_err: Optional[Exception] = None
        # ONE newest-first walk over BOTH tiers — an older local step
        # must never win over a newer verified remote-only mirror
        for s in sorted(set(steps) | set(remote_steps), reverse=True):
            if s in steps:
                try:
                    restored = self._restore_step(ff, s)
                except Exception as e:  # noqa: BLE001 — orbax raises various
                    if s in remote_steps:
                        try:
                            restored = self._restore_remote_step(ff, s)
                        except Exception as re_err:  # noqa: BLE001
                            _log.warning(
                                "checkpoint step %d unrestorable locally "
                                "(%s) and remotely (%s); falling back",
                                s, e, re_err,
                            )
                            last_err = re_err
                            continue
                    else:
                        _log.warning(
                            "checkpoint step %d in %s unrestorable (%s); "
                            "falling back to the previous step",
                            s, self.directory, e,
                        )
                        last_err = e
                        continue
            else:
                try:
                    restored = self._restore_remote_step(ff, s)
                except Exception as e:  # noqa: BLE001
                    _log.warning(
                        "remote checkpoint step %d unrestorable (%s); "
                        "falling back to the previous step", s, e,
                    )
                    last_err = e
                    continue
            if last_err is not None:
                _log.warning(
                    "restored OLDER step %d from %s — newer step(s) were "
                    "corrupt/partial, their progress is lost",
                    restored, self.directory,
                )
            return restored
        raise last_err

    def _restore_remote_step(self, ff, step: int) -> int:
        """Fill the model from a remote mirror (flat-npz format): crc
        re-verify the downloaded bytes, adapt layouts, device_put onto
        the current shardings."""
        import io

        from jax.tree_util import tree_unflatten

        files = self.remote.download_step(step)
        manifest = json.loads(files["manifest.json"])
        meta = json.loads(files["meta.json"])
        with np.load(io.BytesIO(files["state.npz"])) as data:
            arrays = {key: data[key] for key in data.files}
        target = {
            "weights": ff._weights,
            "opt_state": ff._opt_state,
            "op_state": ff._state,
            "rng": jax.random.key_data(ff._rng),
        }
        new_leaves, treedef = _verify_adapt_put(
            ff, target, arrays, manifest, meta, step
        )
        restored = tree_unflatten(treedef, new_leaves)
        ff._weights = restored["weights"]
        ff._opt_state = restored["opt_state"]
        ff._state = restored["op_state"]
        ff._rng = jax.random.wrap_key_data(restored["rng"])
        if hasattr(ff, "sync_decode_pos"):
            ff.sync_decode_pos()
        registry = registry_of(ff)
        if registry is not None:
            registry.counter("resilience/offload_remote_restores").inc()
        _log.info("step %d restored from the remote tier (orbax local "
                  "tier could not serve it)", step)
        return int(step)

    def _restore_step(self, ff, step: int) -> int:
        ocp = self._ocp
        target = {
            "weights": ff._weights,
            "opt_state": ff._opt_state,
            "op_state": ff._state,
            "rng": jax.random.key_data(ff._rng),
        }
        # layout validation up front: a structurally incompatible
        # checkpoint fails with one clear error naming the leaves,
        # not a restore-time reshape traceback from orbax internals
        try:
            meta = self.restore_meta(step)
        except Exception:  # meta unreadable -> let the restore itself fail
            meta = None
        if meta and meta.get("leaf_specs"):
            mismatches = _spec_mismatches(meta["leaf_specs"],
                                          _tree_specs(target))
            if mismatches:
                raise CheckpointCompatibilityError(step, mismatches, meta)
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype,
                sharding=getattr(x, "sharding", None),
            ),
            target,
        )
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract),
                meta=ocp.args.JsonRestore(),
            ),
        )
        state = restored["state"]
        ff._weights = state["weights"]
        ff._opt_state = state["opt_state"]
        ff._state = state["op_state"]
        ff._rng = jax.random.wrap_key_data(state["rng"])
        # restored cache_pos may be mid-sequence; rebuild the host-side
        # decode guard from the device value (ADVICE r4)
        if hasattr(ff, "sync_decode_pos"):
            ff.sync_decode_pos()
        return int(step)

    def restore_meta(self, step: Optional[int] = None) -> Dict[str, Any]:
        ocp = self._ocp
        if step is None:
            step = self._mgr.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints in {self.directory}")
        restored = self._mgr.restore(
            step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
        )
        return dict(restored["meta"])

    def close(self):
        self.drain()
        self._mgr.close()


def _tree_specs(tree) -> Dict[str, Dict[str, Any]]:
    """keystr-keyed {shape, dtype} specs for every leaf of a state
    tree — the structural signature layout validation compares."""
    from jax.tree_util import keystr, tree_flatten_with_path

    leaves, _ = tree_flatten_with_path(tree)
    return {
        keystr(path): {
            "shape": list(np.shape(leaf)),
            "dtype": str(getattr(leaf, "dtype", np.asarray(leaf).dtype)),
        }
        for path, leaf in leaves
    }


def _verify_adapt_put(ff, target, arrays: Dict[str, np.ndarray],
                      manifest: Optional[Dict], meta: Optional[Dict],
                      step: int):
    """The shared restore core for flat (keystr-keyed) checkpoints:
    crc-verify against the manifest's saved-layout keys FIRST (so
    corruption surfaces as a verify error and falls back, never
    masquerading as a layout problem), map per-op <-> `__pipeline__`
    stacked layouts onto the current executor, validate leaf specs,
    then device_put every leaf onto the target's shardings.  Returns
    (new_leaves, treedef) for the target tree."""
    from jax.tree_util import keystr, tree_flatten_with_path

    if manifest is not None:
        for key, spec in manifest["leaves"].items():
            arr = arrays.get(key)
            if arr is None:
                raise CheckpointVerifyError(
                    f"step {step}: leaf {key!r} in manifest but not in "
                    "state.npz"
                )
            crc = _leaf_crc(arr)
            if crc != spec["crc32"]:
                raise CheckpointVerifyError(
                    f"step {step}: leaf {key!r} crc32 {crc:#010x} "
                    f"!= manifest {spec['crc32']:#010x}"
                )
        # every saved leaf must be covered: a manifest that lists fewer
        # leaves than state.npz (torn/older/hand-edited) would otherwise
        # let the uncovered bytes restore with no integrity check at all
        unverified = sorted(set(arrays) - set(manifest["leaves"]))
        if unverified:
            shown = ", ".join(repr(k) for k in unverified[:5])
            more = (f", ... {len(unverified) - 5} more"
                    if len(unverified) > 5 else "")
            raise CheckpointVerifyError(
                f"step {step}: leaves in state.npz but missing from the "
                f"manifest (unverifiable): {shown}{more}"
            )
    arrays = _adapt_saved_layout(ff, arrays)
    leaves, treedef = tree_flatten_with_path(target)
    # layout validation before materializing: one clear error naming
    # every mismatched leaf beats a KeyError/reshape traceback from
    # whichever leaf happened to differ
    saved_specs = {
        key: {"shape": list(arr.shape), "dtype": str(arr.dtype)}
        for key, arr in arrays.items()
    }
    current_specs = {
        keystr(path): {
            "shape": list(cur.shape),
            "dtype": str(cur.dtype),
        }
        for path, cur in leaves
    }
    mismatches = _spec_mismatches(saved_specs, current_specs)
    if mismatches:
        raise CheckpointCompatibilityError(step, mismatches, meta)
    new_leaves = []
    for path, cur in leaves:
        arr = arrays[keystr(path)]
        sh = getattr(cur, "sharding", None)
        new_leaves.append(
            jax.device_put(arr, sh) if sh is not None else arr
        )
    return new_leaves, treedef


_KEYSTR_TOKEN_RE = re.compile(r"\['([^']*)'\]")


def _unflatten_keystr(flat: Dict[str, Any]) -> Optional[Dict]:
    """Rebuild the nested dict tree a keystr-keyed flat mapping came
    from.  Returns None when any key is not a pure string-keyed dict
    path (lists/custom nodes) — callers then skip layout adaptation and
    let spec validation report the mismatch."""
    root: Dict = {}
    for key, leaf in flat.items():
        toks = _KEYSTR_TOKEN_RE.findall(key)
        if not toks or "".join(f"['{t}']" for t in toks) != key:
            return None
        d = root
        for t in toks[:-1]:
            d = d.setdefault(t, {})
            if not isinstance(d, dict):
                return None
        d[toks[-1]] = leaf
    return root


def _flatten_keystr(tree) -> Dict[str, Any]:
    from jax.tree_util import keystr, tree_flatten_with_path

    leaves, _ = tree_flatten_with_path(tree)
    return {keystr(path): leaf for path, leaf in leaves}


def _adapt_saved_layout(ff, arrays: Dict[str, np.ndarray]
                        ) -> Dict[str, np.ndarray]:
    """Map a flat saved state between the per-op and the
    `__pipeline__`-stacked weight layouts to match the CURRENT
    executor, reusing `FFModel._adapt_weight_layout` for the weight
    tree and each weight-shaped optimizer-slot subtree (exactly
    recompile's carry).  This is what lets the supervisor's elastic
    re-search restore a per-op-keyed checkpoint onto a freshly
    compiled pipeline strategy (and back).  A failed adaptation
    returns the arrays unchanged so spec validation reports the real
    mismatch instead of a mapping traceback."""
    saved_pp = any(
        k.startswith("['weights']['__pipeline__']") for k in arrays
    )
    cur_pp = "__pipeline__" in (getattr(ff, "_weights", None) or {})
    if saved_pp == cur_pp:
        return arrays
    adapt = getattr(ff, "_adapt_weight_layout", None)
    nested = _unflatten_keystr(arrays)
    if adapt is None or nested is None or "weights" not in nested:
        return arrays
    try:
        out = dict(nested)
        out["weights"] = adapt(nested["weights"])
        if isinstance(nested.get("opt_state"), dict):
            out["opt_state"] = {
                k: adapt(sub) if isinstance(sub, dict) else sub
                for k, sub in nested["opt_state"].items()
            }
        return _flatten_keystr(out)
    except Exception as e:  # genuinely incompatible trees
        _log.warning(
            "pipeline layout adaptation failed (%s); restoring with the "
            "saved layout as-is", e,
        )
        return arrays


def _spec_mismatches(saved: Dict[str, Dict], current: Dict[str, Dict]
                     ) -> List[str]:
    """Human-readable list of structural differences between a saved
    tree signature and the current model's (empty == compatible)."""
    problems = []
    for key in sorted(set(saved) - set(current)):
        problems.append(f"{key}: in checkpoint but not in current state")
    for key in sorted(set(current) - set(saved)):
        problems.append(f"{key}: required by current state, missing "
                        "from checkpoint")
    for key in sorted(set(saved) & set(current)):
        s, c = saved[key], current[key]
        if list(s["shape"]) != list(c["shape"]):
            problems.append(
                f"{key}: shape {tuple(s['shape'])} in checkpoint vs "
                f"{tuple(c['shape'])} in current state"
            )
        elif str(s["dtype"]) != str(c["dtype"]):
            problems.append(
                f"{key}: dtype {s['dtype']} in checkpoint vs "
                f"{c['dtype']} in current state"
            )
    return problems


# -- orbax-free full-state checkpoints ----------------------------------

_STEP_DIR_RE = re.compile(r"step_(\d{8})")


class LocalCheckpointManager:
    """Self-contained full-train-state checkpoints without orbax: one
    flat .npz + meta.json + crc32 manifest.json per step.

    Robustness contract (the supervisor's default backend):
      * atomic verified writes — each step is staged in a `.tmp-*` dir,
        fsynced, re-read and crc-verified against its manifest, and
        only then `os.replace`d into place; the `LATEST` pointer
        advances only after that verification, so a crash or kill at
        any point mid-write never leaves `latest` naming a torn or
        unverified checkpoint;
      * async saves — `save(..., wait=False)` stalls training only for
        the device->host snapshot; serialization/fsync/verify/publish
        run on a background writer thread (`drain()` to wait them out);
      * keep-last-k retention with pruning of older step dirs — never
        of the newest *verified* checkpoint, even when it falls outside
        the retention window;
      * restore re-verifies the manifest and detects corrupt/partial/
        incompatible steps, falling back to the previous intact one,
        oldest-surviving last.

    Restore device_puts every leaf onto the model's CURRENT shardings,
    so a checkpoint taken on one mesh resumes on another (the same
    reshard-on-restore contract as the orbax manager) — this is what
    carries trained state onto the surviving mesh after a device loss.
    """

    # async backpressure: a save(wait=False) finding this many jobs
    # already queued drains the backlog first.  Each queued job holds a
    # full host copy of the train state (3x weight bytes under Adam), so
    # an unbounded queue behind a slow disk would OOM the host — the
    # durability layer must never be the thing that kills the run.
    MAX_PENDING_SAVES = 2

    def __init__(self, directory: str, max_to_keep: int = 3,
                 offloader=None, remote=None):
        if max_to_keep < 1:
            raise ValueError(f"max_to_keep must be >= 1, got {max_to_keep}")
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        # second durability tier (resilience/offload.py): the offloader
        # mirrors every verified publish; `remote` alone is enough for
        # restore-only consumers (a fresh host, tools/checkpoint_fsck)
        self.offloader = offloader
        self.remote = remote if remote is not None else (
            offloader.remote if offloader is not None else None
        )
        os.makedirs(self.directory, exist_ok=True)
        # tmp dirs from a writer that died mid-save are dead weight
        for name in os.listdir(self.directory):
            if name.startswith(".tmp-"):
                shutil.rmtree(
                    os.path.join(self.directory, name), ignore_errors=True
                )
        self._latest = _LatestPointer(self.directory)
        self._writer = None  # lazy: only wait=False saves pay for a thread
        self._tmp_ids = itertools.count()

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:08d}")

    def all_steps(self):
        out = []
        for name in os.listdir(self.directory):
            m = _STEP_DIR_RE.fullmatch(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def latest_verified_step(self) -> Optional[int]:
        """Newest step the LATEST pointer committed to after write-time
        verification (None when the pointer is absent/stale — e.g. a
        directory written entirely by older code)."""
        step = self._latest.read()
        if step is None or not os.path.isdir(self._path(step)):
            return None
        return step

    def any_restorable(self) -> bool:
        """True when EITHER tier holds at least one checkpoint — the
        resume gate for a fresh host whose local directory is empty but
        whose remote mirror survived the old host's loss."""
        return self.latest_step() is not None or bool(self._remote_steps())

    @staticmethod
    def _state_tree(ff):
        return {
            "weights": ff._weights,
            "opt_state": ff._opt_state,
            "op_state": ff._state,
            "rng": jax.random.key_data(ff._rng),
        }

    # -- save -----------------------------------------------------------
    def _writer_obj(self):
        if self._writer is None:
            from .resilience.async_writer import AsyncCheckpointWriter

            self._writer = AsyncCheckpointWriter()
        return self._writer

    def save(self, ff, step: int, wait: bool = True):
        """Write one full-train-state checkpoint.

        wait=True (default): snapshot + serialize + fsync + verify +
        publish inline — the call returns with the step durable.
        wait=False: only the device->host snapshot happens here (the
        step-boundary stall); the rest runs on the background writer.
        The step becomes visible to latest_step()/restore() once the
        writer publishes it — drain() to wait for that."""
        from jax.tree_util import keystr, tree_flatten_with_path

        tracer = tracer_of(ff)
        registry = registry_of(ff)
        with tracer.span("checkpoint_write", cat="checkpoint", step=step,
                         backend="local", mode="sync" if wait else "async"):
            with tracer.span("snapshot", cat="checkpoint", step=step):
                # async snapshots must own their memory: np.asarray can
                # alias a live device buffer on CPU backends, and the
                # next step DONATES those buffers — a view would be
                # overwritten mid-write.  The sync path writes before
                # returning, so the cheaper view is safe there.
                conv = np.asarray if wait else (lambda x: np.array(x))
                tree = jax.tree.map(conv, self._state_tree(ff))
                leaves, _ = tree_flatten_with_path(tree)
                flat = {keystr(path): leaf for path, leaf in leaves}
                meta = _meta(ff, step)
            if wait:
                with tracer.span("flush", cat="checkpoint", step=step):
                    self._write_and_publish(step, flat, meta, registry)
            else:
                writer = self._writer_obj()
                if registry is not None:
                    gauge = registry.gauge("resilience/ckpt_queue_depth")
                    writer.depth_cb = gauge.set
                if writer.queue_depth >= self.MAX_PENDING_SAVES:
                    # backpressure: the writer is slower than the save
                    # cadence — block until the backlog clears instead
                    # of accumulating full-state host copies unboundedly
                    _log.warning(
                        "async checkpoint writer backlog (%d pending) at "
                        "step %d: draining before the next save — the "
                        "cadence outruns disk bandwidth",
                        writer.queue_depth, step,
                    )
                    writer.wait()  # failures stay for the owner's drain()
                writer.submit(
                    step,
                    lambda: self._flush_job(step, flat, meta, tracer,
                                            registry),
                )

    def _flush_job(self, step, flat, meta, tracer, registry):
        """Writer-thread half of an async save (shows up in the trace
        as a `flush` span on the writer's tid, overlapping the next
        training steps)."""
        with tracer.span("flush", cat="checkpoint", step=step,
                         backend="local", mode="async"):
            self._write_and_publish(step, flat, meta, registry)

    def _write_and_publish(self, step, flat, meta, registry=None):
        """Serialize -> fsync -> re-read + crc-verify -> atomic publish
        -> advance LATEST -> prune.  Any failure leaves the previous
        published state (and pointer) untouched."""
        t0 = time.perf_counter()
        manifest = _build_manifest(step, flat)
        tmp = os.path.join(
            self.directory,
            f".tmp-{step}-{os.getpid()}-{next(self._tmp_ids)}",
        )
        os.makedirs(tmp)
        try:
            with open(os.path.join(tmp, "state.npz"), "wb") as f:
                np.savez(f, **flat)
                f.flush()
                os.fsync(f.fileno())
            _write_json_fsync(os.path.join(tmp, "meta.json"), meta)
            _write_json_fsync(os.path.join(tmp, "manifest.json"), manifest)
            try:
                self._verify_dir(tmp, manifest)
            except CheckpointVerifyError:
                if registry is not None:
                    registry.counter("resilience/ckpt_verify_failures").inc()
                raise
            final = self._path(step)
            if os.path.exists(final):
                # a restored run replaying past an old cadence point
                # re-saves the same step; the fresh write wins
                shutil.rmtree(final)
            os.replace(tmp, final)
            _fsync_dir(self.directory)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._latest.advance(step)
        self._prune()
        if registry is not None:
            registry.histogram("resilience/ckpt_write_latency_s").observe(
                time.perf_counter() - t0
            )
        self._offload_published(step)

    def _offload_published(self, step: int, force: bool = False) -> bool:
        """Hand one just-published (verified) step to the offload tier.
        The bytes are re-read from the published dir so the mirror
        uploads exactly what write-time verification passed.  Runs on
        the async writer thread for wait=False saves — already off the
        step path — and never raises into the publish (the local tier
        must stay intact even when the mirror is broken)."""
        if self.offloader is None:
            return False
        final = self._path(step)
        try:
            files = {}
            for name in ("state.npz", "meta.json", "manifest.json"):
                with open(os.path.join(final, name), "rb") as f:
                    files[name] = f.read()
        except OSError as e:  # pruned/raced away: the mirror skips it
            _log.warning(
                "offload of step %d skipped: published files unreadable "
                "(%s)", step, e,
            )
            return False
        return self.offloader.maybe_submit(step, files, force=force)

    def offload_step(self, step: int) -> bool:
        """Force-mirror one published step regardless of cadence (the
        supervisor's emergency-save path: the last checkpoint before a
        preemption must reach the durable tier)."""
        return self._offload_published(step, force=True)

    @staticmethod
    def _verify_dir(path: str, manifest: Optional[Dict] = None) -> Dict:
        """Re-read a checkpoint dir and check every leaf against its
        manifest crc32; raises CheckpointVerifyError on any mismatch.
        Returns the manifest used."""
        if manifest is None:
            with open(os.path.join(path, "manifest.json")) as f:
                manifest = json.load(f)
        with np.load(os.path.join(path, "state.npz")) as data:
            for key, spec in manifest["leaves"].items():
                if key not in data.files:
                    raise CheckpointVerifyError(
                        f"{path}: leaf {key!r} in manifest but not in "
                        "state.npz"
                    )
                crc = _leaf_crc(data[key])
                if crc != spec["crc32"]:
                    raise CheckpointVerifyError(
                        f"{path}: leaf {key!r} crc32 {crc:#010x} != "
                        f"manifest {spec['crc32']:#010x}"
                    )
            # restore refuses leaves the manifest can't vouch for, so
            # verification must too — a step with extra npz leaves
            # would verify green here and then fail to restore
            for key in data.files:
                if key not in manifest["leaves"]:
                    raise CheckpointVerifyError(
                        f"{path}: leaf {key!r} in state.npz but missing "
                        "from the manifest (unverifiable)"
                    )
        return manifest

    def drain(self) -> List[Tuple[int, Exception]]:
        """Wait for every pending async save to publish (or fail);
        returns the accumulated (step, error) failures."""
        if self._writer is None:
            return []
        return self._writer.drain()

    def _prune(self):
        steps = self.all_steps()
        keep = set(steps[-self.max_to_keep:])
        # the newest VERIFIED checkpoint is the durability floor: never
        # prune it, even when newer (legacy/unverified) steps push it
        # out of the retention window
        verified = self.latest_verified_step()
        if verified is not None:
            keep.add(verified)
        for s in steps:
            if s not in keep:
                shutil.rmtree(self._path(s), ignore_errors=True)

    # -- restore --------------------------------------------------------
    def _remote_steps(self) -> List[int]:
        """Steps the remote tier claims to hold; empty when no remote
        is configured or the remote is unreachable (restore then runs
        local-only — the mirror is an upgrade, never a dependency)."""
        if self.remote is None:
            return []
        try:
            return self.remote.list_steps()
        except Exception as e:  # noqa: BLE001 — any store failure
            _log.warning(
                "remote checkpoint tier unlistable (%s); restoring from "
                "the local tier only", e,
            )
            return []

    def _materialize_remote(self, step: int) -> None:
        """Download one remote step, crc-verify the downloaded bytes in
        a staging dir, and atomically publish it as a LOCAL step dir —
        after this the normal local load path (and every later restore)
        serves it.  A torn/corrupt remote copy never lands locally."""
        files = self.remote.download_step(step)
        tmp = os.path.join(
            self.directory,
            f".tmp-remote-{step}-{os.getpid()}-{next(self._tmp_ids)}",
        )
        os.makedirs(tmp)
        try:
            for name, data in files.items():
                with open(os.path.join(tmp, name), "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
            self._verify_dir(tmp)
            with open(os.path.join(tmp, "meta.json")) as f:
                json.load(f)  # must parse before the dir can publish
            final = self._path(step)
            if os.path.exists(final):
                # the corrupt local copy loses to its verified mirror
                shutil.rmtree(final)
            os.replace(tmp, final)
            _fsync_dir(self.directory)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._latest.advance(step)

    def restore(self, ff, step: Optional[int] = None) -> int:
        """Load a step (default: latest, falling back past corrupt or
        incompatible ones) into a compiled FFModel, re-verifying the
        crc32 manifest and resharding every leaf onto the current
        executor's shardings.  Returns the restored step.

        With a remote tier configured the walk is PER CHECKPOINT,
        local -> remote: a corrupt/missing local step falls back to its
        verified remote mirror (downloaded + re-verified + materialized
        locally) before any progress is given up to an older step — a
        brand-new empty directory restores entirely from remote."""
        from jax.tree_util import tree_unflatten

        strict = step is not None
        local_steps = set(self.all_steps())
        remote_steps = set(self._remote_steps())
        candidates = ([step] if strict
                      else sorted(local_steps | remote_steps, reverse=True))
        if not candidates:
            where = f"no checkpoints in {self.directory}"
            if self.remote is not None:
                where += " (remote tier empty too)"
            raise FileNotFoundError(where)
        last_err: Optional[Exception] = None
        registry = registry_of(ff)
        for s in candidates:
            from_remote = False
            try:
                if s in local_steps or (strict and s not in remote_steps):
                    try:
                        new_leaves, treedef = self._load_step(ff, s)
                    except CheckpointCompatibilityError:
                        raise  # the mirror is byte-identical: same result
                    except Exception as e:
                        if self.remote is None or s not in remote_steps:
                            raise
                        _log.warning(
                            "local step %d unrestorable (%s); trying its "
                            "remote mirror", s, e,
                        )
                        self._materialize_remote(s)
                        new_leaves, treedef = self._load_step(ff, s)
                        from_remote = True
                else:
                    self._materialize_remote(s)
                    new_leaves, treedef = self._load_step(ff, s)
                    from_remote = True
            except Exception as e:  # unreadable/partial -> previous step
                if strict:
                    raise
                _log.warning(
                    "checkpoint step %d in %s unrestorable (%s); "
                    "falling back to the previous step", s, self.directory, e,
                )
                last_err = e
                continue
            if last_err is not None:
                _log.warning(
                    "restored OLDER step %d from %s — newer step(s) were "
                    "corrupt/partial, their progress is lost",
                    s, self.directory,
                )
                # newer steps failed verification: re-point LATEST at
                # the step that actually restored
                self._latest.advance(s, force=True)
            if from_remote:
                _log.info(
                    "step %d restored from the remote tier into %s",
                    s, self.directory,
                )
                if registry is not None:
                    registry.counter(
                        "resilience/offload_remote_restores"
                    ).inc()
            restored = tree_unflatten(treedef, new_leaves)
            ff._weights = restored["weights"]
            ff._opt_state = restored["opt_state"]
            ff._state = restored["op_state"]
            ff._rng = jax.random.wrap_key_data(restored["rng"])
            if hasattr(ff, "sync_decode_pos"):
                ff.sync_decode_pos()
            return int(s)
        raise last_err

    def _load_step(self, ff, step: int):
        """Read + verify + validate one step dir; returns (leaves,
        treedef) device_put onto the current shardings."""
        from jax.tree_util import keystr, tree_flatten_with_path

        with open(os.path.join(self._path(step), "meta.json")) as f:
            meta = json.load(f)  # meta must parse for the step to count
        manifest = None
        manifest_path = os.path.join(self._path(step), "manifest.json")
        if os.path.exists(manifest_path):  # absent in pre-manifest ckpts
            with open(manifest_path) as f:
                manifest = json.load(f)
        with np.load(os.path.join(self._path(step), "state.npz")) as data:
            # one decompression per leaf: each data[key] access re-reads
            arrays = {key: data[key] for key in data.files}
        return _verify_adapt_put(
            ff, self._state_tree(ff), arrays, manifest, meta, step
        )

    def restore_meta(self, step: Optional[int] = None) -> Dict[str, Any]:
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints in {self.directory}")
        with open(os.path.join(self._path(step), "meta.json")) as f:
            return dict(json.load(f))

    def close(self):
        if self._writer is not None:
            self._writer.close()
            self._writer = None


# -- plain numpy weight files (reference-parity path) -------------------

def save_weights_npz(ff, path: str):
    """Weights-only flat .npz (the reference's manual numpy round-trip,
    flexflow_cffi.py Tensor get_weights)."""
    flat = {}
    for op_name, wdict in ff.get_weights().items():
        for wname, arr in wdict.items():
            flat[f"{op_name}/{wname}"] = np.asarray(arr)
    np.savez(path, **flat)


def load_weights_npz(ff, path: str):
    data = np.load(path)
    nested: Dict[str, Dict[str, np.ndarray]] = {}
    for key in data.files:
        op_name, wname = key.rsplit("/", 1)
        nested.setdefault(op_name, {})[wname] = data[key]
    ff.set_weights(nested)


class ModelCheckpoint:
    """Keras-style callback saving every epoch via CheckpointManager.

    async_save=True uses wait=False saves (the epoch boundary stalls
    only for the snapshot); `fit` drains the manager on every exit so a
    crash mid-epoch still lands the last queued save."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = False):
        self.manager = CheckpointManager(directory, max_to_keep=max_to_keep)
        self.async_save = async_save

    def on_train_begin(self, ffmodel):
        pass

    def on_epoch_end(self, ffmodel, epoch: int, metrics):
        self.manager.save(ffmodel, epoch, wait=not self.async_save)

    def on_train_end(self, ffmodel):
        self.manager.close()
