"""Checkpoint / resume.

The reference has no real checkpoint format — weights round-trip through
numpy by hand (parallel_tensor.cc:650-750) and SURVEY §5 flags
checkpoint/resume as a gap to close fresh.  TPU-native answer: orbax for
sharded async-capable saves of the full training state (weights,
optimizer state, op state, step, rng), plus the strategy JSON and a
config snapshot so `restore` can rebuild byte-identical training on a
fresh process — including onto a *different* mesh (orbax resharding on
restore handles the re-layout).
"""
from __future__ import annotations

import json
import logging
import os
import re
import shutil
from typing import Any, Dict, Optional

import jax
import numpy as np

_log = logging.getLogger("flexflow_tpu.checkpoint")


def _meta(ff, step: int) -> Dict[str, Any]:
    return {
        "step": step,
        "version": 1,
        "strategy": ff.strategy.to_json() if ff.strategy is not None else None,
        "batch_size": ff.config.batch_size,
        "num_devices": ff.config.num_devices,
        # ZeRO-1 layout marker: restore reshards slot leaves onto the
        # CURRENT executor's shardings either way (sharded<->replicated
        # and elastic meshes both round-trip); recorded so tooling can
        # see which layout produced the artifact
        "weight_update_sharding": bool(
            getattr(ff.config, "weight_update_sharding", False)
        ),
        "wus_axis": getattr(ff.config, "wus_axis", None),
    }


class CheckpointManager:
    """Orbax-backed manager bound to a compiled FFModel.

    save/restore the full train state; `max_to_keep` rotates old steps.
    Restore reshards to the model's *current* executor shardings, so a
    checkpoint taken on one mesh resumes on another.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )
        self._ocp = ocp

    # -- save -----------------------------------------------------------
    def save(self, ff, step: int, wait: bool = True):
        """Persist weights + optimizer state + op state + rng + strategy."""
        from .obs.trace import tracer_of

        ocp = self._ocp
        state = {
            "weights": ff._weights,
            "opt_state": ff._opt_state,
            "op_state": ff._state,
            "rng": jax.random.key_data(ff._rng),
        }
        with tracer_of(ff).span("checkpoint_write", cat="checkpoint",
                                step=step, backend="orbax"):
            self._mgr.save(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(state),
                    meta=ocp.args.JsonSave(_meta(ff, step)),
                ),
            )
            if wait:
                self._mgr.wait_until_finished()

    # -- restore --------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return list(self._mgr.all_steps())

    def restore(self, ff, step: Optional[int] = None) -> int:
        """Load a step (default: latest) into a compiled FFModel,
        resharding every leaf to the current executor's shardings.
        Returns the restored step.

        With step=None a corrupt/partial latest checkpoint is skipped
        and the previous one restored instead (the crash that truncated
        the write is usually the crash being recovered from); an
        explicitly requested step stays strict."""
        if step is not None:
            return self._restore_step(ff, step)
        steps = sorted(self._mgr.all_steps(), reverse=True)
        if not steps:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        last_err: Optional[Exception] = None
        for s in steps:
            try:
                restored = self._restore_step(ff, s)
            except Exception as e:  # noqa: BLE001 — orbax raises various
                _log.warning(
                    "checkpoint step %d in %s unrestorable (%s); "
                    "falling back to the previous step", s, self.directory, e,
                )
                last_err = e
                continue
            if last_err is not None:
                _log.warning(
                    "restored OLDER step %d from %s — newer step(s) were "
                    "corrupt/partial, their progress is lost",
                    restored, self.directory,
                )
            return restored
        raise last_err

    def _restore_step(self, ff, step: int) -> int:
        ocp = self._ocp
        target = {
            "weights": ff._weights,
            "opt_state": ff._opt_state,
            "op_state": ff._state,
            "rng": jax.random.key_data(ff._rng),
        }
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype,
                sharding=getattr(x, "sharding", None),
            ),
            target,
        )
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract),
                meta=ocp.args.JsonRestore(),
            ),
        )
        state = restored["state"]
        ff._weights = state["weights"]
        ff._opt_state = state["opt_state"]
        ff._state = state["op_state"]
        ff._rng = jax.random.wrap_key_data(state["rng"])
        # restored cache_pos may be mid-sequence; rebuild the host-side
        # decode guard from the device value (ADVICE r4)
        if hasattr(ff, "sync_decode_pos"):
            ff.sync_decode_pos()
        return int(step)

    def restore_meta(self, step: Optional[int] = None) -> Dict[str, Any]:
        ocp = self._ocp
        if step is None:
            step = self._mgr.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints in {self.directory}")
        restored = self._mgr.restore(
            step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
        )
        return dict(restored["meta"])

    def close(self):
        self._mgr.close()


# -- orbax-free full-state checkpoints ----------------------------------

_STEP_DIR_RE = re.compile(r"step_(\d{8})")


class LocalCheckpointManager:
    """Self-contained full-train-state checkpoints without orbax: one
    flat .npz + meta.json per step.

    Robustness contract (the supervisor's default backend):
      * atomic writes — each step is staged in a `.tmp-*` dir and
        `os.replace`d into place, so a crash mid-save never leaves a
        half-written step dir that parses as a checkpoint;
      * keep-last-k retention with pruning of older step dirs;
      * restore detects a corrupt/partial latest step (unreadable npz,
        missing meta, missing leaves) and falls back to the previous
        one, oldest-surviving last.

    Restore device_puts every leaf onto the model's CURRENT shardings,
    so a checkpoint taken on one mesh resumes on another (the same
    reshard-on-restore contract as the orbax manager) — this is what
    carries trained state onto the surviving mesh after a device loss.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        if max_to_keep < 1:
            raise ValueError(f"max_to_keep must be >= 1, got {max_to_keep}")
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        os.makedirs(self.directory, exist_ok=True)
        # tmp dirs from a writer that died mid-save are dead weight
        for name in os.listdir(self.directory):
            if name.startswith(".tmp-"):
                shutil.rmtree(
                    os.path.join(self.directory, name), ignore_errors=True
                )

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:08d}")

    def all_steps(self):
        out = []
        for name in os.listdir(self.directory):
            m = _STEP_DIR_RE.fullmatch(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    @staticmethod
    def _state_tree(ff):
        return {
            "weights": ff._weights,
            "opt_state": ff._opt_state,
            "op_state": ff._state,
            "rng": jax.random.key_data(ff._rng),
        }

    # -- save -----------------------------------------------------------
    def save(self, ff, step: int, wait: bool = True):
        from jax.tree_util import keystr, tree_flatten_with_path

        from .obs.trace import tracer_of

        with tracer_of(ff).span("checkpoint_write", cat="checkpoint",
                                step=step, backend="local"):
            tree = jax.tree.map(np.asarray, self._state_tree(ff))
            leaves, _ = tree_flatten_with_path(tree)
            flat = {keystr(path): leaf for path, leaf in leaves}
            tmp = os.path.join(self.directory, f".tmp-{step}-{os.getpid()}")
            os.makedirs(tmp)
            try:
                np.savez(os.path.join(tmp, "state.npz"), **flat)
                with open(os.path.join(tmp, "meta.json"), "w") as f:
                    json.dump(_meta(ff, step), f)
                final = self._path(step)
                if os.path.exists(final):
                    # a restored run replaying past an old cadence point
                    # re-saves the same step; the fresh write wins
                    shutil.rmtree(final)
                os.replace(tmp, final)
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
        self._prune()

    def _prune(self):
        steps = self.all_steps()
        for s in steps[: -self.max_to_keep]:
            shutil.rmtree(self._path(s), ignore_errors=True)

    # -- restore --------------------------------------------------------
    def restore(self, ff, step: Optional[int] = None) -> int:
        """Load a step (default: latest, falling back past corrupt ones)
        into a compiled FFModel, resharding every leaf onto the current
        executor's shardings.  Returns the restored step."""
        from jax.tree_util import keystr, tree_flatten_with_path, tree_unflatten

        if step is not None:
            candidates = [step]
        else:
            candidates = list(reversed(self.all_steps()))
        if not candidates:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        last_err: Optional[Exception] = None
        for s in candidates:
            try:
                with open(os.path.join(self._path(s), "meta.json")) as f:
                    json.load(f)  # meta must parse for the step to count
                with np.load(os.path.join(self._path(s), "state.npz")) as data:
                    target = self._state_tree(ff)
                    leaves, treedef = tree_flatten_with_path(target)
                    new_leaves = []
                    for path, cur in leaves:
                        arr = data[keystr(path)]  # KeyError -> partial ckpt
                        sh = getattr(cur, "sharding", None)
                        new_leaves.append(
                            jax.device_put(arr, sh) if sh is not None else arr
                        )
            except Exception as e:  # unreadable/partial -> previous step
                _log.warning(
                    "checkpoint step %d in %s unrestorable (%s); "
                    "falling back to the previous step", s, self.directory, e,
                )
                last_err = e
                continue
            if last_err is not None:
                _log.warning(
                    "restored OLDER step %d from %s — newer step(s) were "
                    "corrupt/partial, their progress is lost",
                    s, self.directory,
                )
            restored = tree_unflatten(treedef, new_leaves)
            ff._weights = restored["weights"]
            ff._opt_state = restored["opt_state"]
            ff._state = restored["op_state"]
            ff._rng = jax.random.wrap_key_data(restored["rng"])
            if hasattr(ff, "sync_decode_pos"):
                ff.sync_decode_pos()
            return int(s)
        raise last_err

    def restore_meta(self, step: Optional[int] = None) -> Dict[str, Any]:
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints in {self.directory}")
        with open(os.path.join(self._path(step), "meta.json")) as f:
            return dict(json.load(f))

    def close(self):
        pass


# -- plain numpy weight files (reference-parity path) -------------------

def save_weights_npz(ff, path: str):
    """Weights-only flat .npz (the reference's manual numpy round-trip,
    flexflow_cffi.py Tensor get_weights)."""
    flat = {}
    for op_name, wdict in ff.get_weights().items():
        for wname, arr in wdict.items():
            flat[f"{op_name}/{wname}"] = np.asarray(arr)
    np.savez(path, **flat)


def load_weights_npz(ff, path: str):
    data = np.load(path)
    nested: Dict[str, Dict[str, np.ndarray]] = {}
    for key in data.files:
        op_name, wname = key.rsplit("/", 1)
        nested.setdefault(op_name, {})[wname] = data[key]
    ff.set_weights(nested)


class ModelCheckpoint:
    """Keras-style callback saving every epoch via CheckpointManager."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.manager = CheckpointManager(directory, max_to_keep=max_to_keep)

    def on_train_begin(self, ffmodel):
        pass

    def on_epoch_end(self, ffmodel, epoch: int, metrics):
        self.manager.save(ffmodel, epoch)

    def on_train_end(self, ffmodel):
        self.manager.close()
