// Native batch-assembly core for the dataloader.
//
// Counterpart of the reference's C++/CUDA dataloader
// (python/flexflow_dataloader.cc: full-dataset-in-ZC-mem ingest +
// per-batch index-task loads).  On TPU the device transfer is
// jax.device_put; the host-side hot path — gathering shuffled sample
// rows into a contiguous batch buffer — is this file.  ctypes releases
// the GIL for the call, so assembly overlaps with the jitted step.
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Gather rows: dst[i] = src[indices[i]] for i in [0, n).
// row_bytes is the size of one sample row; src has num_rows rows.
// Multithreaded for large batches; returns 0 on success.
int ffdl_gather_rows(const uint8_t *src, int64_t num_rows, int64_t row_bytes,
                     const int64_t *indices, int64_t n, uint8_t *dst) {
  for (int64_t i = 0; i < n; i++) {
    if (indices[i] < 0 || indices[i] >= num_rows) return -1;
  }
  const int64_t total = n * row_bytes;
  int nthreads = 1;
  if (total > (4 << 20)) {
    unsigned hw = std::thread::hardware_concurrency();
    nthreads = hw > 8 ? 8 : (hw ? (int)hw : 1);
  }
  if (nthreads <= 1) {
    for (int64_t i = 0; i < n; i++) {
      std::memcpy(dst + i * row_bytes, src + indices[i] * row_bytes,
                  (size_t)row_bytes);
    }
    return 0;
  }
  std::vector<std::thread> workers;
  workers.reserve(nthreads);
  const int64_t chunk = (n + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; t++) {
    const int64_t lo = t * chunk;
    const int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    workers.emplace_back([=]() {
      for (int64_t i = lo; i < hi; i++) {
        std::memcpy(dst + i * row_bytes, src + indices[i] * row_bytes,
                    (size_t)row_bytes);
      }
    });
  }
  for (auto &w : workers) w.join();
  return 0;
}

// Fisher-Yates shuffle of [0..n) with an xorshift64 PRNG — matches the
// Python fallback in dataloader.py exactly (same algorithm, same seed
// evolution) so shuffled epochs are reproducible across backends.
void ffdl_shuffle_indices(int64_t *indices, int64_t n, uint64_t seed) {
  for (int64_t i = 0; i < n; i++) indices[i] = i;
  uint64_t s = seed ? seed : 0x9E3779B97F4A7C15ull;
  for (int64_t i = n - 1; i > 0; i--) {
    s ^= s << 13;
    s ^= s >> 7;
    s ^= s << 17;
    int64_t j = (int64_t)(s % (uint64_t)(i + 1));
    int64_t tmp = indices[i];
    indices[i] = indices[j];
    indices[j] = tmp;
  }
}

}  // extern "C"
