"""Native (C++) runtime components, loaded via ctypes.

The reference implements its runtime core in C++ (simulator event loop
src/runtime/simulator.cc, dataloader python/flexflow_dataloader.cc);
this package holds the TPU-native equivalents.  The shared library is
(re)built on demand with the in-tree Makefile — `g++` is assumed (no
pip deps); when the toolchain or build is unavailable every consumer
falls back to a pure-Python implementation with identical semantics.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libffnative.so")
_lock = threading.Lock()
_lib = None
_load_attempted = False


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    for f in os.listdir(_DIR):
        if f.endswith((".cc", ".h")) and os.path.getmtime(
            os.path.join(_DIR, f)
        ) > lib_mtime:
            return True
    return False


def _build() -> bool:
    try:
        r = subprocess.run(
            ["make", "-C", _DIR],
            capture_output=True, text=True, timeout=120,
        )
        return r.returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The native library, building it first if stale; None if
    unavailable (consumers must fall back to Python)."""
    global _lib, _load_attempted
    with _lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        if _needs_build() and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            if lib.ffsim_abi_version() != 1:
                return None
            _lib = lib
        except OSError:
            return None
        return _lib
