// Discrete-event task-graph simulator — the native core of the
// strategy-cost engine.
//
// TPU-native counterpart of the reference's C++ simulator event loop
// (/root/reference/src/runtime/simulator.cc:822-1250 simulate_runtime,
// and the fork's LogicalTaskgraphBasedSimulator :1251-1800 with routed
// per-link transfers + ring allreduce expansion network.cc).  Fresh
// implementation: a single chronological event heap drives per-device
// FIFO execution and per-link FIFO transfer serialization; collectives
// arrive already expanded into ring phases by the Python builder
// (flexflow_tpu/sim/taskgraph.py), the way expand_allreduce does.
//
// Build: make -C flexflow_tpu/native   (g++ -O2 -shared -fPIC)
// ABI: plain C, consumed via ctypes; arrays are CSR-encoded.
//
// Determinism contract (mirrored by the pure-Python fallback in
// sim/taskgraph.py): ties broken by (time, sequence-number), transfers
// scheduled in the chronological order of their producing task's finish
// event, links traversed store-and-forward in route order.

#include <cstdint>
#include <cstring>
#include <queue>
#include <vector>

namespace {

struct Event {
  double time;
  int64_t seq;   // tie-break: deterministic ordering
  int kind;      // 0 = task ready on its device, 1 = task finish
  int64_t task;
  bool operator>(const Event& o) const {
    if (time != o.time) return time > o.time;
    if (seq != o.seq) return seq > o.seq;
    return task > o.task;
  }
};

struct ReadyItem {
  double ready;
  int64_t task;
  bool operator>(const ReadyItem& o) const {
    if (ready != o.ready) return ready > o.ready;
    return task > o.task;
  }
};

}  // namespace

extern "C" {

// Returns 0 on success, nonzero on malformed input (cycle / bad ids).
//
// Tasks: num_tasks entries; compute_time[t] seconds on device_of[t].
// Dependencies (plain, same- or cross-device with zero transfer):
//   CSR dep_offsets[num_tasks+1] -> dep_ids[].
// Comm edges (producer -> consumer with routed transfer):
//   num_edges entries, edge_src/edge_dst tasks, edge_bytes[],
//   CSR route_offsets[num_edges+1] -> route_links[] (link ids in
//   traversal order; empty route = zero-time dependency).
// Links: link_bandwidth[l] bytes/s, link_latency[l] seconds.
// Outputs: out_makespan, out_device_busy[num_devices],
//   out_finish[num_tasks] (may be null).
int ffsim_simulate(
    int64_t num_tasks, const double* compute_time, const int32_t* device_of,
    const int64_t* dep_offsets, const int32_t* dep_ids,
    int64_t num_edges, const int32_t* edge_src, const int32_t* edge_dst,
    const double* edge_bytes,
    const int64_t* route_offsets, const int32_t* route_links,
    int64_t num_links, const double* link_bandwidth,
    const double* link_latency,
    int32_t num_devices,
    double* out_makespan, double* out_device_busy, double* out_finish) {
  if (num_tasks <= 0 || num_devices <= 0) return 1;

  // per-task incoming counts = plain deps + incoming comm edges
  std::vector<int64_t> remaining(num_tasks, 0);
  std::vector<double> ready_time(num_tasks, 0.0);
  for (int64_t t = 0; t < num_tasks; ++t)
    remaining[t] = dep_offsets[t + 1] - dep_offsets[t];
  // outgoing adjacency for plain deps: build reverse CSR
  std::vector<std::vector<int32_t>> dep_out(num_tasks);
  for (int64_t t = 0; t < num_tasks; ++t)
    for (int64_t i = dep_offsets[t]; i < dep_offsets[t + 1]; ++i) {
      int32_t p = dep_ids[i];
      if (p < 0 || p >= num_tasks) return 2;
      dep_out[p].push_back((int32_t)t);
    }
  std::vector<std::vector<int32_t>> edge_out(num_tasks);
  for (int64_t e = 0; e < num_edges; ++e) {
    if (edge_src[e] < 0 || edge_src[e] >= num_tasks) return 2;
    if (edge_dst[e] < 0 || edge_dst[e] >= num_tasks) return 2;
    edge_out[edge_src[e]].push_back((int32_t)e);
    remaining[edge_dst[e]] += 1;
  }

  std::vector<double> link_avail(num_links, 0.0);
  std::vector<double> dev_busy(num_devices, 0.0);
  std::vector<bool> dev_idle(num_devices, true);
  std::vector<double> finish(num_tasks, 0.0);
  std::vector<std::priority_queue<ReadyItem, std::vector<ReadyItem>,
                                  std::greater<ReadyItem>>>
      dev_queue(num_devices);

  std::priority_queue<Event, std::vector<Event>, std::greater<Event>> events;
  int64_t seq = 0;
  int64_t completed = 0;
  double makespan = 0.0;

  for (int64_t t = 0; t < num_tasks; ++t)
    if (remaining[t] == 0)
      events.push(Event{0.0, seq++, 0, t});

  auto try_start = [&](int32_t dev, double now) {
    while (dev_idle[dev] && !dev_queue[dev].empty()) {
      ReadyItem it = dev_queue[dev].top();
      dev_queue[dev].pop();
      double start = now > it.ready ? now : it.ready;
      double fin = start + compute_time[it.task];
      dev_idle[dev] = false;
      dev_busy[dev] += compute_time[it.task];
      finish[it.task] = fin;
      events.push(Event{fin, seq++, 1, it.task});
    }
  };

  auto satisfy = [&](int64_t t, double at) {
    if (at > ready_time[t]) ready_time[t] = at;
    if (--remaining[t] == 0)
      events.push(Event{ready_time[t], seq++, 0, t});
  };

  while (!events.empty()) {
    Event ev = events.top();
    events.pop();
    double now = ev.time;
    int32_t dev = device_of[ev.task];
    if (dev < 0 || dev >= num_devices) return 3;
    if (ev.kind == 0) {  // ready
      dev_queue[dev].push(ReadyItem{now, ev.task});
      try_start(dev, now);
    } else {  // finish
      ++completed;
      if (now > makespan) makespan = now;
      // plain dependents
      for (int32_t d : dep_out[ev.task]) satisfy(d, now);
      // routed transfers, in deterministic (finish-event, edge) order
      for (int32_t e : edge_out[ev.task]) {
        double t = now;
        for (int64_t i = route_offsets[e]; i < route_offsets[e + 1]; ++i) {
          int32_t l = route_links[i];
          if (l < 0 || l >= num_links) return 4;
          double begin = t > link_avail[l] ? t : link_avail[l];
          double done = begin + link_latency[l] +
                        (link_bandwidth[l] > 0.0
                             ? edge_bytes[e] / link_bandwidth[l]
                             : 0.0);
          link_avail[l] = done;
          t = done;
        }
        satisfy(edge_dst[e], t);
      }
      dev_idle[dev] = true;
      try_start(dev, now);
    }
  }

  if (completed != num_tasks) return 5;  // cycle or unreachable tasks
  *out_makespan = makespan;
  if (out_device_busy)
    std::memcpy(out_device_busy, dev_busy.data(),
                sizeof(double) * num_devices);
  if (out_finish)
    std::memcpy(out_finish, finish.data(), sizeof(double) * num_tasks);
  return 0;
}

// ABI version probe for the ctypes loader.
int ffsim_abi_version() { return 1; }

}  // extern "C"
