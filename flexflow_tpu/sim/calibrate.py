"""Fit the simulator's cost scales + overlap constants from measured
step times.

Reference: every per-op cost in the reference search is a real kernel
measurement (inner_measure_operator_cost, model.cu:38-75), but its
comm/compute OVERLAP treatment is baked into the event simulation.
This module closes the same gap for the analytic path: the
`overlap_fraction` (how much parallel-op comm hides behind compute) and
`sync_overlap_fraction` (how much gradient sync hides behind backward)
were hand-set heuristics (0.3 / 0.7, pcg/unity.py:90-107, VERDICT r03
Weak #4).  Here the full prediction

    measured(s) ~= c·compute(s) + u·comm(s) + v·sync(s)

is least-squares fit over the SAME model compiled under different
strategies (single-device anchors c; dp / dp x tp / tp separate u and
v).  c calibrates the cost model's roofline to the live backend (the
role per-op measurement plays on-chip); u and v generalize
(1-overlap_fraction) / (1-sync_overlap_fraction) — they also absorb any
machine-model bandwidth error, which is exactly right for a constant
consumed by the same machine model during search ranking.  Fitted
values persist beside the op-cost cache and are picked up by the search
entry points (unity_optimize / mcmc_optimize) in later runs.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as np


def fit_cost_scales(
    records: Sequence[Tuple[float, float, float, float]],
) -> Dict[str, float]:
    """records: (measured_total, compute, comm, sync) seconds per
    strategy.  Solves nonneg least squares for (c, u, v); returns the
    scales plus the equivalent overlap constants (of = 1-u, sof = 1-v,
    may be negative when the machine model underestimates comm) and the
    mean relative prediction error after the fit."""
    A = np.asarray([[r[1], r[2], r[3]] for r in records], np.float64)
    b = np.asarray([r[0] for r in records], np.float64)
    x = np.array([1.0, 0.7, 0.3])  # priors: c=1, u=1-of, v=1-sof
    usable = np.abs(A).sum(axis=0) > 0
    if usable.any():
        sol, *_ = np.linalg.lstsq(A[:, usable], b, rcond=None)
        x[usable] = np.maximum(sol, 0.0)
    pred = A @ x
    rel = np.abs(pred - b) / np.maximum(b, 1e-12)
    return {
        "compute_scale": float(x[0]),
        "comm_scale": float(x[1]),
        "sync_scale": float(x[2]),
        "overlap_fraction": float(1.0 - x[1]),
        "sync_overlap_fraction": float(1.0 - x[2]),
        "mean_rel_error": float(rel.mean()),
        "max_rel_error": float(rel.max()),
        "num_strategies": len(records),
    }


def measure_step_time(ff, inputs, labels, iters: int = 12,
                      windows: int = 3) -> float:
    """Best-of-N windows of serial steps with ONE hard sync each (the
    bench.py `_steady_state` discipline — see
    .claude/skills/verify/SKILL.md on tunnel jitter)."""
    for _ in range(2):
        m = ff.train_step(inputs, labels)
    _ = float(m["loss"])

    def window():
        t0 = time.perf_counter()
        for _ in range(iters):
            m = ff.train_step(inputs, labels)
        _ = float(m["loss"])
        return (time.perf_counter() - t0) / iters

    return min(window() for _ in range(windows))


def simulate_components(ff, strategy, machine,
                        cost_model) -> Tuple[float, float, float]:
    """(compute, comm, sync) seconds the simulator attributes to the
    compiled model under `strategy` — the regressors of the fit."""
    from .simulator import Simulator

    sim = Simulator(machine, cost_model)
    res = sim.simulate(ff.operators, strategy.mesh_axes, training=True)
    return res.compute_time, res.comm_time, res.sync_time


def calibrate_overlap(
    build, strategies, devices, machine, cost_model,
    make_inputs, iters: int = 12, windows: int = 3,
) -> Dict[str, float]:
    """Compile `build()` under each (strategy, num_devices) pair,
    measure real step time, simulate its analytic components, and fit.

    build() -> a fresh un-compiled FFModel with layers added.
    strategies: [(Strategy, n_devices)] — include a single-device entry
        (comm=sync=0) so the compute scale is anchored.
    make_inputs(ff) -> (inputs dict, labels) device-put for ff.
    """
    from .. import SGDOptimizer

    records = []
    for s, n in strategies:
        ff = build()
        ff.compile(optimizer=SGDOptimizer(lr=0.01), strategy=s,
                   devices=devices[:n])
        inputs, labels = make_inputs(ff)
        measured = measure_step_time(ff, inputs, labels, iters, windows)
        compute, comm, sync = simulate_components(ff, s, machine, cost_model)
        records.append((measured, compute, comm, sync))
    fit = fit_cost_scales(records)
    # constants are backend-specific (a CPU-mesh compute_scale is ~200x
    # a chip's); loaders refuse mismatched backends
    fit["fitted_on"] = devices[0].platform if devices else "unknown"
    return fit


# -- persistence (beside the op-cost cache) --------------------------------

def overlap_constants_path() -> str:
    base = os.environ.get("FLEXFLOW_TPU_CACHE_DIR",
                          os.path.expanduser("~/.cache/flexflow_tpu"))
    os.makedirs(base, exist_ok=True)
    return os.path.join(base, "overlap_constants.json")


def save_overlap_constants(fit: Dict[str, float],
                           path: Optional[str] = None) -> str:
    path = path or overlap_constants_path()
    with open(path, "w") as f:
        json.dump(fit, f, indent=1)
    return path


def load_overlap_constants(path: Optional[str] = None,
                           backend: Optional[str] = None) -> Optional[Dict]:
    """Returns the fitted constants only when their recorded backend
    matches the one in use (default: jax's current backend) — a
    CPU-mesh compute_scale applied on a chip would corrupt every search
    ranking."""
    path = path or overlap_constants_path()
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, ValueError):
        return None
    # sanity: scales nonnegative and finite
    try:
        ok = (np.isfinite(d["compute_scale"]) and d["compute_scale"] >= 0
              and np.isfinite(d["comm_scale"]) and d["comm_scale"] >= 0
              and np.isfinite(d["sync_scale"]) and d["sync_scale"] >= 0)
    except (KeyError, TypeError):
        return None
    if not ok:
        return None
    if backend is None:
        try:
            import jax

            backend = jax.default_backend()
        except Exception:
            return None
    if d.get("fitted_on") != backend:
        return None
    return d
