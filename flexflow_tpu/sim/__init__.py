from .machine_model import MachineModel, SimpleMachineModel, TpuPodModel
from .simulator import CostMetrics, Simulator
