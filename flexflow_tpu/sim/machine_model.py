"""Machine models: analytic cluster models feeding the strategy search.

Reference: src/runtime/machine_model.cc — SimpleMachineModel (v0, flat
inter-GPU/inter-node bandwidths, defaults at machine_model.cc:68-70),
EnhancedMachineModel (v1, config file with membus/UPI/NIC/PCIe/NVLink),
and the fork's NetworkedMachineModel (arbitrary topology matrix with
routed transfers, simulator.h:515-605, network.cc).

TPU-native redesign: `TpuPodModel` models what actually exists on a pod
slice — a per-axis ICI torus (per-hop bandwidth/latency, wraparound
links) and DCN between slices — and exposes *collective* costs
(all-reduce, all-gather, reduce-scatter, all-to-all, ppermute) rather
than point-to-point NCCL costs, because XLA emits collectives.  The
same interface backs the event simulator and the search.

All times in seconds, sizes in bytes.
"""
from __future__ import annotations

import dataclasses
import json
import math
from typing import Dict, Optional, Sequence, Tuple


@dataclasses.dataclass
class DeviceSpec:
    """Per-chip compute/memory capability (defaults: TPU v5p)."""

    peak_flops: float = 459e12  # bf16 FLOP/s (v5p)
    peak_flops_f32: float = 115e12
    hbm_bandwidth: float = 2765e9  # bytes/s (v5p 2.77 TB/s)
    hbm_capacity: float = 95e9  # bytes
    vmem_bytes: float = 128 * 2**20


V5E_DEVICE = DeviceSpec(
    peak_flops=197e12, peak_flops_f32=49e12, hbm_bandwidth=819e9,
    hbm_capacity=16e9,
)
V5P_DEVICE = DeviceSpec()


def detect_device_spec() -> DeviceSpec:
    """Spec for the LIVE accelerator by device_kind — the reference
    profiles the actual GPU (model.cu:38); calibrated analytic costs
    need the actual chip's roofline too."""
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return V5P_DEVICE
    if "lite" in kind or "v5e" in kind:
        return V5E_DEVICE
    return V5P_DEVICE


class MachineModel:
    """Interface consumed by the simulator/search."""

    version: int = -1

    def num_devices(self) -> int:
        raise NotImplementedError

    def device(self) -> DeviceSpec:
        raise NotImplementedError

    def p2p_time(self, size: int, src: int, dst: int) -> float:
        raise NotImplementedError

    # -- collective costs over a device group ---------------------------
    def allreduce_time(self, size: int, group: Sequence[int]) -> float:
        n = len(group)
        if n <= 1:
            return 0.0
        # ring: 2 (n-1)/n * size over the slowest link in the group
        bw, lat = self._group_link(group)
        return 2.0 * (n - 1) / n * size / bw + 2 * (n - 1) * lat

    def allgather_time(self, size: int, group: Sequence[int]) -> float:
        n = len(group)
        if n <= 1:
            return 0.0
        bw, lat = self._group_link(group)
        return (n - 1) / n * size / bw + (n - 1) * lat

    def reducescatter_time(self, size: int, group: Sequence[int]) -> float:
        return self.allgather_time(size, group)

    def ps_link(self) -> Tuple[float, float]:
        """(bandwidth, latency) for parameter-server gradient sync —
        the reference's flat 2*size/BW sync estimate
        (simulator.cc:786-813) rides one link to the server."""
        if hasattr(self, "ici_bw"):  # TpuPodModel
            return self.ici_bw, self.ici_lat
        if hasattr(self, "link_bw"):  # NetworkedMachineModel (network.py:223)
            return self.link_bw, self.link_lat
        return (  # SimpleMachineModel
            getattr(self, "intra_bw", 100e9),
            getattr(self, "intra_lat", 1e-6),
        )

    def alltoall_time(self, size: int, group: Sequence[int]) -> float:
        n = len(group)
        if n <= 1:
            return 0.0
        bw, lat = self._group_link(group)
        return (n - 1) / n * size / bw + (n - 1) * lat

    def _group_link(self, group: Sequence[int]) -> Tuple[float, float]:
        """(bandwidth, latency) of the slowest link inside the group."""
        raise NotImplementedError


class SimpleMachineModel(MachineModel):
    """Flat two-level model for parity with the reference's v0
    (machine_model.cc:58: intra-node bw, inter-node bw/num_nodes)."""

    version = 0

    def __init__(self, num_nodes: int = 1, devices_per_node: int = 8,
                 device: DeviceSpec = V5P_DEVICE,
                 intra_bw: float = 100e9, inter_bw: float = 25e9,
                 intra_lat: float = 1e-6, inter_lat: float = 10e-6):
        self._num_nodes = num_nodes
        self._per_node = devices_per_node
        self._device = device
        self.intra_bw, self.inter_bw = intra_bw, inter_bw
        self.intra_lat, self.inter_lat = intra_lat, inter_lat

    def num_devices(self) -> int:
        return self._num_nodes * self._per_node

    def device(self) -> DeviceSpec:
        return self._device

    def node_of(self, d: int) -> int:
        return d // self._per_node

    def p2p_time(self, size: int, src: int, dst: int) -> float:
        if src == dst:
            return 0.0
        if self.node_of(src) == self.node_of(dst):
            return self.intra_lat + size / self.intra_bw
        return self.inter_lat + size / (self.inter_bw / max(1, self._num_nodes))

    def _group_link(self, group: Sequence[int]) -> Tuple[float, float]:
        nodes = {self.node_of(d) for d in group}
        if len(nodes) > 1:
            return self.inter_bw, self.inter_lat
        return self.intra_bw, self.intra_lat


class TpuPodModel(MachineModel):
    """ICI torus + DCN machine model for TPU pod slices.

    topology: per-axis chip counts of the slice, e.g. (4, 4) for v5p-32
    (16 chips in a 4x4 torus), (2, 2, 1) etc.  Mesh axes of the strategy
    map onto torus axes in order — the canonical layout the real
    mesh_utils.create_device_mesh produces — so a collective over mesh
    axis i rides the per-hop ICI bandwidth of torus axis i.

    slices > 1 models multi-slice training: groups spanning slices pay
    DCN cost per host.
    """

    version = 2

    def __init__(
        self,
        topology: Tuple[int, ...] = (4, 4),
        device: DeviceSpec = V5P_DEVICE,
        ici_bw_per_link: float = 90e9,  # bytes/s each direction (v5p ~100GB/s)
        ici_latency: float = 1e-6,
        dcn_bw_per_host: float = 25e9,
        dcn_latency: float = 10e-6,
        slices: int = 1,
    ):
        self.topology = tuple(topology)
        self._device = device
        self.ici_bw = ici_bw_per_link
        self.ici_lat = ici_latency
        self.dcn_bw = dcn_bw_per_host
        self.dcn_lat = dcn_latency
        self.slices = slices

    @classmethod
    def from_file(cls, path: str) -> "TpuPodModel":
        with open(path) as f:
            d = json.load(f)
        dev = DeviceSpec(**d.get("device", {}))
        return cls(
            topology=tuple(d.get("topology", (4, 4))),
            device=dev,
            ici_bw_per_link=d.get("ici_bw_per_link", 90e9),
            ici_latency=d.get("ici_latency", 1e-6),
            dcn_bw_per_host=d.get("dcn_bw_per_host", 25e9),
            dcn_latency=d.get("dcn_latency", 10e-6),
            slices=d.get("slices", 1),
        )

    def num_devices(self) -> int:
        n = self.slices
        for t in self.topology:
            n *= t
        return n

    def device(self) -> DeviceSpec:
        return self._device

    def coords(self, d: int) -> Tuple[int, ...]:
        out = []
        for t in reversed(self.topology):
            out.append(d % t)
            d //= t
        return tuple(reversed(out))

    def p2p_time(self, size: int, src: int, dst: int) -> float:
        if src == dst:
            return 0.0
        a, b = self.coords(src % self._chips_per_slice()), self.coords(
            dst % self._chips_per_slice()
        )
        if src // self._chips_per_slice() != dst // self._chips_per_slice():
            return self.dcn_lat + size / self.dcn_bw
        hops = 0
        for ai, bi, t in zip(a, b, self.topology):
            d = abs(ai - bi)
            hops += min(d, t - d)  # torus wraparound
        return hops * self.ici_lat + size / self.ici_bw

    def _chips_per_slice(self) -> int:
        n = 1
        for t in self.topology:
            n *= t
        return n

    def _group_link(self, group: Sequence[int]) -> Tuple[float, float]:
        per_slice = self._chips_per_slice()
        slices = {d // per_slice for d in group}
        if len(slices) > 1:
            return self.dcn_bw, self.dcn_lat
        return self.ici_bw, self.ici_lat

    # -- axis-aware collective costs (preferred API) --------------------
    # `lat_scale` scales the per-hop latency term only (bandwidth bytes
    # are untouched): the DCN grad-sync bucketing amortizes a bucketed
    # leaf's launch latency over the bucket it rides in
    # (sim/simulator.py _collective).  1.0 = the unbucketed estimate.
    def axis_allreduce_time(self, size: int, axis_len: int,
                            over_dcn: bool = False,
                            lat_scale: float = 1.0) -> float:
        """Bidirectional-ring all-reduce along one torus axis: each of
        the two directions carries half the data, so the effective
        bandwidth is 2 links."""
        if axis_len <= 1:
            return 0.0
        bw = self.dcn_bw if over_dcn else 2.0 * self.ici_bw
        lat = (self.dcn_lat if over_dcn else self.ici_lat) * lat_scale
        return 2.0 * (axis_len - 1) / axis_len * size / bw + 2 * (axis_len - 1) * lat

    def axis_allgather_time(self, size: int, axis_len: int,
                            over_dcn: bool = False,
                            lat_scale: float = 1.0) -> float:
        if axis_len <= 1:
            return 0.0
        bw = self.dcn_bw if over_dcn else 2.0 * self.ici_bw
        lat = (self.dcn_lat if over_dcn else self.ici_lat) * lat_scale
        return (axis_len - 1) / axis_len * size / bw + (axis_len - 1) * lat

    def axis_alltoall_time(self, size: int, axis_len: int,
                           over_dcn: bool = False,
                           lat_scale: float = 1.0) -> float:
        if axis_len <= 1:
            return 0.0
        bw = self.dcn_bw if over_dcn else 2.0 * self.ici_bw
        lat = (self.dcn_lat if over_dcn else self.ici_lat) * lat_scale
        t_bw = (axis_len - 1) / axis_len * size / bw
        if not over_dcn:
            # on a ring/torus axis the all-to-all is bisection-bound:
            # ~axis_len/4 of the traffic crosses the cut links (scales
            # the bandwidth term only, not per-hop latency)
            t_bw *= max(1.0, axis_len / 4.0)
        return t_bw + (axis_len - 1) * lat


def make_machine_model(config, num_devices: int) -> MachineModel:
    """Build from FFConfig (--machine-model-version/-file parity).
    Device roofline auto-matches the live chip (cpu -> v5p defaults,
    keeping hermetic tests deterministic).  --slices > 1 selects the
    multi-slice hierarchy (topology/hierarchy.py SliceHierarchy: ICI
    inside each slice, DCN between) regardless of model version — the
    hierarchy is what the searches must see; 1 slice is exactly the
    flat pre-topology behavior."""
    if getattr(config, "slices", 1) > 1:
        # a degraded mesh (elastic re-search on survivors) may no
        # longer split into equal slices — or match the configured
        # per-slice topology's chip count: degrade to the flat model
        # rather than failing recovery over a cost-model nicety
        import logging

        try:
            from ..topology.hierarchy import hierarchy_from_config

            return hierarchy_from_config(config, num_devices)
        except ValueError as e:
            logging.getLogger("flexflow_tpu.topology").warning(
                "slice hierarchy unusable for %d devices (%s); falling "
                "back to the flat machine model", num_devices, e,
            )
    if config.machine_model_file:
        return TpuPodModel.from_file(config.machine_model_file)
    spec = detect_device_spec()
    if config.machine_model_version == 0:
        return SimpleMachineModel(
            num_nodes=max(1, config.num_nodes),
            devices_per_node=max(1, num_devices // max(1, config.num_nodes)),
            device=spec,
        )
    # default TPU pod: 1-D ring topology of the right size
    return TpuPodModel(topology=(num_devices,), device=spec)
