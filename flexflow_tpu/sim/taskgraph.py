"""Event-driven task-graph simulation (native C++ core + Python fallback).

Reference: the simulator's event loop `simulate_runtime`
(src/runtime/simulator.cc:822-1250) and the fork's topology-aware
`LogicalTaskgraphBasedSimulator` (:1251-1800) with `route_transfer`
(:1488) and `expand_allreduce` (:1690) over the network model
(network.cc).  Like the reference, the hot loop is native C++
(flexflow_tpu/native/taskgraph_sim.cc, loaded via ctypes); a
semantically identical pure-Python event loop backs it for environments
without a toolchain, and the two are tested for exact agreement.

TPU-native redesign of the *model*: devices sit on an ICI ring/torus
(TpuPodModel); XLA collectives are expanded into ring phases — a ring
all-reduce over n devices of S bytes becomes 2(n-1) phases of n
neighbor transfers of S/n bytes, each routed over the per-hop ICI links
so link contention between overlapping collectives is simulated, which
the analytic model (sim/simulator.py) cannot see.
"""
from __future__ import annotations

import ctypes
import dataclasses
import heapq
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..fftype import OperatorType
from ..pcg.graph import Graph
from .machine_model import MachineModel, TpuPodModel
from .simulator import OpCostModel, SimResult


@dataclasses.dataclass
class TaskGraphArrays:
    compute_time: np.ndarray  # f64 [T]
    device_of: np.ndarray  # i32 [T]
    dep_offsets: np.ndarray  # i64 [T+1]
    dep_ids: np.ndarray  # i32
    edge_src: np.ndarray  # i32 [E]
    edge_dst: np.ndarray  # i32 [E]
    edge_bytes: np.ndarray  # f64 [E]
    route_offsets: np.ndarray  # i64 [E+1]
    route_links: np.ndarray  # i32
    link_bandwidth: np.ndarray  # f64 [L]
    link_latency: np.ndarray  # f64 [L]
    num_devices: int


class TaskGraphBuilder:
    """Accumulates tasks/deps/edges, then freezes to CSR arrays."""

    def __init__(self, num_devices: int, machine: MachineModel):
        self.machine = machine
        self.D = num_devices
        self._compute: List[float] = []
        self._device: List[int] = []
        self._deps: List[List[int]] = []
        self._edges: List[Tuple[int, int, float, List[int]]] = []
        from .network import NetworkedMachineModel

        self._net: "NetworkedMachineModel | None" = None
        if isinstance(machine, NetworkedMachineModel):
            # arbitrary topology: one contention link per directed edge
            self._net = machine
            links, self._link_index = machine.link_table()
            self._link_bw = [
                machine.link_bw * machine.conn[u, v] for u, v in links
            ]
            self._link_lat = [machine.link_lat] * len(links)
        else:
            # bidirectional ring: 2*d = d -> (d+1)%D, 2*d+1 = d -> (d-1)%D
            if isinstance(machine, TpuPodModel):
                bw, lat = machine.ici_bw, machine.ici_lat
            else:
                bw, lat = getattr(machine, "intra_bw", 100e9), getattr(
                    machine, "intra_lat", 1e-6
                )
            self._link_bw = [bw] * (2 * num_devices)
            self._link_lat = [lat] * (2 * num_devices)

    def add_task(self, compute: float, device: int,
                 deps: Sequence[int] = ()) -> int:
        tid = len(self._compute)
        self._compute.append(float(compute))
        self._device.append(int(device))
        self._deps.append(list(deps))
        return tid

    def add_dep(self, task: int, dep: int):
        self._deps[task].append(dep)

    def route(self, src: int, dst: int) -> List[int]:
        """Link ids along src->dst: routed over the topology's shortest
        path when a NetworkedMachineModel is attached (reference
        route_transfer, simulator.cc:1488-1689), else the ring."""
        if src == dst:
            return []
        if self._net is not None:
            return self._net.route_links(src, dst, self._link_index)
        return self.ring_route(src, dst)

    def ring_route(self, src: int, dst: int) -> List[int]:
        """Store-and-forward over consecutive ring links, shorter way."""
        if src == dst:
            return []
        D = self.D
        fwd = (dst - src) % D
        bwd = (src - dst) % D
        links = []
        cur = src
        if fwd <= bwd:
            for _ in range(fwd):
                links.append(2 * cur)
                cur = (cur + 1) % D
        else:
            for _ in range(bwd):
                links.append(2 * cur + 1)
                cur = (cur - 1) % D
        return links

    def add_edge(self, src_task: int, dst_task: int, nbytes: float,
                 src_dev: int, dst_dev: int):
        self._edges.append(
            (src_task, dst_task, float(nbytes),
             self.route(src_dev, dst_dev))
        )

    def expand_allreduce(
        self, group: Sequence[int], nbytes: float,
        dep_task_of: Dict[int, int],
    ) -> Dict[int, int]:
        """Ring all-reduce expansion (reference expand_allreduce,
        simulator.cc:1690-1800): 2(n-1) phases of neighbor transfers of
        nbytes/n.  dep_task_of: device -> task the collective waits on.
        Returns device -> final phase task."""
        n = len(group)
        if n <= 1:
            return dict(dep_task_of)
        chunk = nbytes / n
        prev = dict(dep_task_of)
        for _ in range(2 * (n - 1)):
            cur: Dict[int, int] = {}
            for i, d in enumerate(group):
                t = self.add_task(0.0, d, [prev[d]])
                left = group[(i - 1) % n]
                self.add_edge(prev[left], t, chunk, left, d)
                cur[d] = t
            prev = cur
        return prev

    def expand_allgather(
        self, group: Sequence[int], nbytes: float,
        dep_task_of: Dict[int, int],
    ) -> Dict[int, int]:
        """Ring all-gather: n-1 phases of nbytes/n neighbor transfers."""
        n = len(group)
        if n <= 1:
            return dict(dep_task_of)
        chunk = nbytes / n
        prev = dict(dep_task_of)
        for _ in range(n - 1):
            cur: Dict[int, int] = {}
            for i, d in enumerate(group):
                t = self.add_task(0.0, d, [prev[d]])
                left = group[(i - 1) % n]
                self.add_edge(prev[left], t, chunk, left, d)
                cur[d] = t
            prev = cur
        return prev

    def finalize(self) -> TaskGraphArrays:
        T = len(self._compute)
        dep_offsets = np.zeros(T + 1, np.int64)
        for t in range(T):
            dep_offsets[t + 1] = dep_offsets[t] + len(self._deps[t])
        dep_ids = np.asarray(
            [d for deps in self._deps for d in deps], np.int32
        )
        E = len(self._edges)
        route_offsets = np.zeros(E + 1, np.int64)
        for e in range(E):
            route_offsets[e + 1] = route_offsets[e] + len(self._edges[e][3])
        return TaskGraphArrays(
            compute_time=np.asarray(self._compute, np.float64),
            device_of=np.asarray(self._device, np.int32),
            dep_offsets=dep_offsets,
            dep_ids=dep_ids,
            edge_src=np.asarray([e[0] for e in self._edges], np.int32),
            edge_dst=np.asarray([e[1] for e in self._edges], np.int32),
            edge_bytes=np.asarray([e[2] for e in self._edges], np.float64),
            route_offsets=route_offsets,
            route_links=np.asarray(
                [l for e in self._edges for l in e[3]], np.int32
            ),
            link_bandwidth=np.asarray(self._link_bw, np.float64),
            link_latency=np.asarray(self._link_lat, np.float64),
            num_devices=self.D,
        )


# ---------------------------------------------------------------------------
# event loops
# ---------------------------------------------------------------------------

def simulate_native(tg: TaskGraphArrays) -> Optional[Tuple[float, np.ndarray]]:
    """Run the C++ event loop; None when the native lib is unavailable."""
    from ..native import get_lib

    lib = get_lib()
    if lib is None:
        return None
    T = len(tg.compute_time)
    makespan = ctypes.c_double()
    busy = np.zeros(tg.num_devices, np.float64)

    def p(arr, ctype):
        if len(arr) == 0:
            return None
        return arr.ctypes.data_as(ctypes.POINTER(ctype))

    rc = lib.ffsim_simulate(
        ctypes.c_int64(T),
        p(tg.compute_time, ctypes.c_double),
        p(tg.device_of, ctypes.c_int32),
        p(tg.dep_offsets, ctypes.c_int64),
        p(tg.dep_ids, ctypes.c_int32),
        ctypes.c_int64(len(tg.edge_src)),
        p(tg.edge_src, ctypes.c_int32),
        p(tg.edge_dst, ctypes.c_int32),
        p(tg.edge_bytes, ctypes.c_double),
        p(tg.route_offsets, ctypes.c_int64),
        p(tg.route_links, ctypes.c_int32),
        ctypes.c_int64(len(tg.link_bandwidth)),
        p(tg.link_bandwidth, ctypes.c_double),
        p(tg.link_latency, ctypes.c_double),
        ctypes.c_int32(tg.num_devices),
        ctypes.byref(makespan),
        busy.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        None,
    )
    if rc != 0:
        raise RuntimeError(f"ffsim_simulate failed with code {rc}")
    return makespan.value, busy


def simulate_python(tg: TaskGraphArrays) -> Tuple[float, np.ndarray]:
    """Pure-Python event loop, semantically identical to the native one
    (same (time, seq) tie-breaking; tested for exact agreement)."""
    T = len(tg.compute_time)
    remaining = (tg.dep_offsets[1:] - tg.dep_offsets[:-1]).astype(np.int64)
    dep_out: List[List[int]] = [[] for _ in range(T)]
    for t in range(T):
        for i in range(tg.dep_offsets[t], tg.dep_offsets[t + 1]):
            dep_out[tg.dep_ids[i]].append(t)
    edge_out: List[List[int]] = [[] for _ in range(T)]
    for e in range(len(tg.edge_src)):
        edge_out[tg.edge_src[e]].append(e)
        remaining[tg.edge_dst[e]] += 1

    ready_time = np.zeros(T, np.float64)
    link_avail = np.zeros(len(tg.link_bandwidth), np.float64)
    dev_busy = np.zeros(tg.num_devices, np.float64)
    dev_idle = [True] * tg.num_devices
    dev_queue: List[List[Tuple[float, int]]] = [
        [] for _ in range(tg.num_devices)
    ]
    events: List[Tuple[float, int, int, int]] = []  # (time, seq, kind, task)
    seq = 0
    completed = 0
    makespan = 0.0

    for t in range(T):
        if remaining[t] == 0:
            heapq.heappush(events, (0.0, seq, 0, t))
            seq += 1

    def try_start(dev: int, now: float):
        nonlocal seq
        while dev_idle[dev] and dev_queue[dev]:
            ready, task = heapq.heappop(dev_queue[dev])
            start = max(now, ready)
            fin = start + tg.compute_time[task]
            dev_idle[dev] = False
            dev_busy[dev] += tg.compute_time[task]
            heapq.heappush(events, (fin, seq, 1, task))
            seq += 1

    def satisfy(t: int, at: float):
        nonlocal seq
        if at > ready_time[t]:
            ready_time[t] = at
        remaining[t] -= 1
        if remaining[t] == 0:
            heapq.heappush(events, (ready_time[t], seq, 0, t))
            seq += 1

    while events:
        now, _, kind, task = heapq.heappop(events)
        dev = tg.device_of[task]
        if kind == 0:
            heapq.heappush(dev_queue[dev], (now, task))
            try_start(dev, now)
        else:
            completed += 1
            makespan = max(makespan, now)
            for d in dep_out[task]:
                satisfy(d, now)
            for e in edge_out[task]:
                t_cur = now
                for i in range(tg.route_offsets[e], tg.route_offsets[e + 1]):
                    l = tg.route_links[i]
                    begin = max(t_cur, link_avail[l])
                    bw = tg.link_bandwidth[l]
                    done = begin + tg.link_latency[l] + (
                        tg.edge_bytes[e] / bw if bw > 0 else 0.0
                    )
                    link_avail[l] = done
                    t_cur = done
                satisfy(tg.edge_dst[e], t_cur)
            dev_idle[dev] = True
            try_start(dev, now)

    if completed != T:
        raise RuntimeError("task graph has a cycle")
    return makespan, dev_busy


# ---------------------------------------------------------------------------
# PCG -> task graph
# ---------------------------------------------------------------------------

class TaskGraphSimulator:
    """Expand a strategy-applied PCG into an SPMD per-device task graph
    (tasks per (op, device); collectives as ring phases) and run the
    event simulation.  Complements the analytic Simulator: this one sees
    pipelining, device imbalance, and link contention."""

    def __init__(self, machine: MachineModel,
                 cost_model: Optional[OpCostModel] = None,
                 force_python: bool = False,
                 ring_attention: bool = True):
        self.machine = machine
        self.cost_model = cost_model or OpCostModel(machine)
        self.force_python = force_python
        # model seq-sharded attention's KV rotation as ring phases
        # (ablation toggle for tests/what-if costing)
        self.ring_attention = ring_attention

    def build(self, graph: Graph, mesh_axes: Dict[str, int],
              training: bool = True) -> TaskGraphArrays:
        D = 1
        for v in mesh_axes.values():
            D *= v
        b = TaskGraphBuilder(D, self.machine)
        # tensor guid -> {device: producing task}
        producer: Dict[int, Dict[int, int]] = {}
        all_devices = list(range(D))
        for op in graph.topo_order():
            if op.op_type == OperatorType.INPUT:
                tasks = {d: b.add_task(0.0, d) for d in all_devices}
                for t in op.outputs:
                    producer[t.guid] = tasks
                continue
            cm = self.cost_model.cost(op)
            compute = cm.forward_time + (cm.backward_time if training else 0.0)
            if op.is_parallel_op():
                compute = 0.0
            tasks = {}
            for d in all_devices:
                deps = [
                    producer[t.guid][d] for t in op.inputs
                    if t.guid in producer
                ]
                tasks[d] = b.add_task(compute, d, deps)
            if op.is_parallel_op():
                tasks = self._expand_parallel_op(b, op, tasks, all_devices)
            else:
                out_rep = (
                    op.outputs[0].shape.replica_degree if op.outputs else 1
                )
                in_rep = max(
                    (t.shape.replica_degree for t in op.inputs), default=1
                )
                if out_rep > in_rep:
                    # contraction-dim partial sums -> psum (ring allreduce)
                    k = out_rep // max(1, in_rep)
                    size = op.outputs[0].shape.shard_bytes()
                    tasks = self._grouped_collective(
                        b, "allreduce", k, size, tasks, all_devices
                    )
                if (
                    self.ring_attention
                    and op.op_type == OperatorType.MULTIHEAD_ATTENTION
                    and len(op.inputs) >= 3
                ):
                    # ring attention: seq-sharded KV rotates once around
                    # the sp group per forward (ppermute per block step),
                    # ~2x more for backward re-rotation + dK/dV — the
                    # bandwidth equivalent of 3 allgathers of the local
                    # KV (replaces the analytic flat term, unity.py
                    # _sp_candidates)
                    dd = [
                        d for d in op.inputs[0].shape.dims
                        if not d.is_replica_dim
                    ]
                    if len(dd) >= 2 and dd[1].degree > 1:
                        sp = dd[1].degree
                        kv = (
                            op.inputs[1].shape.shard_bytes()
                            + op.inputs[2].shape.shard_bytes()
                        )
                        tasks = self._grouped_collective(
                            b, "allgather", sp,
                            3.0 * kv * sp if training else kv * sp,
                            tasks, all_devices,
                        )
            for t in op.outputs:
                producer[t.guid] = tasks
        if training:
            # gradient sync: ring allreduce per replicated weight, hanging
            # off that op's tasks (reference optimizer ncclAllReduce)
            for op in graph.ops:
                if op.op_type == OperatorType.INPUT or op.is_parallel_op():
                    continue
                base = (
                    producer[op.outputs[0].guid] if op.outputs else None
                )
                if base is None:
                    continue
                for w in op.weights:
                    rep = w.shape.replica_degree
                    if rep > 1 and w.create_gradients:
                        self._grouped_collective(
                            b, "allreduce", rep, w.shape.shard_bytes(),
                            base, all_devices,
                        )
        return b.finalize()

    def _grouped_collective(self, b: TaskGraphBuilder, kind: str, k: int,
                            size: float, dep_tasks: Dict[int, int],
                            all_devices: List[int]) -> Dict[int, int]:
        """Run a collective over contiguous groups of size k."""
        D = len(all_devices)
        k = min(k, D)
        out: Dict[int, int] = {}
        for g in range(max(1, D // k)):
            group = all_devices[g * k:(g + 1) * k]
            if not group:
                continue
            deps = {d: dep_tasks[d] for d in group}
            fn = (b.expand_allreduce if kind == "allreduce"
                  else b.expand_allgather)
            res = fn(group, size, deps)
            out.update(res)
        for d in all_devices:
            out.setdefault(d, dep_tasks[d])
        return out

    def _expand_parallel_op(self, b: TaskGraphBuilder, op,
                            tasks: Dict[int, int],
                            all_devices: List[int]) -> Dict[int, int]:
        t = op.op_type
        out_shape = op.outputs[0].shape
        if t == OperatorType.COMBINE:
            return self._grouped_collective(
                b, "allgather", op.params.degree,
                op.inputs[0].shape.shard_bytes() * op.params.degree,
                tasks, all_devices,
            )
        if t == OperatorType.REDUCTION:
            return self._grouped_collective(
                b, "allreduce", op.params.degree,
                out_shape.shard_bytes(), tasks, all_devices,
            )
        if t == OperatorType.REPLICATE:
            return self._grouped_collective(
                b, "allgather", op.params.degree,
                out_shape.shard_bytes(), tasks, all_devices,
            )
        if t == OperatorType.ALLTOALL:
            # each device exchanges shard/n with every peer: model as one
            # ring allgather of the shard (bandwidth-equivalent on a ring)
            return self._grouped_collective(
                b, "allgather", op.params.degree,
                out_shape.shard_bytes(), tasks, all_devices,
            )
        # Repartition of on-device data: slicing, no transfer
        return tasks

    def simulate(self, graph: Graph, mesh_axes: Dict[str, int],
                 training: bool = True) -> SimResult:
        tg = self.build(graph, mesh_axes, training)
        res = None if self.force_python else simulate_native(tg)
        used_native = res is not None
        if res is None:
            res = simulate_python(tg)
        makespan, busy = res
        compute = float(busy.max()) if len(busy) else 0.0
        return SimResult(
            total_time=makespan,
            compute_time=compute,
            comm_time=makespan - compute,
            sync_time=0.0,
            per_device_memory=0,
            breakdown={"native": float(used_native)},
        )
