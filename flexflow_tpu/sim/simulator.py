"""Simulator: predicts step time + memory for a strategy-applied PCG.

Reference: src/runtime/simulator.cc — task-graph event simulation
(simulate_runtime :822-1250), per-op cost measurement with a
(params, view) cache (:537-578, model.cu:38-75 cudaEvent timing), comm
cost estimators (estimate_xfer_cost :622-767, sync cost :786-813), and
the fork's topology-routed variant (:1251-1800).

TPU-native redesign: our execution model is SPMD — every device runs the
same jitted program — so the per-device timeline is the SAME sequence of
(sharded) compute ops and collectives.  The simulator therefore costs:

  step = sum_ops max-shard compute (fwd [+ bwd])
       + sum resharding collectives (the parallel ops)
       + partial-sum reductions (contraction-dim sharding)
       + gradient all-reduce over each weight's replica axes
       - a compute/comm overlap credit (XLA latency hiding)

Compute costs come from an analytic roofline (flops/peak, bytes/HBM-bw)
calibrated by optional real measurements (measure_fn timing jitted ops
on the actual chip — the analogue of inner_measure_operator_cost), with
the same (node_key, view)->cost cache as the reference.  Memory is
accounted per device: weight + optimizer-slot + gradient shards plus
peak live activations — feeding the memory-aware search
(memory_optimization.h:45-70 equivalent).
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..fftype import OperatorType
from ..ops.op import Op
from ..pcg.graph import Graph
from .machine_model import MachineModel, TpuPodModel
from ..topology.comm import CommCost, ZERO_COST, ring_bytes


@dataclasses.dataclass
class CostMetrics:
    """Per-op cost record (reference CostMetrics simulator.h)."""

    forward_time: float = 0.0
    backward_time: float = 0.0
    sync_time: float = 0.0
    inputs_memory: int = 0
    outputs_memory: int = 0
    weights_memory: int = 0


class SimResult:
    """Simulation outcome.

    `per_device_memory` is LAZY: searches only consume it when a memory
    budget is set, but the liveness/remat scan behind it used to be paid
    on every evaluation.  Constructing with `memory_fn` defers the scan
    to first access (the computed value is then cached); constructing
    with an int keeps the eager behavior.
    """

    def __init__(
        self,
        total_time: float,
        compute_time: float,
        comm_time: float,
        sync_time: float,
        per_device_memory: Optional[int] = None,
        breakdown: Optional[Dict[str, float]] = None,
        memory_fn: Optional[Callable[[], int]] = None,
    ):
        self.total_time = total_time
        self.compute_time = compute_time
        self.comm_time = comm_time
        self.sync_time = sync_time
        self.breakdown = breakdown if breakdown is not None else {}
        self._memory = per_device_memory
        self._memory_fn = memory_fn
        # per-tier comm split (topology subsystem): simulate_ops fills
        # it from the OpTerms ici_/dcn_ fields; zero on flat meshes
        self.comm_tiers: Dict[str, float] = {
            "ici_time": 0.0, "dcn_time": 0.0,
            "ici_bytes": 0.0, "dcn_bytes": 0.0,
        }
        # searched-remat telemetry (mem/activation_bytes,
        # compute/recompute_s): saved-activation bytes under the costed
        # plan and the recompute seconds the plan charges; simulate_ops
        # fills them (recompute_s is 0 for dense / legacy-bool runs)
        self.activation_bytes: float = 0.0
        self.recompute_s: float = 0.0

    @property
    def per_device_memory(self) -> int:
        if self._memory is None:
            self._memory = int(self._memory_fn()) if self._memory_fn else 0
            self._memory_fn = None  # release the captured op sequence
        return self._memory


@dataclasses.dataclass(frozen=True)
class OpTerms:
    """One op's additive contribution to a simulation — the delta-sim
    decomposition (reference delta simulation in simulate_runtime: after
    an MCMC substitution only affected tasks re-simulate).  Every field
    depends ONLY on the cache key — node_key (op type, params,
    ShardConfig, input parallel shapes), mesh signature, training — so
    terms are cached across candidate strategies and whole-graph totals
    are re-aggregated from cache."""

    compute: float = 0.0      # analytic fwd(+bwd) time, pre compute_scale
    xfer: float = 0.0         # parallel-op resharding collective
    partial: float = 0.0      # fwd partial-sum all-reduce (undoubled)
    grad_sync: float = 0.0    # gradient sync over weight replica axes
    #                           (all-reduce; reduce-scatter at stage >= 1)
    opt_numel: float = 0.0    # master-precision elements the update touches
    #                           (already /rep under the sharded update)
    opt_xfer: float = 0.0     # post-update weight all-gather (stage 1/2)
    gather_xfer: float = 0.0  # ZeRO-3 per-layer weight all-gathers
    #                           (fwd + bwd re-gather; prefetch-credited)
    ici_xfer: float = 0.0     # per-tier (uncredited) split of ALL the
    dcn_xfer: float = 0.0     # op's comm seconds: intra-slice ICI vs
    #                           inter-slice DCN (flat mesh = all ICI);
    #                           grad/opt legs fold in only when training
    ici_bytes: float = 0.0    # per-device ring bytes over each tier —
    dcn_bytes: float = 0.0    # the comm/{ici,dcn}_bytes telemetry split
    mem_weights: int = 0      # per-device weight shard bytes (compute copy)
    mem_master: int = 0       # per-device master-resident weight bytes
    #                           (== mem_weights below stage 3; /group at 3)
    mem_grad: int = 0         # per-device gradient buffer bytes
    #                           (== mem_weights below stage 2; /group at 2+)
    mem_gather: int = 0       # stage-3 gathered weight copy bytes for THIS
    #                           op (double-buffer window: 2x the max rides
    #                           the memory total)
    mem_opt: int = 0          # per-device bytes ONE optimizer slot costs
    #                           (== mem_weights replicated; grad weights
    #                           /rep under the sharded update)
    mem_residual: int = 0     # backward-residual activation bytes
    mem_transient: int = 0    # fused transient workspace bytes (max-reduced)
    mem_activation: int = 0   # per-device saved-activation bytes when this
    #                           op's remat segment is OFF (== the dense
    #                           residual term; a remat'd segment drops its
    #                           internals from the step-long residency)
    recompute: float = 0.0    # backward re-execution seconds when the
    #                           op's segment is remat'd: the forward pass
    #                           runs again inside backward (compute + fwd
    #                           collectives; at ZeRO-3 the re-gather loses
    #                           its double-buffered prefetch credit)


_KERNEL_OVERHEAD = 2e-6  # per-op dispatch/fusion overhead (XLA fuses, small)

#: semantic version of the analytic cost model + simulator formulas.
#: Part of the strategy store's simulator-version key component
#: (store/key.py): bump it whenever cost semantics change — OpTerms
#: decomposition, comm estimators, overlap crediting, memory accounting
#: — so strategies searched under the old model stop hitting and
#: re-search under the new one instead of replaying stale rankings.
#: (The learned cost model, arXiv:2008.01040, will ride this same
#: constant: model retrain => version bump => fleet-wide invalidation.)
#: v2: the ZeRO ladder — OpTerms grew mem_master/mem_grad/mem_gather/
#: gather_xfer and the memory/update accounting became zero_stage-aware,
#: so stage-blind v1 rankings must re-search.  A tier-1 guard test pins
#: the OpTerms field set to this number (tests/test_zero_ladder.py):
#: changing the decomposition without bumping here fails CI.
#: v3: the multi-slice topology subsystem (docs/TOPOLOGY.md) — OpTerms
#: grew the ici_xfer/dcn_xfer/ici_bytes/dcn_bytes per-tier split, comm
#: estimators became placement-aware (a collective crossing the slice
#: boundary costs the hierarchical / DCN form), and the sharded-update
#: group shrinks to the intra-slice remainder under a cross-slice
#: placement — slice-blind v2 rankings must re-search.
#: v4: searched rematerialization (docs/PERF.md "Searched
#: rematerialization") — OpTerms grew mem_activation/recompute, remat
#: became a per-segment plan both searches cost under --memory-search,
#: and DCN grad-sync latency is bucket-amortized (--dcn-bucket-mb) on
#: hierarchy machines — remat-blind v3 rankings must re-search.
COST_MODEL_VERSION = 4

#: per-candidate cap on the segments the searches treat as independent
#: remat decisions; plans may still name higher indices (ignored past
#: the graph's actual segment count)
MAX_REMAT_SEGMENTS = 24

#: default DCN grad-sync coalescing bucket (bytes): real runtimes bucket
#: grad all-reduces (~25MB), so the per-leaf DCN latency term amortizes
#: over the bucket a leaf rides in instead of being paid per leaf
DEFAULT_DCN_BUCKET_BYTES = 25 * 2**20

#: overlap credit for the ZeRO-3 per-layer weight all-gathers: the
#: executor double-buffers (layer k+1's gather issues before layer k's
#: compute), but the gathers sit on the layer-boundary critical path, so
#: they hide WORSE than generic resharding collectives.  This replaces
#: the generic overlap_fraction credit for what used to be opt_xfer:
#: 2 gathers/step at (1 - 0.5) exposed always costs more than stage 1's
#: single post-update gather at the generic credit, which is what keeps
#: unconstrained searches on stages <= 1.
Z3_PREFETCH_OVERLAP = 0.5

# backward/forward cost ratio per op class (replaces the old flat 2x:
# conv/matmul backward really is two same-size contractions, but an
# embedding backward is one gradient scatter with no input grad, and
# elementwise/pool/softmax backward is a single pass like forward)
_BWD_RATIO_DEFAULT = 2.0
_BWD_RATIO = {
    OperatorType.CONV2D: 2.0,
    OperatorType.LINEAR: 2.0,
    OperatorType.BATCH_MATMUL: 2.0,
    # flash backward recomputes scores in both the dq and dkv kernels
    OperatorType.MULTIHEAD_ATTENTION: 2.5,
    OperatorType.EMBEDDING: 1.0,
    OperatorType.BATCH_NORM: 1.5,
    OperatorType.LAYER_NORM: 1.5,
    OperatorType.POOL2D: 1.0,
    OperatorType.SOFTMAX: 1.0,
    OperatorType.DROPOUT: 1.0,
    OperatorType.CAST: 1.0,
    OperatorType.ELEMENT_UNARY: 1.0,
    OperatorType.ELEMENT_BINARY: 1.0,
    OperatorType.CONCAT: 1.0,
    OperatorType.SPLIT: 1.0,
    OperatorType.FLAT: 0.5,
    OperatorType.RESHAPE: 0.5,
    OperatorType.TRANSPOSE: 1.0,
}


def backward_ratio(op: Op) -> float:
    return _BWD_RATIO.get(op.op_type, _BWD_RATIO_DEFAULT)


class OpCostModel:
    """(node_key)->cost cache with analytic roofline + measured override.

    measure_fn, when provided, times the real jitted op on hardware and
    its result replaces the analytic estimate (reference
    inner_measure_operator_cost, model.cu:38-75, with the same
    (params, view)->cost cache, simulator.cc:550-560).  Measured results
    additionally persist to `cache_path` as JSON so later searches —
    even in fresh processes — reuse chip timings without re-profiling.
    """

    #: ops cheaper than this many FLOPs keep the analytic estimate —
    #: their cost is dispatch-dominated and profiling each candidate
    #: view would cost far more than the information is worth
    MEASURE_MIN_FLOPS = 5e6

    def __init__(
        self,
        machine: MachineModel,
        measure_fn: Optional[Callable[[Op], Optional[float]]] = None,
        compute_dtype_bytes: int = 2,  # bf16
        cache_path: Optional[str] = None,
        device_key: str = "",
    ):
        self.machine = machine
        self.measure_fn = measure_fn
        self.cache: Dict[Tuple, CostMetrics] = {}
        self.dtype_bytes = compute_dtype_bytes
        self.cache_path = cache_path
        # measured times are chip-specific: namespace persisted keys by
        # the device kind so a cache calibrated on one backend is never
        # replayed on another
        self.device_key = device_key
        self.measured_hits = 0  # cost() calls answered by a measurement
        self.cost_hits = 0      # cost() calls answered by the node_key cache
        self._persistent: Dict[str, float] = {}
        self._dirty = False
        if cache_path:
            self.load_persistent(cache_path)

    # -- measured-cost persistence --------------------------------------
    def load_persistent(self, path: str):
        import json
        import os

        if os.path.exists(path):
            try:
                with open(path) as f:
                    data = json.load(f)
                self._persistent.update(
                    {k: float(v) for k, v in data.items()}
                )
            except (OSError, ValueError, TypeError, AttributeError):
                pass  # absent, torn, or valid-JSON-wrong-shape

    def save_persistent(self, path: Optional[str] = None):
        """Crash-safe, concurrency-safe persistence of the measured-cost
        cache.  Called unconditionally at the end of every Unity/MCMC
        search (unity.py/mcmc.py), so a mid-write kill must never
        corrupt the shared file: the write goes to a process-unique tmp
        (mkstemp — a fixed `.tmp` name would let two searches clobber
        each other's staging) and lands via one atomic os.replace.
        Merge-on-save: entries measured by OTHER concurrent searches
        since our load are re-read and kept — last writer no longer
        erases them; our own measurements win ties."""
        import json
        import os
        import tempfile

        path = path or self.cache_path
        if not path or not self._dirty:
            return
        path = os.path.abspath(path)
        dirname = os.path.dirname(path)
        os.makedirs(dirname, exist_ok=True)
        merged: Dict[str, float] = {}
        try:
            with open(path) as f:
                merged = {k: float(v) for k, v in json.load(f).items()}
        except (OSError, ValueError, TypeError, AttributeError):
            # absent, torn, or valid-JSON-wrong-shape (a list, null
            # values) — our entries still publish whole either way
            merged = {}
        merged.update(self._persistent)
        fd, tmp = tempfile.mkstemp(
            dir=dirname, prefix=os.path.basename(path) + ".tmp-"
        )
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(merged, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._persistent = merged
        self._dirty = False

    def cost(self, op: Op) -> CostMetrics:
        key = op.node_key()
        hit = self.cache.get(key)
        if hit is not None:
            self.cost_hits += 1
            return hit
        cm = self._analytic(op)
        measured = self._measured(op, key)
        if measured is not None:
            self.measured_hits += 1
            cm.forward_time = measured
            cm.backward_time = backward_ratio(op) * measured
        self.cache[key] = cm
        return cm

    #: bump when the measurement harness changes semantics — v2: the
    #: r01/r02 chained-scan timing was DCE'd by XLA (barrier split) and
    #: persisted near-zero garbage that must never be replayed
    MEASURE_CACHE_VERSION = 2

    def _measured(self, op: Op, key: Tuple) -> Optional[float]:
        if self.measure_fn is None or op.is_parallel_op():
            return None
        if op.flops() < self.MEASURE_MIN_FLOPS:
            return None
        skey = f"v{self.MEASURE_CACHE_VERSION}|{self.device_key}|{key!r}"
        if skey in self._persistent:
            return self._persistent[skey]
        measured = self.measure_fn(op)
        if measured is not None:
            self._persistent[skey] = measured
            self._dirty = True
        return measured

    def _shard_fraction(self, op: Op) -> float:
        """Fraction of the op's total FLOPs done by one device."""
        deg = 1
        for t in op.outputs:
            deg = max(deg, int(np.prod([d.degree for d in t.shape.dims
                                        if not d.is_replica_dim])))
        # contraction-dim sharding also divides flops
        red = max(
            (t.shape.replica_degree for t in op.outputs), default=1
        )
        return 1.0 / max(1, deg * red)

    def _analytic(self, op: Op) -> CostMetrics:
        dev = self.machine.device()
        flops = op.flops() * self._shard_fraction(op)
        in_bytes = sum(t.shape.shard_bytes() for t in op.inputs)
        out_bytes = sum(t.shape.shard_bytes() for t in op.outputs)
        w_bytes = sum(w.shape.shard_bytes() for w in op.weights)
        bytes_moved = in_bytes + out_bytes + w_bytes
        t_compute = flops / dev.peak_flops
        t_mem = bytes_moved / dev.hbm_bandwidth
        fwd = max(t_compute, t_mem) + _KERNEL_OVERHEAD
        return CostMetrics(
            forward_time=fwd,
            backward_time=(
                backward_ratio(op) * fwd if op.weights or op.inputs else 0.0
            ),
            inputs_memory=in_bytes,
            outputs_memory=out_bytes,
            weights_memory=w_bytes,
        )


def make_cost_model(cfg, machine: MachineModel) -> OpCostModel:
    """Build the search's cost model from FFConfig: measured calibration
    (profiler.make_measure_fn) when cfg.should_calibrate(), with the
    measured cache persisted across runs (reference keeps the same
    (params, view)->cost cache for the whole search,
    simulator.cc:550-560)."""
    measure_fn = None
    cache_path = cfg.op_cost_cache_file
    device_key = ""
    if cfg.should_calibrate():
        from ..profiler import make_measure_fn

        measure_fn = make_measure_fn()
        try:
            import jax

            device_key = jax.devices()[0].device_kind
        except Exception:
            device_key = "unknown"
        if cache_path is None:
            import os

            cache_path = os.path.join(
                os.path.expanduser("~"), ".cache", "flexflow_tpu",
                "op_costs.json",
            )
    return OpCostModel(machine, measure_fn=measure_fn, cache_path=cache_path,
                       device_key=device_key)


#: ops whose segments can never rematerialize (side effects / host
#: state / routing state) — the shared impurity rule of the searched
#: remat dimension (the executor's _build_remat_plan additionally
#: excludes pipeline blocks and non-trainable-state ops it alone can
#: see; an over-approximate simulator plan only mis-prices, never
#: mis-executes, those segments)
REMAT_IMPURE_TYPES = frozenset({
    OperatorType.INPUT, OperatorType.CACHE, OperatorType.GROUP_BY,
    OperatorType.AGGREGATE, OperatorType.AGGREGATE_SPEC,
})


def remat_segments(ops: Sequence[Op]) -> List[Tuple[List[Op], bool]]:
    """[(segment, pure)] over a topo-ordered op sequence — the remat
    decision units a strategy's plan indexes: plan entry i names the
    i-th single-tensor-boundary segment.  Impure segments (pure=False)
    always run inline regardless of the plan."""
    from ..pcg.segments import split_segments_ops

    segments, _ = split_segments_ops(list(ops))
    return [
        (seg, all(op.op_type not in REMAT_IMPURE_TYPES for op in seg))
        for seg in segments
    ]


def _axis_sizes_of_view(pt, mesh_axes: Dict[str, int]) -> Dict[str, int]:
    out = {}
    if pt.machine_view is None:
        return out
    for axes in pt.machine_view.axes:
        for ax in axes:
            out[ax] = mesh_axes[ax]
    return out


class Simulator:
    """Strategy cost evaluation (replaces simulate_runtime's event loop
    for the SPMD execution model; see module docstring)."""

    def __init__(
        self,
        machine: MachineModel,
        cost_model: Optional[OpCostModel] = None,
        overlap_fraction: float = 0.3,
        optimizer_slots: int = 2,  # adam m+v
        sync_overlap_fraction: Optional[float] = None,
        parameter_sync: str = "allreduce",
        remat: bool = False,
        compute_scale: float = 1.0,
        weight_update_sharding: bool = False,
        wus_axis: str = "data",
        zero_stage: Optional[int] = None,
        placement: Optional[str] = None,
        dcn_bucket_bytes: float = DEFAULT_DCN_BUCKET_BYTES,
    ):
        self.machine = machine
        self.cost_model = cost_model or OpCostModel(machine)
        self.overlap_fraction = overlap_fraction
        # fitted backend calibration (sim/calibrate.py): scales the
        # analytic compute term to measured reality; 1.0 = roofline
        self.compute_scale = compute_scale
        self.optimizer_slots = optimizer_slots
        # executor --remat: checkpointed segments change peak memory
        self.remat = remat
        # gradient-sync overlap with remaining backward compute
        # (reference --search-overlap-backward-update, config.h:130):
        # None -> same credit as other comm
        self.sync_overlap_fraction = (
            sync_overlap_fraction if sync_overlap_fraction is not None
            else overlap_fraction
        )
        # "allreduce" (ring, NCCL-equivalent) | "ps" (parameter server:
        # flat 2*size/BW, reference default_estimate_sync_cost
        # simulator.cc:786-813 + ParameterSyncType::PS optimizer.h:47)
        self.parameter_sync = parameter_sync
        # ZeRO ladder stage (docs/PERF.md "The ZeRO ladder").  This is
        # the simulator's DEFAULT stage; every stage-sensitive method
        # also takes a per-call zero_stage override (keyed into the
        # OpTerms cache) so one simulator can cost all four rungs of
        # the ladder for the searches:
        #   1: grad reduce-scatter, update numel/rep, post-update
        #      weight all-gather, slot memory /rep;
        #   2: + gradient-resident bytes /rep;
        #   3: + master-weight-resident bytes /rep, per-layer weight
        #      all-gathers (fwd + bwd) instead of the post-update one.
        # weight_update_sharding=True is the deprecated alias for
        # stage 1; the bool attribute mirrors `zero_stage >= 1`.
        self.zero_stage = (
            int(zero_stage) if zero_stage is not None
            else (1 if weight_update_sharding else 0)
        )
        self.weight_update_sharding = self.zero_stage >= 1
        # the ONE mesh axis the executor shards the update over
        # (FFConfig.wus_axis); wus_group() resolves each weight's
        # actual sharding group from it
        self.wus_axis = wus_axis
        # multi-slice hierarchy (topology/hierarchy.py): placement is
        # the DEFAULT cross-slice mesh axis; every placement-sensitive
        # method also takes a per-call override (keyed into the OpTerms
        # cache) so one simulator costs every placement for the
        # searches.  Single-slice machines ignore it entirely — the
        # flat costs are bit-identical to the pre-topology model.
        self.placement = placement
        self._slices = max(1, int(getattr(machine, "slices", 1) or 1))
        self._hier = (
            self._slices > 1 and hasattr(machine, "collective_cost")
        )
        # DCN grad-sync bucketing (ROADMAP multi-slice follow-up 1):
        # runtimes coalesce grad all-reduces into ~bucket-sized chunks,
        # so a leaf's DCN latency term is amortized by the fraction of
        # a bucket its DCN-leg bytes fill.  0/None disables (pay the
        # full per-leaf latency, the pre-v4 behavior).  Flat machines
        # never consult it — there is no DCN leg to bucket.
        self.dcn_bucket_bytes = dcn_bucket_bytes
        # (node_key, mesh signature, training) -> OpTerms: per-op
        # contribution terms for the delta/memoized evaluator (the
        # machine and sync mode are fixed per Simulator)
        self._term_cache: Dict[Tuple, OpTerms] = {}
        self.term_hits = 0
        self.term_misses = 0
        # (params, input shape) -> reconstructed member sub-ops for
        # FUSED_PARALLEL costing (rebuilt on every call before)
        self._fused_members: Dict[Tuple, List[Op]] = {}

    # -- comm costs ------------------------------------------------------
    def _collective(self, kind: str, size: float, group_len: int,
                    cross: bool = False, grad_bucket: bool = False):
        """One collective as a topology.CommCost: the flat single-tier
        estimate on ordinary machines (everything ICI), the
        hierarchical / DCN synthesis on a SliceHierarchy when the
        group spans the slice boundary (`cross`).

        `grad_bucket` marks gradient-sync legs: their DCN latency term
        is amortized by the bucket fraction the leaf's DCN-leg bytes
        fill (dcn_bucket_bytes), because real runtimes coalesce grad
        all-reduces into buckets — many small leaves then cost
        latency-sublinear in leaf count while total bytes are
        unchanged.  Activation/resharding collectives are NOT bucketed
        (each is a real standalone collective on the wire)."""
        if group_len <= 1:
            return ZERO_COST
        if self._hier:
            lat_scale = 1.0
            if grad_bucket and cross and self.dcn_bucket_bytes:
                intra, _ = self.machine.split_group(group_len)
                dcn_size = size / intra if intra > 1 else size
                lat_scale = min(1.0, dcn_size / self.dcn_bucket_bytes)
            return self.machine.collective_cost(kind, size, group_len,
                                                cross=cross,
                                                dcn_lat_scale=lat_scale)
        return CommCost(
            ici_time=self._collective_time(kind, size, group_len),
            ici_bytes=ring_bytes(kind, size, group_len),
        )

    def _collective_time(self, kind: str, size: int, group_len: int,
                         over_dcn: bool = False) -> float:
        m = self.machine
        if isinstance(m, TpuPodModel):
            if kind == "allreduce":
                return m.axis_allreduce_time(size, group_len, over_dcn)
            if kind in ("allgather", "reducescatter"):
                return m.axis_allgather_time(size, group_len, over_dcn)
            if kind == "alltoall":
                return m.axis_alltoall_time(size, group_len, over_dcn)
        group = list(range(group_len))
        if kind == "allreduce":
            return m.allreduce_time(size, group)
        if kind in ("allgather", "reducescatter"):
            return m.allgather_time(size, group)
        return m.alltoall_time(size, group)

    # -- placement / tier decisions (topology/hierarchy.py) --------------
    def effective_placement(self, mesh_axes: Optional[Dict[str, int]],
                            placement: Optional[str]) -> Optional[str]:
        """The cross-slice mesh axis one evaluation costs under: the
        per-call override (searches costing placements), else the
        simulator default, else the shared resolve_placement default —
        always validated against the mesh (an axis the slice count
        cannot divide falls back to the default).  None on flat
        machines, so every tier decision degrades to ICI."""
        if not self._hier or not mesh_axes:
            return None
        from ..topology.hierarchy import resolve_placement

        p = placement if placement is not None else self.placement
        if p is not None:
            n = mesh_axes.get(p, 0)
            if n >= self._slices and n % self._slices == 0:
                return p
        return resolve_placement(mesh_axes, self._slices)

    @staticmethod
    def _view_axes(pt) -> frozenset:
        view = getattr(pt, "machine_view", None)
        if view is None:
            return frozenset()
        return frozenset(view.used_axes())

    def _xfer_crosses(self, op: Op, eff_p: Optional[str]) -> bool:
        """Does a parallel op's resharding collective ride the
        cross-slice axis?  The moved degrees are the axes entering or
        leaving between input and output views (best-effort: views are
        assigned on the evaluator's applied graphs; viewless fallback
        stays ICI)."""
        if eff_p is None or not op.inputs or not op.outputs:
            return False
        return eff_p in (
            self._view_axes(op.inputs[0]) ^ self._view_axes(op.outputs[0])
        )

    def _partial_crosses(self, op: Op, eff_p: Optional[str]) -> bool:
        """Does a contraction partial-sum all-reduce span slices?  The
        psum group rides the output's replica-dim axes."""
        if eff_p is None or not op.outputs:
            return False
        view = getattr(op.outputs[0], "machine_view", None)
        if view is None:
            return False
        for dim, axes in zip(op.outputs[0].shape.dims, view.axes):
            if dim.is_replica_dim and eff_p in axes:
                return True
        return False

    def _weight_rep_crosses(self, w, eff_p: Optional[str]) -> bool:
        """Does this weight's gradient-sync replica group include the
        cross-slice axis?  True unless the placement axis SHARDS the
        weight (then its replicas all live inside one slice)."""
        if eff_p is None:
            return False
        view = getattr(w, "machine_view", None)
        if view is not None:
            for dim, axes in zip(w.shape.dims, view.axes):
                if not dim.is_replica_dim and eff_p in axes:
                    return False
        return True

    def xfer_cost(self, op: Op, mesh_axes: Dict[str, int]) -> float:
        """Cost of a parallel op's resharding collective (reference
        estimate_xfer_cost per type, simulator.cc:622-767).  Flat
        (single-tier) estimate — op_terms costs the placement-aware
        form through _xfer_cc."""
        return self._xfer_cc(op, mesh_axes, cross=False).time

    def _xfer_cc(self, op: Op, mesh_axes: Dict[str, int],
                 cross: bool = False):
        """The resharding collective as a per-tier CommCost; `cross`
        routes it over the slice boundary on hierarchy machines."""
        overhead = CommCost(ici_time=_KERNEL_OVERHEAD)
        if not op.is_parallel_op():
            return ZERO_COST
        inp, out = op.inputs[0].shape, op.outputs[0].shape
        shard_bytes = out.shard_bytes()
        t = op.op_type
        if t == OperatorType.REPARTITION:
            # slicing data already on-device under SPMD: near-free when
            # coming from replicated, all-to-all otherwise
            degree = op.params.degree
            if inp.total_degree == 1 or inp.replica_degree >= degree:
                return overhead
            return self._collective("alltoall", shard_bytes, degree, cross)
        if t == OperatorType.COMBINE:
            return self._collective(
                "allgather", inp.shard_bytes() * op.params.degree,
                op.params.degree, cross,
            )
        if t == OperatorType.REPLICATE:
            return self._collective(
                "allgather", shard_bytes, op.params.degree, cross
            )
        if t == OperatorType.REDUCTION:
            return self._collective(
                "allreduce", shard_bytes, op.params.degree, cross
            )
        if t == OperatorType.ALLTOALL:
            return self._collective(
                "alltoall", shard_bytes, op.params.degree, cross
            )
        if t == OperatorType.FUSED_PARALLEL:
            # one boundary, but each fused member still moves its bytes
            # (reference estimate_xfer_cost on FusedParallelOp walks the
            # member ops); shape propagates member to member
            key = (op.params, inp)
            members = self._fused_members.get(key)
            if members is None:
                from ..parallel.parallel_op import PARALLEL_OP_KINDS
                from ..tensor import ParallelTensor

                members = []
                shape = inp
                for kind, params in op.params.ops:
                    sub = PARALLEL_OP_KINDS[kind](params, [ParallelTensor(shape)])
                    members.append(sub)
                    shape = sub.outputs[0].shape
                self._fused_members[key] = members
            total = ZERO_COST
            for sub in members:
                total = total + self._xfer_cc(sub, mesh_axes, cross)
            return total if total.time > _KERNEL_OVERHEAD else overhead
        return overhead

    def partial_sum_cost(self, op: Op, mesh_axes: Dict[str, int]) -> float:
        """An op whose output replica degree exceeds its inputs' implies
        a contraction-dim partial sum -> all-reduce inserted by SPMD."""
        return self._partial_cc(op, mesh_axes, cross=False).time

    def _partial_cc(self, op: Op, mesh_axes: Dict[str, int],
                    cross: bool = False):
        if op.is_parallel_op() or not op.outputs:
            return ZERO_COST
        out_rep = op.outputs[0].shape.replica_degree
        in_rep = max((t.shape.replica_degree for t in op.inputs), default=1)
        if out_rep > in_rep:
            k = out_rep // max(1, in_rep)
            return self._collective(
                "allreduce", op.outputs[0].shape.shard_bytes(), k, cross
            )
        return ZERO_COST

    def sync_time(self, size: int, rep: int) -> float:
        """One weight's gradient sync under the configured
        ParameterSyncType: ring all-reduce, the parameter-server
        estimate 2*size/BW (reference simulator.cc:786-813), or free
        under NONE (reference config.h:55: no sync)."""
        if self.parameter_sync == "none":
            return 0.0
        if self.parameter_sync == "ps":
            bw, lat = self.machine.ps_link()
            return 2.0 * lat + 2.0 * size / bw
        return self._collective_time("allreduce", size, rep)

    def _stage(self, zero_stage: Optional[int]) -> int:
        """Effective ZeRO stage for one call: the per-call override
        (searches costing the ladder), else the simulator default."""
        return self.zero_stage if zero_stage is None else int(zero_stage)

    def wus_group(self, w, mesh_axes: Optional[Dict[str, int]] = None,
                  zero_stage: Optional[int] = None,
                  placement: Optional[str] = None) -> int:
        """The group size this weight's update actually shards over —
        the executor-fidelity mirror of parallel/zero.py.  1 means the
        leaf keeps the replicated update (wus off, a mesh without the
        wus axis, a weight not replicated over it, or no free logical
        dim evenly divisible by it), so it must keep replicated
        cost/memory here too.

        The runtime shards over the SINGLE configured wus mesh axis,
        not the weight's whole replica group, so on a mixed mesh
        ({data: 4, model: 2}) an 8-way-replicated weight shards 4-ways.
        Eligibility mirrors zero.py's rule exactly: the axis must be
        unused by the weight's spec — i.e. by its non-replica dims
        (replication is expressed by omission, so a replica-dim entry
        doesn't block) — and a free logical dim must divide evenly.
        Callers without mesh context (unity's per-op DP stage) fall
        back to the replica degree — exact on pure-dp meshes, and the
        authoritative evaluation always re-scores with mesh_axes.

        `placement` (the effective cross-slice axis): when the wus axis
        itself spans slices with an intra-slice remainder, the executor
        scatters over THAT remainder only (the expanded mesh's reduced
        axis, topology.expand_mesh_axes) — so the group shrinks to
        n / slices and the inter-slice leg rides grad_sync as a DCN
        all-reduce of the scattered shard."""
        if self._stage(zero_stage) < 1 or self.parameter_sync == "none":
            return 1
        if mesh_axes is None:
            n = w.shape.replica_degree
            if n <= 1:
                return 1
        else:
            n = mesh_axes.get(self.wus_axis, 1)
            if (placement == self.wus_axis and self._slices > 1
                    and n > self._slices and n % self._slices == 0):
                n //= self._slices
            if n <= 1:
                return 1
            view = getattr(w, "machine_view", None)
            if view is not None and any(
                self.wus_axis in axes
                for dim, axes in zip(w.shape.dims, view.axes)
                if not dim.is_replica_dim
            ):
                return 1  # axis already shards a logical dim
        if not any(
            not d.is_replica_dim and d.degree == 1
            and d.size > 0 and d.size % n == 0
            for d in w.shape.dims
        ):
            return 1
        return n

    def weight_update_comm(self, size: int, rep: int,
                           zero_stage: Optional[int] = None
                           ) -> Tuple[float, float, float]:
        """One weight's (grad-sync, post-update-all-gather, per-layer
        gather) times under the effective ZeRO stage.

        Replicated update (stage 0): ring all-reduce of the grad
        (sync_time), no gathers.  Stages 1/2: reduce-scatter the grad +
        all-gather the updated weight — the same ring bytes as the
        all-reduce, split around an update that now touches only
        numel/rep elements (stage 2 differs from 1 in MEMORY only: the
        grad buffer stays scattered).  Stage 3: the post-update gather
        disappears — weights stay resident-scattered — and instead the
        step pays TWO per-layer all-gathers (forward use + backward
        re-gather), credited with the double-buffered-prefetch overlap
        (Z3_PREFETCH_OVERLAP), not the generic one.  parameter_sync
        "none" keeps replicas unsynced, which the sharded update cannot
        express — it stays on the replicated path."""
        s, x, gx = self._weight_update_comm_cc(size, rep,
                                               zero_stage=zero_stage)
        return s.time, x.time, gx.time

    def _weight_update_comm_cc(self, size: int, rep: int,
                               zero_stage: Optional[int] = None,
                               cross: bool = False):
        """weight_update_comm as per-tier CommCosts: (grad leg,
        post-update gather, stage-3 per-layer gathers).  `cross` routes
        the group over the slice boundary — the placement axis exactly
        equal to the slice count, where the scattered update's RS/AG
        ride DCN whole (an intra-slice remainder instead shrinks the
        group and keeps these legs on ICI; see wus_group)."""
        stage = self._stage(zero_stage)
        if stage < 1 or self.parameter_sync == "none":
            t = self.sync_time(size, rep)
            sync = CommCost(ici_time=t, ici_bytes=(
                2.0 * size if (t and self.parameter_sync == "ps")
                else ring_bytes("allreduce", size, rep)
            )) if t else ZERO_COST
            return sync, ZERO_COST, ZERO_COST
        if self.parameter_sync == "ps":
            # flat 2*size/BW grad leg rides the ps link (single-tier)
            sync = CommCost(ici_time=self.sync_time(size, rep),
                            ici_bytes=2.0 * size)
        else:
            sync = self._collective("reducescatter", size, rep, cross,
                                    grad_bucket=True)
        gather = self._collective("allgather", size, rep, cross)
        if stage >= 3:
            return sync, ZERO_COST, gather + gather
        return sync, gather, ZERO_COST

    def grad_sync_cost(self, graph: Graph, mesh_axes: Dict[str, int]) -> float:
        """Gradient sync over each weight's replica axes (SPMD's psum in
        backward == reference optimizer ncclAllReduce; PS path
        optimizer.h:47-58)."""
        total = 0.0
        for op in graph.ops:
            for w in op.weights:
                rep = w.shape.replica_degree
                if rep > 1 and w.create_gradients:
                    total += self.sync_time(w.shape.shard_bytes(), rep)
        return total

    # -- per-op contribution terms (delta-sim decomposition) -------------
    def op_terms(self, op: Op, mesh_axes: Dict[str, int],
                 training: bool = True, skip_compute: bool = False,
                 zero_stage: Optional[int] = None,
                 placement: Optional[str] = None) -> OpTerms:
        """All of `op`'s additive contributions to simulate(), cached by
        (node_key, mesh signature, training).  node_key already encodes
        params + ShardConfig + input parallel shapes, so a strategy move
        that leaves an op's config and input shapes unchanged reuses its
        terms across candidates.  skip_compute: the op's compute is
        covered by a measured segment — don't run (or cache-measure) the
        per-op cost model for a term the aggregation will discard.

        On a SliceHierarchy machine, `placement` (per-call override of
        the simulator default) decides which mesh axis spans the DCN
        boundary: collectives whose group rides it cost the
        hierarchical / DCN synthesis, everything else stays on ICI, and
        the ici_/dcn_ tier fields carry the split."""
        # mesh signature preserves INSERTION order (not sorted): views —
        # which wus_group reads — are assigned by assign_axes' axis-
        # declaration-order heuristic, so two orderings of equal-size
        # axes are distinct mesh configurations and must not alias one
        # cache entry (strategy_signature keeps order for the same
        # reason)
        stage = self._stage(zero_stage)
        eff_p = self.effective_placement(mesh_axes, placement)
        # stage only shapes the weight-update terms, so weightless ops
        # are stage-invariant — key them at a single rung so a stage
        # sweep doesn't recompute their compute/xfer terms per stage
        key = (op.node_key(), tuple(mesh_axes.items()), training,
               skip_compute, stage if op.weights else 0, eff_p)
        hit = self._term_cache.get(key)
        if hit is not None:
            self.term_hits += 1
            return hit
        self.term_misses += 1
        compute = xfer = partial = grad_sync = opt_numel = 0.0
        opt_xfer = gather_xfer = 0.0
        fwd_time = recompute_extra = 0.0
        tiers = ZERO_COST  # per-tier time/bytes over every comm term
        mem_weights = mem_master = mem_grad = mem_gather = 0
        mem_opt = mem_residual = mem_transient = 0
        if op.op_type != OperatorType.INPUT:
            if op.is_parallel_op():
                cc = self._xfer_cc(op, mesh_axes,
                                   cross=self._xfer_crosses(op, eff_p))
                xfer = cc.time
                tiers = tiers + cc
            else:
                cc = self._partial_cc(op, mesh_axes,
                                      cross=self._partial_crosses(op, eff_p))
                partial = cc.time
                tiers = tiers + cc
                if training:
                    tiers = tiers + cc  # bwd mirror (simulate_ops's 2x)
                if not skip_compute:
                    cm = self.cost_model.cost(op)
                    fwd_time = cm.forward_time
                    compute = cm.forward_time + (
                        cm.backward_time if training else 0.0
                    )
        for w in op.weights:
            sb = w.shape.shard_bytes()
            mem_weights += sb
            opt_sb = sb
            master_sb = grad_sb = sb
            if w.create_gradients:
                numel = sb / max(
                    1, np.dtype(w.shape.dtype.np_dtype).itemsize
                )
                rep = w.shape.replica_degree
                g = self.wus_group(w, mesh_axes, zero_stage=stage,
                                   placement=eff_p)
                if g > 1:
                    # whole-axis crossing: the wus axis IS the slice dim
                    # (no intra remainder), so the scattered update's
                    # RS/AG ride DCN; with a remainder, wus_group shrank
                    # g to it and these legs stay on ICI
                    cross_whole = (
                        eff_p is not None and eff_p == self.wus_axis
                        and mesh_axes.get(self.wus_axis, 1) == self._slices
                    )
                    s_cc, x_cc, gx_cc = self._weight_update_comm_cc(
                        sb, g, zero_stage=stage, cross=cross_whole
                    )
                    grad_sync += s_cc.time
                    wcc = s_cc + x_cc + gx_cc
                    if (rep > g and rep % g == 0
                            and self.parameter_sync == "allreduce"):
                        # tracked replication beyond the (intra) wus
                        # group still all-reduces on the scattered
                        # shard — over DCN when the slice factor is in
                        # that remainder (the hierarchical reduction's
                        # inter-slice leg)
                        rem_cc = self._collective(
                            "allreduce", sb // g, rep // g,
                            cross=(not cross_whole
                                   and self._weight_rep_crosses(w, eff_p)),
                            grad_bucket=True,
                        )
                        grad_sync += rem_cc.time
                        wcc = wcc + rem_cc
                    opt_xfer += x_cc.time
                    gather_xfer += gx_cc.time
                    if training and gx_cc.time:
                        # ZeRO-3 x remat: backward recompute re-emits
                        # the per-layer gather INSIDE the checkpointed
                        # segment (executor keeps z3_cache=None under
                        # remat), where the double-buffered prefetch
                        # cannot run — one of the two gathers loses its
                        # credit.  The lost credit rides `recompute`
                        # (charged at full exposure only when the op's
                        # segment is ON), so remat-off plans keep
                        # today's gather_xfer pricing exactly.
                        recompute_extra += (
                            gx_cc.time / 2.0
                        ) * Z3_PREFETCH_OVERLAP
                    if training:
                        tiers = tiers + wcc
                    # the update runs on the 1/g shard; slots live
                    # there permanently
                    numel /= g
                    opt_sb = sb // g
                    if stage >= 2:
                        # ZeRO-2: the grad buffer stays reduce-scattered
                        # through the update — 1/g resident per device
                        grad_sb = sb // g
                    if stage >= 3:
                        # ZeRO-3/FSDP: master lives scattered; the
                        # gathered compute copy is transient (the
                        # double-buffer window rides mem_gather)
                        master_sb = sb // g
                        mem_gather += sb
                elif rep > 1:
                    # replicated update (stage 0, or this leaf falls
                    # back per parallel/zero.py): hierarchical
                    # all-reduce when the replica group spans slices
                    if self.parameter_sync == "allreduce":
                        rcc = self._collective(
                            "allreduce", sb, rep,
                            cross=self._weight_rep_crosses(w, eff_p),
                            grad_bucket=True,
                        )
                    else:
                        t = self.sync_time(sb, rep)
                        rcc = CommCost(
                            ici_time=t, ici_bytes=2.0 * sb
                        ) if t else ZERO_COST
                    grad_sync += rcc.time
                    if training:
                        tiers = tiers + rcc
                opt_numel += numel
            mem_opt += opt_sb
            mem_master += master_sb
            mem_grad += grad_sb
        for t in op.outputs:
            b = t.shape.shard_bytes()
            if op.op_type in self._FUSED_ACT_TYPES:
                mem_transient = max(mem_transient, b)
            else:
                mem_residual += b
        # searched remat (docs/PERF.md): what this op saves per device
        # when its segment is OFF, and what re-running its forward in
        # backward costs when it is ON.  Parallel ops re-run their
        # resharding collective; compute ops re-run forward plus the
        # fwd partial-sum psum; measured (skip_compute) ops contribute
        # no recompute estimate — their fwd split is unknown.
        recompute = 0.0
        if training and op.op_type != OperatorType.INPUT:
            recompute = (
                xfer if op.is_parallel_op()
                else fwd_time + partial
            ) + recompute_extra
        terms = OpTerms(
            compute=compute, xfer=xfer, partial=partial,
            grad_sync=grad_sync, opt_numel=opt_numel, opt_xfer=opt_xfer,
            gather_xfer=gather_xfer,
            ici_xfer=tiers.ici_time, dcn_xfer=tiers.dcn_time,
            ici_bytes=tiers.ici_bytes, dcn_bytes=tiers.dcn_bytes,
            mem_weights=mem_weights, mem_master=mem_master,
            mem_grad=mem_grad, mem_gather=mem_gather, mem_opt=mem_opt,
            mem_residual=mem_residual, mem_transient=mem_transient,
            mem_activation=mem_residual, recompute=recompute,
        )
        self._term_cache[key] = terms
        return terms

    def memory_from_terms(self, ops: Sequence[Op], mesh_axes: Dict[str, int],
                          training: bool = True,
                          zero_stage: Optional[int] = None,
                          placement: Optional[str] = None) -> int:
        """per_device_memory re-aggregated from cached OpTerms — exact
        for the training non-remat accounting (weights + residual sum +
        transient max; all integer bytes, so order-independent).  The
        remat and inference liveness models need whole-graph structure
        and keep using per_device_memory().

        Training weight accounting follows the ZeRO ladder: master
        resident (mem_master: /g at stage 3) + gradient buffer
        (mem_grad: /g at stage 2+) + slot bytes (mem_opt: /g at 1+) +
        the stage-3 double-buffered gather window (2x the largest op's
        gathered weight copies).  At stages 0/1 this is bit-identical
        to the pre-ladder weights*2 + slots*opt formula."""
        compute_copy = master = grads = opt = residuals = transient = 0
        gather_peak = 0
        for op in ops:
            terms = self.op_terms(op, mesh_axes, training,
                                  zero_stage=zero_stage,
                                  placement=placement)
            compute_copy += terms.mem_weights
            master += terms.mem_master
            grads += terms.mem_grad
            opt += terms.mem_opt
            residuals += terms.mem_residual
            transient = max(transient, terms.mem_transient)
            gather_peak = max(gather_peak, terms.mem_gather)
        if training:
            weights = (master + grads + self.optimizer_slots * opt
                       + 2 * gather_peak)
        else:
            weights = compute_copy
        return int(weights + residuals + transient)

    # -- searched rematerialization (docs/PERF.md) -----------------------
    def remat_layout(self, ops: Sequence[Op],
                     plan: Optional[Sequence[int]],
                     op_scale=None) -> Tuple[set, float, float]:
        """(on_guids, residual_bytes, worst_internal) for a per-segment
        remat plan over a topo-ordered op sequence.

          * on_guids — guids of ops inside ON (and pure) segments, whose
            `recompute` term the aggregation charges;
          * residual_bytes — activations that persist to backward under
            the plan: every segment-boundary tensor (the checkpoint
            saves — live as later segments' inputs either way) plus the
            internals of OFF / impure segments;
          * worst_internal — the largest ON segment's internal bytes,
            alive only while that segment's backward recomputes.

        plan=None means every pure segment is ON (the legacy --remat
        shape); an empty plan reproduces the dense accounting exactly
        (residual_bytes == the sum of mem_activation terms)."""
        from ..pcg.segments import split_segments_ops

        ops = list(ops)
        segments, boundaries = split_segments_ops(ops)
        boundary_guids = {g for g in boundaries if g is not None}
        sel = None if plan is None else {int(i) for i in plan}
        sc = op_scale or (lambda op: 1.0)
        on_guids: set = set()
        residual = 0.0
        worst = 0.0
        for i, seg in enumerate(segments):
            pure = all(op.op_type not in REMAT_IMPURE_TYPES for op in seg)
            on = pure and (sel is None or i in sel)
            internal = 0.0
            for op in seg:
                if op.op_type in self._FUSED_ACT_TYPES:
                    continue  # transient workspace, never a residual
                for t in op.outputs:
                    b = t.shape.shard_bytes() * sc(op)
                    if t.guid in boundary_guids:
                        residual += b
                    else:
                        internal += b
            if on:
                worst = max(worst, internal)
                on_guids.update(op.guid for op in seg)
            else:
                residual += internal
        return on_guids, residual, worst

    def remat_memory_from_terms(
        self, ops: Sequence[Op], mesh_axes: Dict[str, int],
        plan: Optional[Sequence[int]], training: bool = True,
        zero_stage: Optional[int] = None,
        placement: Optional[str] = None,
    ) -> int:
        """per_device_memory under a per-segment remat plan, aggregated
        from cached OpTerms + one O(n) segment sweep over the op
        sequence — usable on the evaluator's DELTA path (no Graph
        needed), unlike the legacy whole-graph _remat_peak.  Weight /
        optimizer residency is identical to memory_from_terms (the
        ZeRO ladder accounting); only the activation term changes.  An
        all-OFF plan is bit-identical to memory_from_terms."""
        compute_copy = master = grads = opt = transient = 0
        gather_peak = 0
        for op in ops:
            terms = self.op_terms(op, mesh_axes, training,
                                  zero_stage=zero_stage,
                                  placement=placement)
            compute_copy += terms.mem_weights
            master += terms.mem_master
            grads += terms.mem_grad
            opt += terms.mem_opt
            transient = max(transient, terms.mem_transient)
            gather_peak = max(gather_peak, terms.mem_gather)
        _, residual, worst = self.remat_layout(ops, plan)
        if training:
            weights = (master + grads + self.optimizer_slots * opt
                       + 2 * gather_peak)
        else:
            weights = compute_copy
        return int(weights + residual + worst + transient)

    # -- memory ----------------------------------------------------------

    #: outputs XLA recomputes inside fusions rather than materializing
    #: as backward residuals — they cost transient workspace, not
    #: step-long liveness
    _FUSED_ACT_TYPES = frozenset({
        OperatorType.ELEMENT_UNARY, OperatorType.ELEMENT_BINARY,
        OperatorType.CAST, OperatorType.DROPOUT,
    })

    def per_device_memory(self, graph: Graph, training: bool = True,
                          op_scale=None, remat: Optional[bool] = None,
                          mesh_axes: Optional[Dict[str, int]] = None,
                          zero_stage: Optional[int] = None,
                          placement: Optional[str] = None) -> int:
        """Peak per-device bytes: weights (+grads+optimizer slots when
        training) plus LIVE activations, not the sum of every tensor
        ever produced (the r02 model summed all of them, so
        memory_search optimized a systematically inflated objective).

          * training, no remat: backward residuals = outputs of
            non-fused ops persist to their backward; fused elementwise
            outputs only cost transient workspace (max single one);
          * training, remat: only single-tensor segment boundaries
            persist (jax.checkpoint semantics, executor._build_remat_plan)
            plus the largest segment's internals for recomputation;
          * inference: a liveness scan — a tensor dies after its last
            consumer.

        op_scale(op) -> float scales an op's contribution (pipeline
        strategies pass 1/num_stages for block ops — each device holds
        only its stage's weights/activations)."""
        remat = self.remat if remat is None else remat
        stage = self._stage(zero_stage)
        eff_p = self.effective_placement(mesh_axes, placement)
        scale = (lambda op: op_scale(op)) if op_scale is not None \
            else (lambda op: 1.0)
        weights = sum(
            w.shape.shard_bytes() * scale(op)
            for op in graph.ops for w in op.weights
        )
        if training:
            if stage >= 1 and self.parameter_sync != "none":
                # ZeRO ladder: slots of grad-bearing replicated weights
                # live on their 1/group shard (stage 1+); the gradient
                # buffer joins them at stage 2+ and the master weights
                # at stage 3 (plus the 2-layer gathered-copy window);
                # unshardable leaves fall back whole at every rung
                master = grads = opt = 0.0
                gather_peak = 0.0
                for op in graph.ops:
                    op_gather = 0.0
                    for w in op.weights:
                        sb = w.shape.shard_bytes()
                        sc = scale(op)
                        g = (self.wus_group(w, mesh_axes, zero_stage=stage,
                                            placement=eff_p)
                             if w.create_gradients else 1)
                        opt += (sb // g) * sc
                        grads += (sb // g if stage >= 2 else sb) * sc
                        if g > 1 and stage >= 3:
                            master += (sb // g) * sc
                            op_gather += sb * sc
                        else:
                            master += sb * sc
                    gather_peak = max(gather_peak, op_gather)
                weights = (master + grads + self.optimizer_slots * opt
                           + 2 * gather_peak)
            else:
                # master copy + grads + optimizer slots
                weights *= (2 + self.optimizer_slots)

        if not training:
            acts = self._liveness_peak(graph, scale)
        elif remat:
            acts = self._remat_peak(graph, scale)
        else:
            residuals = 0.0
            transient = 0.0
            for op in graph.ops:
                for t in op.outputs:
                    b = t.shape.shard_bytes() * scale(op)
                    if op.op_type in self._FUSED_ACT_TYPES:
                        transient = max(transient, b)
                    else:
                        residuals += b
            acts = residuals + transient
        return int(weights + acts)

    def _liveness_peak(self, graph: Graph, scale) -> float:
        from ..pcg.segments import last_use_positions

        topo = graph.topo_order()
        last_use = last_use_positions(topo)
        bytes_of: Dict[int, float] = {
            t.guid: t.shape.shard_bytes() * scale(op)
            for op in topo for t in op.outputs
        }
        live = peak = 0.0
        for i, op in enumerate(topo):
            for t in op.outputs:
                live += bytes_of[t.guid]
            peak = max(peak, live)
            for t in op.inputs:
                if last_use.get(t.guid) == i:
                    live -= bytes_of.get(t.guid, 0.0)
        return peak

    def _remat_peak(self, graph: Graph, scale) -> float:
        from ..pcg.segments import split_segments

        impure = {OperatorType.INPUT, OperatorType.CACHE,
                  OperatorType.GROUP_BY, OperatorType.AGGREGATE,
                  OperatorType.AGGREGATE_SPEC}
        segments, boundaries = split_segments(graph)
        boundary_guids = {g for g in boundaries if g is not None}
        bytes_of = {
            t.guid: t.shape.shard_bytes() * scale(op)
            for op in graph.ops for t in op.outputs
        }
        acts = sum(bytes_of[g] for g in boundary_guids)
        worst_internal = 0.0
        for seg in segments:
            pure = all(op.op_type not in impure for op in seg)
            internal = sum(
                bytes_of[t.guid]
                for op in seg for t in op.outputs
                if t.guid not in boundary_guids
                and op.op_type not in self._FUSED_ACT_TYPES
            )
            if pure:
                # recomputed in backward: alive only while this
                # segment's backward runs
                worst_internal = max(worst_internal, internal)
            else:
                acts += internal  # runs inline, residuals persist
        return acts + worst_internal

    def optimizer_update_cost(self, graph: Graph,
                              mesh_axes: Optional[Dict[str, int]] = None,
                              zero_stage: Optional[int] = None,
                              placement: Optional[str] = None) -> float:
        """Weight-update pass: read master weight + grad, write weight,
        touch each optimizer slot — pure HBM traffic in f32 (master
        precision), one fused kernel under jit.  At ZeRO stage >= 1 the
        pass touches only each replicated weight's 1/group shard
        (arXiv:2004.13336); stages 2/3 change residency, not the pass."""
        numel = 0.0
        eff_p = self.effective_placement(mesh_axes, placement)
        for op in graph.ops:
            for w in op.weights:
                if w.create_gradients:
                    sb = w.shape.shard_bytes()
                    n = sb / max(1, np.dtype(w.shape.dtype.np_dtype).itemsize)
                    numel += n / self.wus_group(w, mesh_axes,
                                                zero_stage=zero_stage,
                                                placement=eff_p)
        bytes_moved = numel * 4.0 * (3 + self.optimizer_slots)
        return bytes_moved / self.machine.device().hbm_bandwidth

    # -- top level -------------------------------------------------------
    def simulate(
        self,
        graph: Graph,
        mesh_axes: Dict[str, int],
        training: bool = True,
        segment_costs: Optional[Sequence[Tuple[Sequence[int], float]]] = None,
        zero_stage: Optional[int] = None,
        placement: Optional[str] = None,
        remat_plan: Optional[Sequence[int]] = None,
    ) -> SimResult:
        """segment_costs: [(member op guids, fwd+bwd seconds)] from
        profiler.measure_segment_costs — ops inside a measured region
        take the measurement (fused-granularity calibration); everything
        else stays analytic.

        remat_plan: a strategy's per-segment remat plan (list of ON
        segment indices; docs/PERF.md "Searched rematerialization") —
        charges each ON segment's recompute seconds and prices memory
        with the plan-aware accounting.  None keeps the legacy
        behavior: the `remat` bool changes memory only (_remat_peak),
        never time."""
        measured_ops: Dict[int, float] = {}  # op guid -> its region's cost
        seg_cost_total = 0.0
        if segment_costs:
            for guids, c in segment_costs:
                seg_cost_total += c
                for g in guids:
                    measured_ops[g] = c
        topo = graph.topo_order()
        if training and remat_plan is not None:
            memory_fn = lambda: self.remat_memory_from_terms(  # noqa: E731
                topo, mesh_axes, remat_plan, training,
                zero_stage=zero_stage, placement=placement,
            )
        elif training and not self.remat:
            memory_fn = lambda: self.memory_from_terms(  # noqa: E731
                topo, mesh_axes, training, zero_stage=zero_stage,
                placement=placement,
            )
        else:
            memory_fn = lambda: self.per_device_memory(  # noqa: E731
                graph, training, mesh_axes=mesh_axes, zero_stage=zero_stage,
                placement=placement,
            )
        return self.simulate_ops(
            topo, mesh_axes, training=training, measured_ops=measured_ops,
            seg_cost_total=seg_cost_total, memory_fn=memory_fn,
            zero_stage=zero_stage, placement=placement,
            remat_plan=remat_plan,
        )

    def simulate_ops(
        self,
        ops: Sequence[Op],
        mesh_axes: Dict[str, int],
        training: bool = True,
        measured_ops: Optional[Dict[int, float]] = None,
        seg_cost_total: float = 0.0,
        memory_fn: Optional[Callable[[], int]] = None,
        zero_stage: Optional[int] = None,
        placement: Optional[str] = None,
        remat_plan: Optional[Sequence[int]] = None,
    ) -> SimResult:
        """Aggregate cached per-op terms over `ops` (a topo-ordered op
        sequence).  The ONE aggregation path shared by full and delta
        evaluations: the invariant delta_eval(state) == full_eval(state)
        holds bit-for-bit because both sum identical cached OpTerms in
        identical order.  A remat_plan (docs/PERF.md "Searched
        rematerialization") adds each ON segment's `recompute` terms to
        the analytic compute — the segment sweep is a deterministic
        function of the op sequence, so the invariant extends across
        remat flips."""
        measured_ops = measured_ops or {}
        compute = seg_cost_total if training else seg_cost_total / 3.0
        analytic_compute = 0.0  # compute_scale applies ONLY here —
        # measured segment costs are already real backend seconds
        comm = 0.0
        sync = 0.0
        opt_numel = 0.0
        opt_xfer = 0.0
        gather_xfer = 0.0
        ici_time = dcn_time = ici_bytes = dcn_bytes = 0.0
        recompute_s = 0.0
        activation_bytes = 0.0
        on_guids = None
        if training and remat_plan is not None:
            on_guids, activation_bytes, _ = self.remat_layout(
                ops, remat_plan
            )
        breakdown: Dict[str, float] = {}
        for op in ops:
            if op.op_type == OperatorType.INPUT:
                if training and on_guids is None:
                    # keep the dense telemetry consistent with the
                    # memory accounting (and the plan-aware sweep),
                    # which both count input residuals
                    activation_bytes += sum(
                        t.shape.shard_bytes() for t in op.outputs
                    )
                continue
            terms = self.op_terms(op, mesh_axes, training,
                                  skip_compute=op.guid in measured_ops,
                                  zero_stage=zero_stage,
                                  placement=placement)
            if on_guids is None:
                if training:
                    activation_bytes += terms.mem_activation
            elif op.guid in on_guids:
                recompute_s += terms.recompute
                analytic_compute += terms.recompute
            ici_time += terms.ici_xfer
            dcn_time += terms.dcn_xfer
            ici_bytes += terms.ici_bytes
            dcn_bytes += terms.dcn_bytes
            if training:
                sync += terms.grad_sync
                opt_numel += terms.opt_numel
                opt_xfer += terms.opt_xfer
                gather_xfer += terms.gather_xfer
            if op.is_parallel_op():
                comm += terms.xfer
                breakdown[op.name] = terms.xfer
                continue
            ps = terms.partial
            if training and ps:
                ps *= 2.0  # fwd psum + bwd mirrored all-gather/psum
            comm += ps
            if op.guid in measured_ops:
                breakdown[op.name] = ps
                continue
            analytic_compute += terms.compute
            breakdown[op.name] = terms.compute + ps
        if training:
            # weight-update pass (optimizer_update_cost, from cached
            # per-op numel terms)
            bytes_moved = opt_numel * 4.0 * (3 + self.optimizer_slots)
            analytic_compute += bytes_moved / self.machine.device().hbm_bandwidth
        # XLA overlaps collectives with independent compute; gradient
        # sync gets its own credit when backward/update overlap is
        # modeled (--search-overlap-backward-update).  The sharded
        # update's weight all-gather (opt_xfer, stages 1/2) overlaps
        # the NEXT step's forward the way other collectives overlap
        # compute, so it takes the standard credit, not the
        # backward-sync one.  The ZeRO-3 per-layer gathers
        # (gather_xfer) take the EXPLICIT double-buffered-prefetch
        # credit instead — they sit on layer-boundary critical paths
        # and hide worse than generic resharding.
        effective_comm = (
            comm * (1.0 - self.overlap_fraction)
            + sync * (1.0 - self.sync_overlap_fraction)
            + opt_xfer * (1.0 - self.overlap_fraction)
            + gather_xfer * (1.0 - Z3_PREFETCH_OVERLAP)
        )
        compute = compute + analytic_compute * self.compute_scale
        total = compute + effective_comm
        res = SimResult(
            total_time=total,
            compute_time=compute,
            comm_time=comm,
            sync_time=sync,
            breakdown=breakdown,
            memory_fn=memory_fn,
        )
        # uncredited per-tier split of every comm term this aggregation
        # charged — the comm/{ici,dcn}_* telemetry + fidelity payload
        res.comm_tiers = {
            "ici_time": ici_time, "dcn_time": dcn_time,
            "ici_bytes": ici_bytes, "dcn_bytes": dcn_bytes,
        }
        # searched-remat telemetry: plan-aware saved activations + the
        # recompute seconds charged (as-scaled, matching total_time)
        res.activation_bytes = activation_bytes
        res.recompute_s = recompute_s * self.compute_scale
        return res
