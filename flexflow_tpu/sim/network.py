"""Network topology modeling + routing for the simulator.

TPU-native rebuild of this fork's distinguishing extension — the
topology-aware simulator (reference src/runtime/network.cc,
include/flexflow/simulator.h:172-605): an explicit connection matrix,
shortest-path/ECMP routing, topology generators, and a
NetworkedMachineModel whose transfer estimates follow routed paths
(per-hop latency, bottleneck bandwidth) instead of a flat constant.

Generators cover the reference's flat degree-constrained random graph
(network.cc:476-566), big-switch (network.cc:573-585), fully-connected,
and — the TPU-idiomatic addition — N-dimensional torus matching ICI
pod slices (each torus axis is a ring, per-hop wraparound links).
"""
from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .machine_model import MachineModel


ConnectionMatrix = np.ndarray  # int [n, n]; entry = #links between nodes


# ----------------------------------------------------------------------
# topology generators
# ----------------------------------------------------------------------

def fully_connected(num_nodes: int) -> ConnectionMatrix:
    conn = np.ones((num_nodes, num_nodes), np.int32)
    np.fill_diagonal(conn, 0)
    return conn


def big_switch(num_nodes: int) -> ConnectionMatrix:
    """num_nodes hosts + 1 switch node (index num_nodes), one link each
    way (network.cc:577-585)."""
    n = num_nodes + 1
    conn = np.zeros((n, n), np.int32)
    conn[:num_nodes, num_nodes] = 1
    conn[num_nodes, :num_nodes] = 1
    return conn


def flat_degree_constrained(num_nodes: int, degree: int,
                            seed: int = 0) -> ConnectionMatrix:
    """Random connected multigraph with per-node interface budget
    `degree` (network.cc:481-558): random-walk spanning tree first, then
    random pairing of remaining interfaces."""
    if degree < 2:
        raise ValueError("degree must be >= 2 for a connected topology")
    rng = np.random.RandomState(seed)
    conn = np.zeros((num_nodes, num_nodes), np.int32)

    visited = {0}
    curr = 0
    while len(visited) < num_nodes:
        nxt = int(rng.randint(num_nodes))
        if nxt == curr:
            continue
        if nxt not in visited:
            if conn[curr, nxt] == degree:
                continue
            conn[curr, nxt] += 1
            conn[nxt, curr] += 1
            visited.add(nxt)
            curr = nxt

    avail: List[List[int]] = [
        [i, degree - int(conn[i].sum())]
        for i in range(num_nodes)
        if conn[i].sum() < degree
    ]
    # random pairing; stop when fewer than two nodes have free interfaces
    guard = 10000
    while len(avail) > 1 and guard:
        guard -= 1
        a, b = rng.randint(len(avail)), rng.randint(len(avail))
        if a == b:
            continue
        na, nb = avail[a][0], avail[b][0]
        if conn[na, nb] >= degree:
            continue
        conn[na, nb] += 1
        conn[nb, na] += 1
        avail[a][1] -= 1
        avail[b][1] -= 1
        avail = [x for x in avail if x[1] > 0]
    return conn


def multi_slice_torus(dims: Sequence[int], slices: int,
                      dcn_links: int = 1) -> ConnectionMatrix:
    """`slices` identical per-slice tori (the ICI fabric) joined by a
    DCN tier: node i of every slice links to node i of every other
    slice with `dcn_links` parallel links (each TPU host owns its own
    DCN NIC, so the cross-slice fabric is host-to-host, not a single
    uplink).  Node order is slice-major — node s*per_slice + i is chip
    i of slice s — matching `SliceHierarchy`/`TpuPodModel` coords and
    the C-order device layout `topology.expand_mesh_axes` produces.

    This is the hierarchy's CONNECTIVITY/ROUTING view (hop structure:
    per-hop ICI inside a slice, one cross-slice hop between same-index
    chips).  A ConnectionMatrix carries link multiplicities only, and
    `NetworkedMachineModel` prices every link at one bandwidth — it
    CANNOT express the DCN tier being slower than ICI; per-tier
    bandwidth/latency live in the analytic `topology.SliceHierarchy`
    costs.  Pricing routed makespans on the real two-tier fabric needs
    per-link bandwidths in the routed model (a ROADMAP follow-up)."""
    if slices < 1:
        raise ValueError(f"slices must be >= 1, got {slices}")
    intra = torus(dims)
    per_slice = intra.shape[0]
    n = per_slice * slices
    conn = np.zeros((n, n), np.int32)
    for s in range(slices):
        base = s * per_slice
        conn[base:base + per_slice, base:base + per_slice] = intra
    for i in range(per_slice):
        for a in range(slices):
            for b in range(slices):
                if a != b:
                    conn[a * per_slice + i, b * per_slice + i] = dcn_links
    return conn


def torus(dims: Sequence[int]) -> ConnectionMatrix:
    """N-D torus (ICI pod-slice shape, e.g. (4,4) or (4,4,4)): each node
    links to +/-1 neighbors per axis with wraparound; axes of size 2
    get a single (not double) link."""
    dims = tuple(int(d) for d in dims)
    n = int(np.prod(dims))
    conn = np.zeros((n, n), np.int32)
    strides = np.cumprod((1,) + dims[:-1])

    def flat(coord):
        return int(sum(c * s for c, s in zip(coord, strides)))

    for idx in range(n):
        coord = [(idx // int(s)) % d for s, d in zip(strides, dims)]
        for ax, d in enumerate(dims):
            if d == 1:
                continue
            for delta in (1, -1):
                nb = list(coord)
                nb[ax] = (nb[ax] + delta) % d
                j = flat(nb)
                if j != idx:
                    conn[idx, j] = 1
    return conn


# ----------------------------------------------------------------------
# routing
# ----------------------------------------------------------------------

class RoutingStrategy:
    def get_routes(self, src: int, dst: int) -> List[List[Tuple[int, int]]]:
        """Equal-cost routes, each a list of (u, v) hops."""
        raise NotImplementedError

    def hop_count(self, src: int, dst: int) -> Tuple[int, int]:
        """(hops, narrowest link multiplicity) along one shortest path."""
        routes = self.get_routes(src, dst)
        if not routes:
            return 0, 0
        r = routes[0]
        narrow = min((self.conn[u, v] for u, v in r), default=0)
        return len(r), int(narrow)


class WeightedShortestPathRouting(RoutingStrategy):
    """Dijkstra unit-weight shortest path (network.cc:53-105), with all
    equal-cost predecessors kept so ECMP route sets are available
    (network.cc's EcmpRoutes)."""

    def __init__(self, conn: ConnectionMatrix, max_ecmp: int = 4):
        self.conn = np.asarray(conn)
        self.n = self.conn.shape[0]
        self.max_ecmp = max_ecmp
        self._cache: Dict[int, Tuple[np.ndarray, List[List[int]]]] = {}

    def _sssp(self, src: int) -> Tuple[np.ndarray, List[List[int]]]:
        if src in self._cache:
            return self._cache[src]
        dist = np.full(self.n, np.inf)
        preds: List[List[int]] = [[] for _ in range(self.n)]
        dist[src] = 0.0
        pq: List[Tuple[float, int]] = [(0.0, src)]
        done = np.zeros(self.n, bool)
        while pq:
            d, u = heapq.heappop(pq)
            if done[u]:
                continue
            done[u] = True
            for v in np.nonzero(self.conn[u])[0]:
                nd = d + 1.0
                if nd < dist[v]:
                    dist[v] = nd
                    preds[v] = [u]
                    heapq.heappush(pq, (nd, int(v)))
                elif nd == dist[v] and u not in preds[v]:
                    preds[v].append(u)
        self._cache[src] = (dist, preds)
        return dist, preds

    def get_routes(self, src: int, dst: int) -> List[List[Tuple[int, int]]]:
        if src == dst:
            return []
        if self.conn[src, dst] > 0:
            return [[(src, dst)]]
        _, preds = self._sssp(src)
        routes: List[List[Tuple[int, int]]] = []

        def walk(node: int, suffix: List[Tuple[int, int]]):
            if len(routes) >= self.max_ecmp:
                return
            if node == src:
                routes.append(list(suffix))
                return
            for p in preds[node]:
                walk(p, [(p, node)] + suffix)

        walk(dst, [])
        return routes


# ----------------------------------------------------------------------
# machine model
# ----------------------------------------------------------------------

class NetworkedMachineModel(MachineModel):
    """MachineModel over an arbitrary topology (reference
    simulator.h:515-605): transfers follow routed paths; collectives
    expand as rings over group members with routed inter-member hops.

    link_bandwidth is per link (a conn entry of k multiplies it);
    intra-node compute devices map 1:1 onto network nodes.
    """

    def __init__(
        self,
        conn: ConnectionMatrix,
        link_bandwidth: float = 100e9,
        link_latency: float = 1e-6,
        compute_tflops: float = 100.0,
        mem_bw: float = 1e12,
        routing: Optional[RoutingStrategy] = None,
        num_compute_nodes: Optional[int] = None,
    ):
        self.conn = np.asarray(conn)
        self.n = self.conn.shape[0]
        # switch-style topologies have extra non-compute nodes at the end
        self._num_compute = num_compute_nodes or self.n
        self.link_bw = link_bandwidth
        self.link_lat = link_latency
        self.compute_tflops = compute_tflops
        self.mem_bw = mem_bw
        self.routing = routing or WeightedShortestPathRouting(self.conn)

    # -- MachineModel interface ----------------------------------------
    def num_devices(self) -> int:
        return self._num_compute

    def device(self):
        from .machine_model import DeviceSpec

        return DeviceSpec(
            compute_tflops=self.compute_tflops, hbm_bytes=32 << 30,
            mem_bw=self.mem_bw,
        )

    def p2p_time(self, size: int, src: int, dst: int) -> float:
        if src == dst:
            return 0.0
        routes = self.routing.get_routes(src, dst)
        if not routes:
            return float("inf")
        best = min(routes, key=len)
        bw = min(self.link_bw * self.conn[u, v] for u, v in best)
        return len(best) * self.link_lat + size / bw

    def _ring_phase_time(self, chunk: float, group: Sequence[int]) -> float:
        """One phase of a ring collective: every member sends `chunk` to
        its ring successor simultaneously; phase time = slowest routed
        neighbor transfer."""
        k = len(group)
        return max(
            self.p2p_time(int(chunk), group[i], group[(i + 1) % k])
            for i in range(k)
        )

    def allreduce_time(self, size: int, group: Sequence[int]) -> float:
        k = len(group)
        if k <= 1:
            return 0.0
        return 2 * (k - 1) * self._ring_phase_time(size / k, list(group))

    def allgather_time(self, size: int, group: Sequence[int]) -> float:
        k = len(group)
        if k <= 1:
            return 0.0
        return (k - 1) * self._ring_phase_time(size / k, list(group))

    def reducescatter_time(self, size: int, group: Sequence[int]) -> float:
        k = len(group)
        if k <= 1:
            return 0.0
        return (k - 1) * self._ring_phase_time(size / k, list(group))

    def alltoall_time(self, size: int, group: Sequence[int]) -> float:
        k = len(group)
        if k <= 1:
            return 0.0
        # each member exchanges size/k with every other; serialize the
        # k-1 routed sends per member, overlapped across members
        return max(
            sum(
                self.p2p_time(int(size / k), g, h)
                for h in group if h != g
            )
            for g in group
        )

    # -- taskgraph-sim integration -------------------------------------
    def link_table(self) -> Tuple[List[Tuple[int, int]], Dict[Tuple[int, int], int]]:
        """Directed link list [(u, v)] and index lookup for building
        per-link contention arrays."""
        links: List[Tuple[int, int]] = []
        index: Dict[Tuple[int, int], int] = {}
        for u in range(self.n):
            for v in np.nonzero(self.conn[u])[0]:
                index[(u, int(v))] = len(links)
                links.append((u, int(v)))
        return links, index

    def route_links(self, src: int, dst: int,
                    index: Dict[Tuple[int, int], int]) -> List[int]:
        routes = self.routing.get_routes(src, dst)
        if not routes:
            raise ValueError(f"no route {src}->{dst}")
        return [index[hop] for hop in min(routes, key=len)]
