"""Slice hierarchy: the two-level (ICI-within / DCN-between) machine model.

Production TPU scale is pods of slices — a fast per-slice ICI torus and
a much slower DCN fabric between slices.  "Synthesizing Optimal
Parallelism Placement and Reduction Strategies on Hierarchical Systems"
(arXiv:2110.10548) shows placement and reduction strategy must be
searched *jointly* on such hierarchies; this module owns the model both
searches and the executor share:

  * `SliceHierarchy` — a `TpuPodModel` whose slice count is live: it
    keeps every flat (single-tier) collective estimate of its parent
    AND exposes the two-level costs — a hierarchical all-reduce is
    intra-slice reduce-scatter over ICI, inter-slice all-reduce over
    DCN on the scattered shard, intra-slice all-gather back;
  * *placement* helpers — `resolve_placement` / `legal_placements` pick
    which strategy mesh axis spans the DCN boundary (every other axis
    stays inside a slice), `expand_mesh_axes` lowers that choice to the
    execution mesh (the placement axis splits into a leading
    `SLICE_AXIS` of size S and its intra-slice remainder, so XLA's
    C-order device layout puts the slice dimension outermost and the
    sharding-constraint re-specs in parallel/zero.py + the executor can
    name the intra-slice axis).

Every cost is returned as a `CommCost` carrying the per-tier (ICI vs
DCN) time and ring-bytes split — the terms `sim/simulator.py` folds
into `OpTerms.ici_xfer`/`dcn_xfer` and the `comm/*_bytes` telemetry.
All times in seconds, sizes in bytes.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..sim.machine_model import DeviceSpec, TpuPodModel, V5P_DEVICE
from .comm import CommCost, ZERO_COST, ring_bytes

#: reserved execution-mesh axis name for the inter-slice (DCN) dim.
#: Strategies never generate it; model.compile refuses to expand a
#: user mesh that already names it.
SLICE_AXIS = "slice"


class SliceHierarchy(TpuPodModel):
    """ICI torus per slice + DCN between slices, with two-level
    collective costs alongside the flat per-axis ones.

    `topology` is ONE slice's per-axis chip counts; `slices` joins that
    many identical slices over DCN.  Mesh axes inside a slice ride ICI;
    the searched *placement* axis spans slices and its collectives cost
    the hierarchical (or pure-DCN) form via `collective_cost`.
    """

    version = 3

    def __init__(
        self,
        topology: Tuple[int, ...] = (4,),
        slices: int = 2,
        device: DeviceSpec = V5P_DEVICE,
        ici_bw_per_link: float = 90e9,
        ici_latency: float = 1e-6,
        dcn_bw_per_host: float = 25e9,
        dcn_latency: float = 10e-6,
    ):
        if slices < 1:
            raise ValueError(f"slices must be >= 1, got {slices}")
        super().__init__(
            topology=topology,
            device=device,
            ici_bw_per_link=ici_bw_per_link,
            ici_latency=ici_latency,
            dcn_bw_per_host=dcn_bw_per_host,
            dcn_latency=dcn_latency,
            slices=slices,
        )

    # -- single-tier legs (flat API, tier explicit) ---------------------
    def tier_collective(self, kind: str, size: float, n: int,
                        over_dcn: bool = False,
                        dcn_lat_scale: float = 1.0) -> CommCost:
        """One collective entirely on one tier, as a CommCost.

        `dcn_lat_scale` scales the latency term of DCN legs only (the
        grad-sync bucketing amortization, sim/simulator.py); ICI legs
        and all bandwidth/byte terms are untouched."""
        if n <= 1:
            return ZERO_COST
        lat_scale = dcn_lat_scale if over_dcn else 1.0
        if kind == "allreduce":
            t = self.axis_allreduce_time(size, n, over_dcn,
                                         lat_scale=lat_scale)
        elif kind in ("allgather", "reducescatter"):
            t = self.axis_allgather_time(size, n, over_dcn,
                                         lat_scale=lat_scale)
        else:
            t = self.axis_alltoall_time(size, n, over_dcn,
                                        lat_scale=lat_scale)
        b = ring_bytes(kind, size, n)
        if over_dcn:
            return CommCost(dcn_time=t, dcn_bytes=b)
        return CommCost(ici_time=t, ici_bytes=b)

    # -- two-level collective costs -------------------------------------
    def split_group(self, group_len: int) -> Tuple[int, int]:
        """(intra, inter) factorization of a cross-slice group: the
        inter leg is the slice count whenever it divides the group,
        else the whole group degrades to pure DCN."""
        s = self.slices
        if s <= 1 or group_len <= 1:
            return group_len, 1
        if group_len % s == 0:
            return group_len // s, s
        return 1, group_len  # unfactorable: every hop may cross DCN

    def hierarchical_allreduce_time(self, size: float, intra: int,
                                    inter: int) -> float:
        return self.hierarchical_cost("allreduce", size, intra, inter).time

    def hierarchical_cost(self, kind: str, size: float, intra: int,
                          inter: int,
                          dcn_lat_scale: float = 1.0) -> CommCost:
        """Two-level synthesis of one collective over `intra * inter`
        devices where the inter leg crosses DCN.

        all-reduce:      RS over ICI -> AR of size/intra over DCN
                         -> AG over ICI  (the reduction the executor
                         synthesizes with sharding-constraint re-specs);
        reduce-scatter:  RS over ICI -> RS of size/intra over DCN;
        all-gather:      AG of size/intra over DCN -> AG over ICI;
        all-to-all:      intra-slice exchange over ICI plus the
                         cross-slice fraction (inter-1)/inter over DCN.
        """
        if intra <= 1:
            return self.tier_collective(kind, size, inter, over_dcn=True,
                                        dcn_lat_scale=dcn_lat_scale)
        if inter <= 1:
            return self.tier_collective(kind, size, intra)
        if kind == "allreduce":
            return (
                self.tier_collective("reducescatter", size, intra)
                + self.tier_collective("allreduce", size / intra, inter,
                                       over_dcn=True,
                                       dcn_lat_scale=dcn_lat_scale)
                + self.tier_collective("allgather", size, intra)
            )
        if kind == "reducescatter":
            return (
                self.tier_collective("reducescatter", size, intra)
                + self.tier_collective("reducescatter", size / intra,
                                       inter, over_dcn=True,
                                       dcn_lat_scale=dcn_lat_scale)
            )
        if kind == "allgather":
            return (
                self.tier_collective("allgather", size / intra, inter,
                                     over_dcn=True,
                                     dcn_lat_scale=dcn_lat_scale)
                + self.tier_collective("allgather", size, intra)
            )
        # alltoall: each device exchanges (n-1)/n of size; the slices it
        # does not share ICI with account for the (inter-1)/inter slab
        cross = size * (inter - 1) / inter
        return (
            self.tier_collective("alltoall", size - cross, intra)
            + self.tier_collective("alltoall", cross, inter, over_dcn=True,
                                   dcn_lat_scale=dcn_lat_scale)
        )

    def collective_cost(self, kind: str, size: float, group_len: int,
                        cross: bool = False,
                        dcn_lat_scale: float = 1.0) -> CommCost:
        """The cost the simulator charges one collective: flat ICI when
        the group stays inside a slice, the hierarchical synthesis when
        it spans the DCN boundary.  `dcn_lat_scale` (grad-sync
        bucketing) scales only the DCN legs' latency terms."""
        if group_len <= 1:
            return ZERO_COST
        if not cross or self.slices <= 1:
            return self.tier_collective(kind, size, group_len)
        intra, inter = self.split_group(group_len)
        return self.hierarchical_cost(kind, size, intra, inter,
                                      dcn_lat_scale=dcn_lat_scale)


PodModel = SliceHierarchy  # the ISSUE's alias


# ----------------------------------------------------------------------
# placement: which strategy mesh axis spans the DCN boundary
# ----------------------------------------------------------------------

def legal_placements(mesh_axes: Dict[str, int], slices: int) -> List[str]:
    """Axes a strategy may place across slices: size divisible by the
    slice count (each slice then holds an equal 1/S of that axis)."""
    if slices <= 1:
        return []
    return [
        a for a, n in mesh_axes.items()
        if n >= slices and n % slices == 0
    ]


def resolve_placement(mesh_axes: Dict[str, int],
                      slices: int) -> Optional[str]:
    """Default placement when a strategy carries none: the first legal
    axis in declaration order (strategies declare the data axis first,
    so the default keeps model/expert groups intra-slice — grad sync
    crosses DCN once per step in hierarchical form, per-layer
    collectives stay on ICI).  None when no axis can span the slices
    (the run degrades to a flat, placement-less execution)."""
    legal = legal_placements(mesh_axes, slices)
    return legal[0] if legal else None


def expand_mesh_axes(
    mesh_axes: Dict[str, int], slices: int, placement: str,
) -> Tuple[Dict[str, int], Optional[str]]:
    """Lower a placement choice to the execution mesh.

    Returns (exec_axes, intra_axis):

      * placement axis larger than the slice count: a leading
        `SLICE_AXIS` of size S is inserted and the placement axis keeps
        its name at 1/S size — `intra_axis` names it, and the
        reduction-synthesis re-specs (executor/parallel.zero) scatter
        over it so the cross-slice reduction decomposes into
        RS(ICI) -> AR(DCN) -> AG(ICI);
      * placement axis exactly the slice count: the axis IS the slice
        dim — it moves to the front (outermost in the C-order device
        layout) and there is no intra remainder (`intra_axis` None).

    The leading position is what aligns the axis with physical slices:
    jax's C-order reshape varies the first axis slowest, so slice id ==
    device_index // devices_per_slice.
    """
    size = mesh_axes.get(placement, 0)
    if slices <= 1 or size < slices or size % slices:
        raise ValueError(
            f"placement {placement!r} (size {size}) cannot span "
            f"{slices} slices"
        )
    if size == slices:
        out = {placement: size}
        out.update(
            (k, v) for k, v in mesh_axes.items() if k != placement
        )
        return out, None
    out = {SLICE_AXIS: slices}
    for k, v in mesh_axes.items():
        out[k] = v // slices if k == placement else v
    return out, placement


def placement_stats(strategy, slices: int) -> Dict[str, object]:
    """The search_stats payload describing a winner's placement: the
    effective cross-slice axis ("" on flat runs) and whether its grad
    reduction lowers to the hierarchical form (an intra-slice remainder
    exists) rather than a pure-DCN ring.  Pipeline winners report no
    placement — model.compile executes them flat (unexpanded), so
    claiming one would advertise a reduction never synthesized."""
    if slices <= 1 or getattr(strategy, "pipeline", None):
        return {"placement": "", "hierarchical_reduction": False}
    eff = getattr(strategy, "placement", None)
    if eff not in legal_placements(strategy.mesh_axes, slices):
        eff = resolve_placement(strategy.mesh_axes, slices)
    return {
        "placement": eff or "",
        "hierarchical_reduction": bool(
            eff and strategy.mesh_axes.get(eff, 0) > slices
        ),
    }


def hierarchy_from_config(cfg, num_devices: int) -> SliceHierarchy:
    """Build the run's SliceHierarchy from FFConfig (--slices,
    --slice-topology, --dcn-bandwidth, --dcn-latency).  The per-slice
    topology defaults to a 1-D ring of num_devices/slices chips —
    the multi-slice face of make_machine_model's flat default.

    --machine-model-file still contributes: its device roofline and
    per-link ICI bandwidth/latency describe ONE slice's fabric (the
    cfg DCN knobs own the inter-slice tier), and its topology serves
    as the per-slice default when --slice-topology is unset."""
    from ..sim.machine_model import TpuPodModel, detect_device_spec

    slices = max(1, int(cfg.slices))
    if num_devices % slices:
        raise ValueError(
            f"{num_devices} devices do not split into {slices} equal "
            "slices"
        )
    per_slice = num_devices // slices
    device = None
    ici_kw = {}
    file_topo: Optional[Tuple[int, ...]] = None
    if getattr(cfg, "machine_model_file", None):
        base = TpuPodModel.from_file(cfg.machine_model_file)
        device = base.device()
        ici_kw = {"ici_bw_per_link": base.ici_bw,
                  "ici_latency": base.ici_lat}
        file_topo = base.topology
    topo: Tuple[int, ...]
    if cfg.slice_topology:
        topo = parse_slice_topology(cfg.slice_topology)
    elif file_topo is not None and _prod(file_topo) == per_slice:
        topo = file_topo
    else:
        topo = (per_slice,)
    if _prod(topo) != per_slice:
        raise ValueError(
            f"slice topology {topo} has {_prod(topo)} chips per slice "
            f"but {num_devices} devices / {slices} slices = {per_slice}"
        )
    return SliceHierarchy(
        topology=topo,
        slices=slices,
        device=device if device is not None else detect_device_spec(),
        dcn_bw_per_host=float(cfg.dcn_bandwidth),
        dcn_latency=float(cfg.dcn_latency),
        **ici_kw,
    )


def _prod(dims: Tuple[int, ...]) -> int:
    n = 1
    for d in dims:
        n *= d
    return n


def parse_slice_topology(spec: str) -> Tuple[int, ...]:
    """'4x4' or '4,4' -> (4, 4); raises ValueError on anything else."""
    parts = [p for p in str(spec).replace("x", ",").split(",") if p.strip()]
    if not parts:
        raise ValueError(f"empty slice topology {spec!r}")
    try:
        dims = tuple(int(p) for p in parts)
    except ValueError:
        raise ValueError(
            f"slice topology {spec!r} must be comma/x-separated ints"
        ) from None
    if any(d < 1 for d in dims):
        raise ValueError(f"slice topology {spec!r} has non-positive dims")
    return dims


__all__ = [
    "SLICE_AXIS",
    "CommCost",
    "ZERO_COST",
    "PodModel",
    "SliceHierarchy",
    "expand_mesh_axes",
    "hierarchy_from_config",
    "legal_placements",
    "parse_slice_topology",
    "placement_stats",
    "resolve_placement",
    "ring_bytes",
]
