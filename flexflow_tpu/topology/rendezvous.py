"""Cross-slice rendezvous: the blob-store agreement primitive.

PR 9's preemption barrier (distributed.preemption_barrier) was a
one-purpose rendezvous: every host posts its boundary step, waits for
the quorum, agrees on the max.  The slice hierarchy needs the same
shape for more than preemption — slices must agree on the training
epoch they resume from after an elastic event, and a coordinator needs
a liveness census of its slices — so the primitive is generalized here
and the preemption barrier becomes one caller of it.

Protocol (unchanged from the barrier):

  * each participant posts JSON under `<prefix>/<run_id>/<kind>/host_i`;
  * everyone polls the prefix until the full quorum posted or the hard
    deadline passes (a dead peer must never cost the agreement);
  * the agreement is a pure reduction over the posted values (MAX for
    steps/epochs — laggards can always run deterministically forward,
    nobody rewinds);
  * posts persist after agreement (deleting would race slower readers
    out of their quorum); callers clear the prefix at run start.

All blob failures degrade to the caller's own value with a warning —
a rendezvous is coordination sugar, never a crash source.
"""
from __future__ import annotations

import json
import logging
import time
from typing import Callable, Dict, List, Optional

_log = logging.getLogger("flexflow_tpu.topology.rendezvous")

#: default key prefix; the preemption barrier keeps its legacy
#: "barrier/<run_id>/" layout for on-store compatibility
RENDEZVOUS_PREFIX = "rendezvous"


def post_and_agree(
    blob,
    run_id: str,
    kind: str,
    value: int,
    *,
    host_id: int,
    num_hosts: int,
    reduce: Callable[[List[int]], int] = max,
    timeout_s: float = 30.0,
    poll_s: float = 0.05,
    sleep: Callable[[float], None] = time.sleep,
    prefix: Optional[str] = None,
    field: str = "step",
) -> int:
    """Post `value`, await the quorum, return the reduced agreement.

    `prefix=None` uses `rendezvous/<run_id>/<kind>/`; the preemption
    barrier passes its legacy `barrier/<run_id>/` layout.  The caller's
    own value always participates in the reduction, so a degraded store
    or timeout returns something no worse than acting alone.
    """
    from ..store.blobstore import BlobStoreError

    if num_hosts <= 1:
        return int(value)
    if prefix is None:
        prefix = f"{RENDEZVOUS_PREFIX}/{run_id}/{kind}/"
    key = f"{prefix}host_{host_id:05d}"
    payload = json.dumps({"host": int(host_id), field: int(value)}).encode()
    try:
        blob.put(key, payload)
    except BlobStoreError as e:
        _log.warning(
            "rendezvous %s post failed (%s); continuing with local "
            "value %d without agreement", kind, e, value,
        )
        return int(value)
    deadline = time.monotonic() + timeout_s
    agreed = int(value)
    while True:
        # the caller's own post is EXCLUDED from the reduced values
        # (its local `value` joins exactly once below) so non-idempotent
        # reductions (sum, count) stay correct; it still counts toward
        # the quorum
        posted = 0
        peer_vals: List[int] = []
        try:
            for k in blob.list(prefix):
                try:
                    v = int(json.loads(blob.get(k))[field])
                except (BlobStoreError, ValueError, KeyError, TypeError):
                    continue  # a peer's post mid-write: next poll sees it
                posted += 1
                if k != key:
                    peer_vals.append(v)
        except BlobStoreError:
            posted, peer_vals = 0, []
        agreed = int(reduce(peer_vals + [int(value)]))
        if posted >= num_hosts:
            return agreed
        if time.monotonic() >= deadline:
            _log.warning(
                "rendezvous %s timed out with %d/%d participants; "
                "agreement so far: %d", kind, posted, num_hosts, agreed,
            )
            return agreed
        sleep(poll_s)


def clear_rendezvous(blob, run_id: str, kind: Optional[str] = None) -> int:
    """Remove posts under `rendezvous/<run_id>/[<kind>/]` — run-start
    hygiene so a previous incarnation can never satisfy a later quorum.
    Returns the count removed; failures are swallowed."""
    from ..store.blobstore import BlobStoreError

    prefix = f"{RENDEZVOUS_PREFIX}/{run_id}/"
    if kind:
        prefix += f"{kind}/"
    removed = 0
    try:
        for k in blob.list(prefix):
            if blob.delete(k):
                removed += 1
    except BlobStoreError as e:
        _log.info("rendezvous clear failed (%s)", e)
    return removed


def epoch_rendezvous(
    blob, run_id: str, epoch: int, *, slice_id: int, num_slices: int,
    round_id: int = 0,
    timeout_s: float = 30.0, poll_s: float = 0.05,
    sleep: Callable[[float], None] = time.sleep,
) -> int:
    """Cross-slice epoch agreement: after an elastic event every slice
    posts the newest epoch/step it can serve from its tier-2 mirror and
    resumes from the MAX (the slice behind runs deterministically
    forward; nobody rewinds — the preemption-barrier invariant at slice
    granularity).

    Posts persist for the life of the run, so each elastic EVENT must
    use a fresh `round_id` (monotonic per event) — otherwise a later
    rendezvous meets its quorum instantly on the previous round's
    stale posts and two slices can agree on divergent epochs."""
    return post_and_agree(
        blob, run_id, f"epoch_{int(round_id):08d}", int(epoch),
        host_id=slice_id, num_hosts=num_slices,
        reduce=max, timeout_s=timeout_s, poll_s=poll_s, sleep=sleep,
        field="epoch",
    )


def health_census(
    blob, run_id: str, *, slice_id: int, num_slices: int,
    healthy: bool = True, round_id: int = 0,
    timeout_s: float = 5.0, poll_s: float = 0.05,
    sleep: Callable[[float], None] = time.sleep,
) -> Dict[int, bool]:
    """Cross-slice liveness census: each slice posts its health bit;
    returns {slice_id: healthy} for every slice that posted before the
    deadline (absent slices are presumed dead — the caller sizes the
    degraded mesh from the survivors).

    Like epoch_rendezvous, each census EVENT needs a fresh `round_id`:
    a dead slice's post from a previous round would otherwise keep
    reporting it healthy forever."""
    from ..store.blobstore import BlobStoreError

    prefix = f"{RENDEZVOUS_PREFIX}/{run_id}/health_{int(round_id):08d}/"
    key = f"{prefix}host_{slice_id:05d}"
    payload = json.dumps(
        {"host": int(slice_id), "healthy": bool(healthy)}
    ).encode()
    try:
        blob.put(key, payload)
    except BlobStoreError as e:
        _log.warning("health census post failed (%s)", e)
        return {int(slice_id): bool(healthy)}
    deadline = time.monotonic() + timeout_s
    seen: Dict[int, bool] = {}
    while True:
        try:
            for k in blob.list(prefix):
                try:
                    d = json.loads(blob.get(k))
                    seen[int(d["host"])] = bool(d["healthy"])
                except (BlobStoreError, ValueError, KeyError, TypeError):
                    continue
        except BlobStoreError:
            pass
        seen[int(slice_id)] = bool(healthy)
        if len(seen) >= num_slices or time.monotonic() >= deadline:
            return seen
        sleep(poll_s)


__all__ = [
    "RENDEZVOUS_PREFIX",
    "clear_rendezvous",
    "epoch_rendezvous",
    "health_census",
    "post_and_agree",
]
