"""Multi-slice topology subsystem (docs/TOPOLOGY.md).

Makes the slice hierarchy — fast ICI inside a slice, slow DCN between —
a first-class, searchable dimension end to end:

  * `hierarchy` — the `SliceHierarchy`/`PodModel` machine model with
    two-level collective costs, plus the placement helpers
    (`resolve_placement`, `legal_placements`, `expand_mesh_axes`) both
    searches and the executor share;
  * `rendezvous` — the cross-slice epoch/health rendezvous generalizing
    PR 9's blob-store preemption barrier.
"""
from .hierarchy import (
    SLICE_AXIS,
    CommCost,
    PodModel,
    SliceHierarchy,
    expand_mesh_axes,
    hierarchy_from_config,
    legal_placements,
    parse_slice_topology,
    resolve_placement,
)
from .rendezvous import (
    clear_rendezvous,
    epoch_rendezvous,
    health_census,
    post_and_agree,
)

__all__ = [
    "SLICE_AXIS",
    "CommCost",
    "PodModel",
    "SliceHierarchy",
    "clear_rendezvous",
    "epoch_rendezvous",
    "expand_mesh_axes",
    "health_census",
    "hierarchy_from_config",
    "legal_placements",
    "parse_slice_topology",
    "post_and_agree",
    "resolve_placement",
]
