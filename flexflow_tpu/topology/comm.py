"""Per-tier collective cost records (dependency-free).

Split out of `hierarchy.py` so `sim/simulator.py` can import the record
types at module level without a cycle: hierarchy.py imports the machine
models from `sim.machine_model` (whose package __init__ pulls in the
simulator), so anything the simulator needs at import time lives here.
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class CommCost:
    """One collective's cost, split by network tier."""

    ici_time: float = 0.0
    dcn_time: float = 0.0
    ici_bytes: float = 0.0  # ring bytes moved per device over ICI
    dcn_bytes: float = 0.0  # ring bytes moved per device over DCN

    @property
    def time(self) -> float:
        return self.ici_time + self.dcn_time

    def __add__(self, other: "CommCost") -> "CommCost":
        return CommCost(
            self.ici_time + other.ici_time,
            self.dcn_time + other.dcn_time,
            self.ici_bytes + other.ici_bytes,
            self.dcn_bytes + other.dcn_bytes,
        )


ZERO_COST = CommCost()


def ring_bytes(kind: str, size: float, n: int) -> float:
    """Per-device bytes a ring collective moves (the bandwidth-term
    bytes of the machine-model formulas)."""
    if n <= 1:
        return 0.0
    if kind == "allreduce":
        return 2.0 * (n - 1) / n * size
    return (n - 1) / n * size  # allgather / reducescatter / alltoall


__all__ = ["CommCost", "ZERO_COST", "ring_bytes"]
