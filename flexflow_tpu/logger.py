"""RecursiveLogger: indentation-scoped debug tracing (reference
src/runtime/recursive_logger.cc, used throughout the substitution
search to print nested DP/rewrite decisions, substitution.cc:1713)."""
from __future__ import annotations

import contextlib
import logging
from typing import Iterator

# library convention: never touch the root logger; the application
# configures handlers, we just avoid "no handler" warnings
logging.getLogger("flexflow_tpu").addHandler(logging.NullHandler())


class RecursiveLogger:
    def __init__(self, name: str = "flexflow_tpu"):
        self._log = logging.getLogger(name)
        self._depth = 0

    @property
    def depth(self) -> int:
        return self._depth

    def _indent(self, msg: str, args) -> str:
        # pre-format so a literal '%' in msg can't break logging
        return "  " * self._depth + (msg % args if args else msg)

    def debug(self, msg: str, *args):
        if self._log.isEnabledFor(logging.DEBUG):
            self._log.debug("%s", self._indent(msg, args))

    def info(self, msg: str, *args):
        if self._log.isEnabledFor(logging.INFO):
            self._log.info("%s", self._indent(msg, args))

    @contextlib.contextmanager
    def enter(self, label: str = "") -> Iterator[None]:
        if label:
            self.debug("%s {", label)
        self._depth += 1
        try:
            yield
        finally:
            self._depth -= 1
            if label:
                self.debug("}")

    def counters(self, label: str, mapping) -> None:
        """Log a flat counters mapping as one `k=v` line — the shared
        surface for search observability (evals/sec, memo hits,
        delta-vs-full evals, dirty-frontier sizes)."""
        if not self._log.isEnabledFor(logging.INFO):
            return
        parts = []
        for k, v in mapping.items():
            parts.append(f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}")
        self.info("%s: %s", label, " ".join(parts))

    def set_level(self, level):
        self._log.setLevel(level)


search_logger = RecursiveLogger("flexflow_tpu.search")

# supervisor observability (resilience/supervisor.py): restarts,
# retries, lost/skipped steps, checkpoint latency — emitted through
# `counters` so bench runs can scrape recovery overhead the same way
# they scrape search throughput
resilience_logger = RecursiveLogger("flexflow_tpu.resilience")

# on-chip calibration observability (profiler.measure_segment_costs):
# region-measurement failures emit here instead of ad-hoc stdout
# prints, so they land in run telemetry (the obs TelemetryLogHandler
# listens on the flexflow_tpu logger tree) and in any app-configured
# logging sink
calib_logger = RecursiveLogger("flexflow_tpu.calib")

# strategy/compile artifact store observability (store/): hit/miss
# decisions, quarantined corrupt entries, survivable publish failures —
# all non-fatal by design, so the log line is the only trace beyond the
# store/* counters
store_logger = RecursiveLogger("flexflow_tpu.store")

# serving-tier observability (serving/): engine build decisions (which
# paged-attention formulation is active — gather oracle vs fused
# Pallas kernel), surfaced here so operators can confirm the hot path
# from logs without scraping /v2/stats
serving_logger = RecursiveLogger("flexflow_tpu.serving")
