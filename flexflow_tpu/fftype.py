"""Core enums and scalar types for the TPU-native framework.

Mirrors the capability surface of the reference's ffconst.h (see
/root/reference/include/flexflow/ffconst.h:63-160 — 90+ operator types,
loss/metric/parameter-sync enums) but is a fresh, JAX-first design:
dtypes map onto jnp dtypes and operator types are used as keys in the
parallel-computation-graph (PCG) and the substitution/search engines.
"""
from __future__ import annotations

import enum

import jax.numpy as jnp


class DataType(enum.Enum):
    BOOL = "bool"
    INT32 = "int32"
    INT64 = "int64"
    HALF = "float16"
    BF16 = "bfloat16"
    FLOAT = "float32"
    DOUBLE = "float64"

    @property
    def np_dtype(self):
        return jnp.dtype(self.value)

    @property
    def size_bytes(self) -> int:
        return self.np_dtype.itemsize

    @classmethod
    def from_any(cls, value) -> "DataType":
        if isinstance(value, cls):
            return value
        name = jnp.dtype(value).name
        for member in cls:
            if member.value == name:
                return member
        raise ValueError(f"unsupported dtype: {value!r}")


class ActiMode(enum.Enum):
    NONE = "none"
    RELU = "relu"
    SIGMOID = "sigmoid"
    TANH = "tanh"
    GELU = "gelu"


class AggrMode(enum.Enum):
    """Embedding aggregation (reference: AGGR_MODE_* ffconst.h:48-52)."""

    NONE = "none"
    SUM = "sum"
    AVG = "avg"


class PoolType(enum.Enum):
    MAX = "max"
    AVG = "avg"


class LossType(enum.Enum):
    CATEGORICAL_CROSSENTROPY = "categorical_crossentropy"
    SPARSE_CATEGORICAL_CROSSENTROPY = "sparse_categorical_crossentropy"
    MEAN_SQUARED_ERROR_AVG_REDUCE = "mean_squared_error_avg"
    MEAN_SQUARED_ERROR_SUM_REDUCE = "mean_squared_error_sum"
    IDENTITY = "identity"


class MetricsType(enum.Enum):
    ACCURACY = "accuracy"
    CATEGORICAL_CROSSENTROPY = "categorical_crossentropy"
    SPARSE_CATEGORICAL_CROSSENTROPY = "sparse_categorical_crossentropy"
    MEAN_SQUARED_ERROR = "mean_squared_error"
    ROOT_MEAN_SQUARED_ERROR = "root_mean_squared_error"
    MEAN_ABSOLUTE_ERROR = "mean_absolute_error"


class CompMode(enum.Enum):
    TRAINING = "training"
    INFERENCE = "inference"


class ParameterSyncType(enum.Enum):
    """Reference: config.h:55-59 (NONE / PS / NCCL).

    On TPU both PS and NCCL collapse into SPMD gradient psum over the mesh;
    we keep the enum for API parity and to let the simulator model either
    a fused reduce-scatter+all-gather or a plain all-reduce.
    """

    NONE = "none"
    PS = "ps"
    ALL_REDUCE = "all_reduce"  # reference's NCCL path


class OperatorType(enum.Enum):
    # Sources
    INPUT = "input"
    WEIGHT = "weight"
    NOOP = "noop"
    # Dense compute
    CONV2D = "conv2d"
    LINEAR = "linear"
    EMBEDDING = "embedding"
    MULTIHEAD_ATTENTION = "multihead_attention"
    BATCH_MATMUL = "batch_matmul"
    # Elementwise
    ELEMENT_BINARY = "element_binary"
    ELEMENT_UNARY = "element_unary"
    # Normalization / pooling
    POOL2D = "pool2d"
    BATCH_NORM = "batch_norm"
    LAYER_NORM = "layer_norm"
    SOFTMAX = "softmax"
    # Shape
    CONCAT = "concat"
    SPLIT = "split"
    FLAT = "flat"
    RESHAPE = "reshape"
    TRANSPOSE = "transpose"
    REVERSE = "reverse"
    PAD = "pad"
    # Reductions / misc
    REDUCE_SUM = "reduce_sum"
    MEAN = "mean"
    CAST = "cast"
    DROPOUT = "dropout"
    GATHER = "gather"
    # MoE quartet (+ cache)
    TOPK = "topk"
    GROUP_BY = "group_by"
    AGGREGATE = "aggregate"
    AGGREGATE_SPEC = "aggregate_spec"
    CACHE = "cache"
    # Recurrent (reference legacy nmt/ LSTM)
    LSTM = "lstm"
    # Size-changing replication/reduction in the reference's convention
    # (replicate.cc:74-75 size *= degree; reduction.cc:74-77 size /=
    # degree): d stacked copies along a dim / fold-sum of d slices.
    # Compute ops here (NOT in the parallel set — our strategy IR's
    # Replicate/Reduction use the implicit replica dim instead); used by
    # the TASO catalog rules (pcg/taso.py).
    REPLICATE_STACK = "replicate_stack"
    REDUCTION_FOLD = "reduction_fold"
    # Fusion
    FUSED = "fused"
    # Parallel ops (the parallelism IR, reference src/parallel_ops/)
    REPARTITION = "repartition"
    COMBINE = "combine"
    REPLICATE = "replicate"
    REDUCTION = "reduction"
    ALLTOALL = "all_to_all"  # TPU-native addition for SP/EP resharding
    PIPELINE = "pipeline"
    FUSED_PARALLEL = "fused_parallel"

    def is_parallel_op(self) -> bool:
        return self in _PARALLEL_OPS


_PARALLEL_OPS = frozenset(
    {
        OperatorType.REPARTITION,
        OperatorType.COMBINE,
        OperatorType.REPLICATE,
        OperatorType.REDUCTION,
        OperatorType.ALLTOALL,
        OperatorType.PIPELINE,
        OperatorType.FUSED_PARALLEL,
    }
)


class OpUnary(enum.Enum):
    EXP = "exp"
    LOG = "log"
    SIN = "sin"
    COS = "cos"
    RELU = "relu"
    GELU = "gelu"
    SIGMOID = "sigmoid"
    TANH = "tanh"
    ELU = "elu"
    IDENTITY = "identity"
    RSQRT = "rsqrt"
    SQRT = "sqrt"
    ERF = "erf"
    FLOOR = "floor"
    POW = "pow"
    SCALAR_MULTIPLY = "scalar_multiply"
    SCALAR_ADD = "scalar_add"
    SCALAR_SUB = "scalar_sub"
    SCALAR_TRUE_DIV = "scalar_true_div"
    NEGATIVE = "negative"


class OpBinary(enum.Enum):
    ADD = "add"
    SUB = "sub"
    MUL = "mul"
    DIV = "div"
    MAX = "max"
    MIN = "min"
    POW = "pow"
