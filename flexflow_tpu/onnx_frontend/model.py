"""ONNX graph -> FFModel importer.

Reference: python/flexflow/onnx/model.py — `ONNXModel.apply` walks the
onnx protobuf graph and dispatches per node.op_type (handle_conv,
handle_gemm/handle_matmul, handle_relu, handle_maxpool, handle_concat,
handle_flatten, handle_add, ...).  Same design here: one handler per
op_type string; initializer tensors become weights copied in after
compile.  Parsing prefers the `onnx` package when installed and falls
back to the vendored wire-format codec (protowire.py) otherwise, so
serialized .onnx files import in dependency-free environments too.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..fftype import ActiMode
from ..model import FFModel
from ..tensor import ParallelTensor
from . import protowire


def _attrs(node) -> Dict[str, object]:
    out = {}
    for a in node.attribute:
        if isinstance(a, protowire.Attribute):
            out[a.name] = a.value
        else:
            import onnx

            out[a.name] = onnx.helper.get_attribute_value(a)
    return out


class ONNXModel:
    def __init__(self, path_or_model):
        try:
            import onnx
            import onnx.numpy_helper
        except ImportError:
            onnx = None
        if isinstance(path_or_model, str):
            self.model = (onnx.load(path_or_model) if onnx is not None
                          else protowire.load_model(path_or_model))
        elif isinstance(path_or_model, bytes):
            self.model = (onnx.ModelProto.FromString(path_or_model)
                          if onnx is not None
                          else protowire.load_model(path_or_model))
        else:
            self.model = path_or_model
        self.graph = self.model.graph
        self.initializers: Dict[str, np.ndarray] = {}
        for init in self.graph.initializer:
            if isinstance(init, protowire.Tensor):
                self.initializers[init.name] = init.array
            else:
                self.initializers[init.name] = onnx.numpy_helper.to_array(
                    init
                )
        self._weight_of_op: Dict[str, Dict[str, np.ndarray]] = {}
        # non-trainable op state captured at import (BatchNorm running
        # stats) — written into ff._state by copy_weights, the same
        # transfer the torch frontend does (torch_frontend/model.py:744)
        self._state_of_op: Dict[str, Dict[str, np.ndarray]] = {}

    # ------------------------------------------------------------------
    def apply(self, ff: FFModel,
              inputs: Sequence[ParallelTensor]) -> List[ParallelTensor]:
        env: Dict[str, object] = {}
        graph_inputs = [
            i for i in self.graph.input if i.name not in self.initializers
        ]
        for gi, t in zip(graph_inputs, inputs):
            env[gi.name] = t
        for name, arr in self.initializers.items():
            env[name] = arr

        for node in self.graph.node:
            handler = getattr(self, f"_handle_{node.op_type.lower()}", None)
            if handler is None:
                raise ValueError(f"unsupported ONNX op: {node.op_type}")
            outs = handler(ff, node, env)
            if not isinstance(outs, (tuple, list)):
                outs = [outs]
            for oname, val in zip(node.output, outs):
                env[oname] = val
        return [env[o.name] for o in self.graph.output]

    def copy_weights(self, ff: FFModel):
        import jax

        weights = ff.get_weights()
        for op_name, entry in self._weight_of_op.items():
            if op_name in weights:
                for k, v in entry.items():
                    weights[op_name][k] = v
        ff.set_weights(weights)
        for op_name, entry in self._state_of_op.items():
            st = (ff._state or {}).get(op_name)
            if st is None:
                continue
            for k, v in entry.items():
                if k in st:
                    old = st[k]
                    st[k] = jax.device_put(
                        np.asarray(v, old.dtype), old.sharding
                    )

    # -- handlers (reference handle_* methods) ---------------------------
    def _handle_gemm(self, ff, node, env):
        x = env[node.input[0]]
        w = env[node.input[1]]  # [out, in] (transB=1 convention)
        at = _attrs(node)
        if at.get("transA", 0):
            raise ValueError(
                f"Gemm {node.name}: transA=1 unsupported (no graph op "
                "transposes the activation operand)"
            )
        if not at.get("transB", 0):
            w = w.T
        # alpha/beta fold into the (constant) weight and bias
        alpha = float(at.get("alpha", 1.0))
        beta = float(at.get("beta", 1.0))
        w = w * alpha if alpha != 1.0 else w
        out_dim = w.shape[0]
        use_bias = len(node.input) > 2
        name = node.name or f"gemm_{node.output[0]}"
        out = ff.dense(x, out_dim, use_bias=use_bias, name=name)
        entry = {"kernel": np.ascontiguousarray(w.T)}
        if use_bias:
            b = np.asarray(env[node.input[2]], np.float32)
            entry["bias"] = b * beta if beta != 1.0 else b
        self._weight_of_op[name] = entry
        return out

    def _handle_matmul(self, ff, node, env):
        x = env[node.input[0]]
        w = env[node.input[1]]
        if isinstance(w, np.ndarray):  # weight matmul == dense, [in, out]
            name = node.name or f"matmul_{node.output[0]}"
            out = ff.dense(x, w.shape[1], use_bias=False, name=name)
            self._weight_of_op[name] = {"kernel": np.ascontiguousarray(w)}
            return out
        return ff.batch_matmul(x, w, name=node.name or None)

    def _handle_conv(self, ff, node, env):
        x = env[node.input[0]]
        w = env[node.input[1]]  # OIHW
        at = _attrs(node)
        kh, kw = at.get("kernel_shape", w.shape[2:4])
        sh, sw = at.get("strides", [1, 1])
        pads = at.get("pads", [0, 0, 0, 0])
        groups = at.get("group", 1)
        use_bias = len(node.input) > 2
        name = node.name or f"conv_{node.output[0]}"
        out = ff.conv2d(x, w.shape[0], kh, kw, sh, sw, pads[0], pads[1],
                        groups=groups, use_bias=use_bias, name=name)
        entry = {"kernel": np.asarray(w)}
        if use_bias:
            entry["bias"] = np.asarray(env[node.input[2]])
        self._weight_of_op[name] = entry
        return out

    def _handle_maxpool(self, ff, node, env):
        at = _attrs(node)
        kh, kw = at["kernel_shape"]
        sh, sw = at.get("strides", [1, 1])
        pads = at.get("pads", [0, 0, 0, 0])
        return ff.pool2d(env[node.input[0]], kh, kw, sh, sw, pads[0], pads[1],
                         pool_type="max", name=node.name or None)

    def _handle_averagepool(self, ff, node, env):
        at = _attrs(node)
        kh, kw = at["kernel_shape"]
        sh, sw = at.get("strides", [1, 1])
        pads = at.get("pads", [0, 0, 0, 0])
        return ff.pool2d(env[node.input[0]], kh, kw, sh, sw, pads[0], pads[1],
                         pool_type="avg", name=node.name or None)

    def _handle_relu(self, ff, node, env):
        return ff.relu(env[node.input[0]], name=node.name or None)

    def _handle_sigmoid(self, ff, node, env):
        return ff.sigmoid(env[node.input[0]], name=node.name or None)

    def _handle_tanh(self, ff, node, env):
        return ff.tanh(env[node.input[0]], name=node.name or None)

    def _handle_softmax(self, ff, node, env):
        at = _attrs(node)
        return ff.softmax(env[node.input[0]], axis=at.get("axis", -1),
                          name=node.name or None)

    def _handle_add(self, ff, node, env):
        return ff.add(env[node.input[0]], env[node.input[1]],
                      name=node.name or None)

    def _handle_sub(self, ff, node, env):
        return ff.subtract(env[node.input[0]], env[node.input[1]],
                           name=node.name or None)

    def _handle_mul(self, ff, node, env):
        return ff.multiply(env[node.input[0]], env[node.input[1]],
                           name=node.name or None)

    def _handle_concat(self, ff, node, env):
        at = _attrs(node)
        return ff.concat([env[i] for i in node.input], at.get("axis", 0),
                         name=node.name or None)

    def _handle_split(self, ff, node, env):
        at = _attrs(node)
        sizes = at.get("split")
        if sizes is None:
            sizes = len(node.output)
        return ff.split(env[node.input[0]], list(sizes)
                        if not isinstance(sizes, int) else sizes,
                        at.get("axis", 0), name=node.name or None)

    def _handle_flatten(self, ff, node, env):
        return ff.flat(env[node.input[0]], name=node.name or None)

    def _handle_reshape(self, ff, node, env):
        shape = env[node.input[1]]
        return ff.reshape(env[node.input[0]], [int(s) for s in shape],
                          name=node.name or None)

    def _handle_transpose(self, ff, node, env):
        at = _attrs(node)
        return ff.transpose(env[node.input[0]], list(at["perm"]),
                            name=node.name or None)

    def _handle_dropout(self, ff, node, env):
        at = _attrs(node)
        return ff.dropout(env[node.input[0]], at.get("ratio", 0.5),
                          name=node.name or None)

    def _handle_identity(self, ff, node, env):
        return env[node.input[0]]

    def _handle_batchnormalization(self, ff, node, env):
        """X, scale, B, mean, var -> batch_norm with trained affine +
        running stats transferred (the reference drops all four:
        python/flexflow/onnx/model.py:143-147)."""
        x = env[node.input[0]]
        at = _attrs(node)
        name = node.name or f"bn_{node.output[0]}"
        out = ff.batch_norm(
            x, relu=False,
            eps=float(at.get("epsilon", 1e-5)),
            momentum=float(at.get("momentum", 0.9)),
            name=name,
        )
        self._weight_of_op[name] = {
            "gamma": np.asarray(env[node.input[1]], np.float32),
            "beta": np.asarray(env[node.input[2]], np.float32),
        }
        self._state_of_op[name] = {
            "running_mean": np.asarray(env[node.input[3]], np.float32),
            "running_var": np.asarray(env[node.input[4]], np.float32),
        }
        return out

    def _handle_globalaveragepool(self, ff, node, env):
        x = env[node.input[0]]
        h, w = x.shape.logical_shape[2:4]
        return ff.pool2d(x, h, w, 1, 1, 0, 0, pool_type="avg",
                         name=node.name or None)

    def _handle_pad(self, ff, node, env):
        at = _attrs(node)
        mode = at.get("mode", b"constant")
        mode = mode.decode() if isinstance(mode, bytes) else mode
        if mode != "constant":
            raise ValueError(f"Pad {node.name}: mode {mode!r} unsupported")
        if "pads" in at:  # opset < 11
            flat = [int(p) for p in at["pads"]]
            value = float(at.get("value", 0.0))
        else:  # opset >= 11: pads (and optional value) are inputs
            flat = [int(p) for p in np.asarray(env[node.input[1]]).ravel()]
            value = (float(np.asarray(env[node.input[2]]).ravel()[0])
                     if len(node.input) > 2 and node.input[2] else 0.0)
        x = env[node.input[0]]
        rank = len(flat) // 2
        pads = list(zip(flat[:rank], flat[rank:]))
        if isinstance(x, np.ndarray):
            return np.pad(x, pads, constant_values=value)
        if not any(b or a for b, a in pads):
            return x
        return ff.pad(x, pads, value=value, name=node.name or None)

    def _handle_cast(self, ff, node, env):
        to = int(_attrs(node)["to"])
        np_dtype = protowire._DTYPES.get(to)
        if np_dtype is None:
            raise ValueError(f"Cast {node.name}: unsupported dtype {to}")
        x = env[node.input[0]]
        if isinstance(x, np.ndarray):
            return x.astype(np_dtype)
        return ff.cast(x, np.dtype(np_dtype).name, name=node.name or None)

    def _axes_arg(self, node, env, at):
        if "axes" in at:  # opset < 13
            return [int(a) for a in at["axes"]]
        return [int(a) for a in np.asarray(env[node.input[1]]).ravel()]

    def _handle_unsqueeze(self, ff, node, env):
        at = _attrs(node)
        axes = self._axes_arg(node, env, at)
        x = env[node.input[0]]
        if isinstance(x, np.ndarray):
            out_rank = x.ndim + len(axes)
            for ax in sorted(a % out_rank for a in axes):
                x = np.expand_dims(x, ax)
            return x
        shape = list(x.shape.logical_shape)
        out_rank = len(shape) + len(axes)
        for ax in sorted(a % out_rank for a in axes):
            shape.insert(ax, 1)
        return ff.reshape(x, shape, name=node.name or None)

    def _handle_squeeze(self, ff, node, env):
        at = _attrs(node)
        x = env[node.input[0]]
        if isinstance(x, np.ndarray):
            axes = (self._axes_arg(node, env, at)
                    if ("axes" in at or len(node.input) > 1) else None)
            return np.squeeze(x, tuple(axes) if axes else None)
        shape = list(x.shape.logical_shape)
        if "axes" in at or len(node.input) > 1:
            axes = {a % len(shape) for a in self._axes_arg(node, env, at)}
        else:
            axes = {i for i, s in enumerate(shape) if s == 1}
        shape = [s for i, s in enumerate(shape) if i not in axes]
        return ff.reshape(x, shape, name=node.name or None)

    def _handle_constant(self, ff, node, env):
        at = _attrs(node)
        if "value" in at:
            v = at["value"]
            if not isinstance(v, np.ndarray):
                # with the onnx package installed get_attribute_value
                # returns a raw TensorProto
                import onnx.numpy_helper

                v = onnx.numpy_helper.to_array(v)
            return np.asarray(v)
        for k in ("value_float", "value_int"):
            if k in at:
                return np.asarray(at[k])
        if "value_floats" in at:
            return np.asarray(at["value_floats"], np.float32)
        if "value_ints" in at:
            return np.asarray(at["value_ints"], np.int64)
        raise ValueError(f"Constant {node.name}: no value attribute")

    def _handle_range(self, ff, node, env):
        vals = [env[i] for i in node.input[:3]]
        if not all(isinstance(v, np.ndarray) for v in vals):
            raise ValueError(
                f"Range {node.name}: only constant start/limit/delta "
                "are supported (graph-tensor ranges are data-dependent "
                "shapes, which XLA cannot compile)"
            )
        start, limit, delta = (v.ravel()[0] for v in vals)
        return np.arange(start, limit, delta)

    def _handle_shape(self, ff, node, env):
        x = env[node.input[0]]
        shape = (x.shape if isinstance(x, np.ndarray)
                 else x.shape.logical_shape)
        at = _attrs(node)  # opset-15 slice attributes
        start = int(at.get("start", 0))
        end = at.get("end")
        return np.asarray(shape, np.int64)[
            start:(int(end) if end is not None else None)]


def onnx_to_flexflow(path_or_model, ff: FFModel,
                     inputs: Sequence[ParallelTensor]):
    m = ONNXModel(path_or_model)
    return m, m.apply(ff, inputs)
