"""ONNX graph -> FFModel importer.

Reference: python/flexflow/onnx/model.py — `ONNXModel.apply` walks the
onnx protobuf graph and dispatches per node.op_type (handle_conv,
handle_gemm/handle_matmul, handle_relu, handle_maxpool, handle_concat,
handle_flatten, handle_add, ...).  Same design here: one handler per
op_type string; initializer tensors become weights copied in after
compile.  Parsing prefers the `onnx` package when installed and falls
back to the vendored wire-format codec (protowire.py) otherwise, so
serialized .onnx files import in dependency-free environments too.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..fftype import ActiMode
from ..model import FFModel
from ..tensor import ParallelTensor
from . import protowire


def _attrs(node) -> Dict[str, object]:
    out = {}
    for a in node.attribute:
        if isinstance(a, protowire.Attribute):
            out[a.name] = a.value
        else:
            import onnx

            out[a.name] = onnx.helper.get_attribute_value(a)
    return out


class ONNXModel:
    def __init__(self, path_or_model):
        try:
            import onnx
            import onnx.numpy_helper
        except ImportError:
            onnx = None
        if isinstance(path_or_model, str):
            self.model = (onnx.load(path_or_model) if onnx is not None
                          else protowire.load_model(path_or_model))
        elif isinstance(path_or_model, bytes):
            self.model = (onnx.ModelProto.FromString(path_or_model)
                          if onnx is not None
                          else protowire.load_model(path_or_model))
        else:
            self.model = path_or_model
        self.graph = self.model.graph
        self.initializers: Dict[str, np.ndarray] = {}
        for init in self.graph.initializer:
            if isinstance(init, protowire.Tensor):
                self.initializers[init.name] = init.array
            else:
                self.initializers[init.name] = onnx.numpy_helper.to_array(
                    init
                )
        self._weight_of_op: Dict[str, Dict[str, np.ndarray]] = {}

    # ------------------------------------------------------------------
    def apply(self, ff: FFModel,
              inputs: Sequence[ParallelTensor]) -> List[ParallelTensor]:
        env: Dict[str, object] = {}
        graph_inputs = [
            i for i in self.graph.input if i.name not in self.initializers
        ]
        for gi, t in zip(graph_inputs, inputs):
            env[gi.name] = t
        for name, arr in self.initializers.items():
            env[name] = arr

        for node in self.graph.node:
            handler = getattr(self, f"_handle_{node.op_type.lower()}", None)
            if handler is None:
                raise ValueError(f"unsupported ONNX op: {node.op_type}")
            outs = handler(ff, node, env)
            if not isinstance(outs, (tuple, list)):
                outs = [outs]
            for oname, val in zip(node.output, outs):
                env[oname] = val
        return [env[o.name] for o in self.graph.output]

    def copy_weights(self, ff: FFModel):
        weights = ff.get_weights()
        for op_name, entry in self._weight_of_op.items():
            if op_name in weights:
                for k, v in entry.items():
                    weights[op_name][k] = v
        ff.set_weights(weights)

    # -- handlers (reference handle_* methods) ---------------------------
    def _handle_gemm(self, ff, node, env):
        x = env[node.input[0]]
        w = env[node.input[1]]  # [out, in] (transB=1 convention)
        at = _attrs(node)
        if not at.get("transB", 0):
            w = w.T
        out_dim = w.shape[0]
        use_bias = len(node.input) > 2
        name = node.name or f"gemm_{node.output[0]}"
        out = ff.dense(x, out_dim, use_bias=use_bias, name=name)
        entry = {"kernel": np.ascontiguousarray(w.T)}
        if use_bias:
            entry["bias"] = np.asarray(env[node.input[2]])
        self._weight_of_op[name] = entry
        return out

    def _handle_matmul(self, ff, node, env):
        x = env[node.input[0]]
        w = env[node.input[1]]
        if isinstance(w, np.ndarray):  # weight matmul == dense, [in, out]
            name = node.name or f"matmul_{node.output[0]}"
            out = ff.dense(x, w.shape[1], use_bias=False, name=name)
            self._weight_of_op[name] = {"kernel": np.ascontiguousarray(w)}
            return out
        return ff.batch_matmul(x, w, name=node.name or None)

    def _handle_conv(self, ff, node, env):
        x = env[node.input[0]]
        w = env[node.input[1]]  # OIHW
        at = _attrs(node)
        kh, kw = at.get("kernel_shape", w.shape[2:4])
        sh, sw = at.get("strides", [1, 1])
        pads = at.get("pads", [0, 0, 0, 0])
        groups = at.get("group", 1)
        use_bias = len(node.input) > 2
        name = node.name or f"conv_{node.output[0]}"
        out = ff.conv2d(x, w.shape[0], kh, kw, sh, sw, pads[0], pads[1],
                        groups=groups, use_bias=use_bias, name=name)
        entry = {"kernel": np.asarray(w)}
        if use_bias:
            entry["bias"] = np.asarray(env[node.input[2]])
        self._weight_of_op[name] = entry
        return out

    def _handle_maxpool(self, ff, node, env):
        at = _attrs(node)
        kh, kw = at["kernel_shape"]
        sh, sw = at.get("strides", [1, 1])
        pads = at.get("pads", [0, 0, 0, 0])
        return ff.pool2d(env[node.input[0]], kh, kw, sh, sw, pads[0], pads[1],
                         pool_type="max", name=node.name or None)

    def _handle_averagepool(self, ff, node, env):
        at = _attrs(node)
        kh, kw = at["kernel_shape"]
        sh, sw = at.get("strides", [1, 1])
        pads = at.get("pads", [0, 0, 0, 0])
        return ff.pool2d(env[node.input[0]], kh, kw, sh, sw, pads[0], pads[1],
                         pool_type="avg", name=node.name or None)

    def _handle_relu(self, ff, node, env):
        return ff.relu(env[node.input[0]], name=node.name or None)

    def _handle_sigmoid(self, ff, node, env):
        return ff.sigmoid(env[node.input[0]], name=node.name or None)

    def _handle_tanh(self, ff, node, env):
        return ff.tanh(env[node.input[0]], name=node.name or None)

    def _handle_softmax(self, ff, node, env):
        at = _attrs(node)
        return ff.softmax(env[node.input[0]], axis=at.get("axis", -1),
                          name=node.name or None)

    def _handle_add(self, ff, node, env):
        return ff.add(env[node.input[0]], env[node.input[1]],
                      name=node.name or None)

    def _handle_sub(self, ff, node, env):
        return ff.subtract(env[node.input[0]], env[node.input[1]],
                           name=node.name or None)

    def _handle_mul(self, ff, node, env):
        return ff.multiply(env[node.input[0]], env[node.input[1]],
                           name=node.name or None)

    def _handle_concat(self, ff, node, env):
        at = _attrs(node)
        return ff.concat([env[i] for i in node.input], at.get("axis", 0),
                         name=node.name or None)

    def _handle_split(self, ff, node, env):
        at = _attrs(node)
        sizes = at.get("split")
        if sizes is None:
            sizes = len(node.output)
        return ff.split(env[node.input[0]], list(sizes)
                        if not isinstance(sizes, int) else sizes,
                        at.get("axis", 0), name=node.name or None)

    def _handle_flatten(self, ff, node, env):
        return ff.flat(env[node.input[0]], name=node.name or None)

    def _handle_reshape(self, ff, node, env):
        shape = env[node.input[1]]
        return ff.reshape(env[node.input[0]], [int(s) for s in shape],
                          name=node.name or None)

    def _handle_transpose(self, ff, node, env):
        at = _attrs(node)
        return ff.transpose(env[node.input[0]], list(at["perm"]),
                            name=node.name or None)

    def _handle_dropout(self, ff, node, env):
        at = _attrs(node)
        return ff.dropout(env[node.input[0]], at.get("ratio", 0.5),
                          name=node.name or None)

    def _handle_identity(self, ff, node, env):
        return env[node.input[0]]


def onnx_to_flexflow(path_or_model, ff: FFModel,
                     inputs: Sequence[ParallelTensor]):
    m = ONNXModel(path_or_model)
    return m, m.apply(ff, inputs)
