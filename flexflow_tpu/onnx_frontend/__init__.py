"""ONNX frontend (gated on the `onnx` package).

Reference: python/flexflow/onnx/model.py (375 LoC) — a protobuf walk
lowering ONNX nodes to FFModel layer calls.
"""
from .model import ONNXModel, onnx_to_flexflow

__all__ = ["ONNXModel", "onnx_to_flexflow"]
