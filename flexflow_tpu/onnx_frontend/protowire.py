"""Minimal protobuf wire-format codec for ONNX ModelProto.

The reference's importer (python/flexflow/onnx/model.py) depends on the
`onnx` package; this image does not bake it in, which previously left
the whole handler table unexecutable.  ONNX's serialization is plain
protobuf, and the importer touches only a small, stable slice of the
schema (onnx/onnx.proto, field numbers fixed by the spec since IR v3):

  ModelProto.graph=7; GraphProto.node=1/.initializer=5/.input=11/
  .output=12; NodeProto.input=1/.output=2/.name=3/.op_type=4/
  .attribute=5; AttributeProto.name=1/f=2/i=3/s=4/t=5/floats=7/ints=8/
  strings=9/type=20; TensorProto.dims=1/data_type=2/float_data=4/
  int32_data=5/int64_data=7/name=8/raw_data=9/double_data=10;
  ValueInfoProto.name=1.

So this module decodes exactly that slice from raw wire bytes (varint /
64-bit / length-delimited / 32-bit records) into plain Python objects
with the same attribute surface the handlers use, plus a tiny encoder
for building fixture graphs in tests.  When the real `onnx` package is
present the frontend prefers it; this is the no-dependency fallback.
"""
from __future__ import annotations

import dataclasses
import struct
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

# TensorProto.DataType (onnx.proto enum, spec-frozen)
_DTYPES = {
    1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16, 5: np.int16,
    6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64,
    12: np.uint32, 13: np.uint64,
}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


# ---------------------------------------------------------------------------
# wire-level primitives
# ---------------------------------------------------------------------------

def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        if i >= len(buf):
            raise ValueError("truncated protobuf: varint runs past end")
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) records.  A record whose
    payload runs past the buffer raises ValueError instead of silently
    yielding a short slice (truncated/corrupt file)."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        field, wt = key >> 3, key & 7
        if wt == 0:  # varint
            v, i = _read_varint(buf, i)
        elif wt == 1:  # 64-bit
            v, i = buf[i:i + 8], i + 8
        elif wt == 2:  # length-delimited
            ln, i = _read_varint(buf, i)
            v, i = buf[i:i + ln], i + ln
        elif wt == 5:  # 32-bit
            v, i = buf[i:i + 4], i + 4
        else:
            raise ValueError(f"unsupported wire type {wt} (field {field})")
        if i > n:
            raise ValueError("truncated protobuf: record runs past end")
        yield field, wt, v


def _packed_varints(v: bytes) -> List[int]:
    out, i = [], 0
    while i < len(v):
        x, i = _read_varint(v, i)
        out.append(x)
    return out


def _signed(x: int) -> int:
    """Protobuf int64 varints are two's-complement."""
    return x - (1 << 64) if x >= (1 << 63) else x


# ---------------------------------------------------------------------------
# decoded objects (attribute surface mirrors the onnx package's)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Attribute:
    name: str = ""
    value: object = None


@dataclasses.dataclass
class Node:
    op_type: str = ""
    name: str = ""
    input: List[str] = dataclasses.field(default_factory=list)
    output: List[str] = dataclasses.field(default_factory=list)
    attribute: List[Attribute] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Tensor:
    name: str = ""
    array: Optional[np.ndarray] = None


@dataclasses.dataclass
class ValueInfo:
    name: str = ""
    # static dims from TypeProto.tensor_type.shape (None = symbolic)
    shape: Optional[List[Optional[int]]] = None


@dataclasses.dataclass
class GraphDef:
    node: List[Node] = dataclasses.field(default_factory=list)
    initializer: List[Tensor] = dataclasses.field(default_factory=list)
    input: List[ValueInfo] = dataclasses.field(default_factory=list)
    output: List[ValueInfo] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ModelDef:
    graph: GraphDef = dataclasses.field(default_factory=GraphDef)


def _parse_tensor(buf: bytes) -> Tensor:
    dims: List[int] = []
    dtype = 1
    raw = None
    floats: List[float] = []
    int32s: List[int] = []
    int64s: List[int] = []
    doubles: List[float] = []
    name = ""
    for field, wt, v in _fields(buf):
        if field == 1:
            dims.extend(_packed_varints(v) if wt == 2 else [v])
        elif field == 2:
            dtype = v
        elif field == 4:  # float_data (packed floats)
            floats.extend(struct.unpack(f"<{len(v) // 4}f", v)
                          if wt == 2 else struct.unpack("<f", v))
        elif field == 5:
            int32s.extend(_packed_varints(v) if wt == 2 else [v])
        elif field == 7:
            vals = _packed_varints(v) if wt == 2 else [v]
            int64s.extend(_signed(x) for x in vals)
        elif field == 8:
            name = v.decode()
        elif field == 9:
            raw = v
        elif field == 10:
            doubles.extend(struct.unpack(f"<{len(v) // 8}d", v)
                           if wt == 2 else struct.unpack("<d", v))
    np_dtype = _DTYPES.get(dtype)
    if np_dtype is None:
        raise ValueError(f"unsupported TensorProto data_type {dtype}")
    if raw is not None:
        arr = np.frombuffer(raw, dtype=np_dtype)
    elif floats:
        arr = np.asarray(floats, dtype=np_dtype)
    elif doubles:
        arr = np.asarray(doubles, dtype=np_dtype)
    elif int64s:
        arr = np.asarray(int64s, dtype=np_dtype)
    elif int32s:
        # int32_data is the spec container for int8/16/32 AND float16:
        # values are 32-bit two's complement varints (sign-convert),
        # except float16 which is bit-packed in the low 16 bits
        vals = [v & 0xFFFFFFFF for v in int32s]
        if np_dtype == np.float16:
            arr = np.asarray(vals, dtype=np.uint32).astype(
                np.uint16
            ).view(np.float16)
        else:
            signed = [v - (1 << 32) if v >= (1 << 31) else v for v in vals]
            arr = np.asarray(signed, dtype=np.int64).astype(np_dtype)
    else:
        arr = np.zeros(0, dtype=np_dtype)
    if dims or arr.size == 1:
        arr = arr.reshape(dims)  # [] -> 0-d scalar, like numpy_helper
    return Tensor(name=name, array=arr)


def _parse_attribute(buf: bytes) -> Attribute:
    a = Attribute()
    atype = 0
    f = i64 = s = t = None
    floats: List[float] = []
    ints: List[int] = []
    strings: List[bytes] = []
    for field, wt, v in _fields(buf):
        if field == 1:
            a.name = v.decode()
        elif field == 2:
            f = struct.unpack("<f", v)[0]
        elif field == 3:
            i64 = _signed(v)
        elif field == 4:
            s = v
        elif field == 5:
            t = _parse_tensor(v)
        elif field == 7:
            floats.extend(struct.unpack(f"<{len(v) // 4}f", v)
                          if wt == 2 else struct.unpack("<f", v))
        elif field == 8:
            vals = _packed_varints(v) if wt == 2 else [v]
            ints.extend(_signed(x) for x in vals)
        elif field == 9:
            strings.append(v)
        elif field == 20:
            atype = v
    # AttributeProto.AttributeType: FLOAT=1 INT=2 STRING=3 TENSOR=4
    # FLOATS=6 INTS=7 STRINGS=8; infer when the writer omitted type
    if atype == 1 or (atype == 0 and f is not None):
        a.value = f
    elif atype == 2 or (atype == 0 and i64 is not None):
        a.value = i64
    elif atype == 3 or (atype == 0 and s is not None):
        # bytes, matching onnx.helper.get_attribute_value: handlers see
        # the same type whichever parser decoded the model
        a.value = s
    elif atype == 4 or (atype == 0 and t is not None):
        a.value = t.array
    elif atype == 6 or (atype == 0 and floats):
        a.value = list(floats)
    elif atype == 7 or (atype == 0 and ints):
        a.value = list(ints)
    elif atype == 8 or (atype == 0 and strings):
        a.value = list(strings)  # bytes, like the onnx package
    return a


def _parse_node(buf: bytes) -> Node:
    n = Node()
    for field, wt, v in _fields(buf):
        if field == 1:
            n.input.append(v.decode())
        elif field == 2:
            n.output.append(v.decode())
        elif field == 3:
            n.name = v.decode()
        elif field == 4:
            n.op_type = v.decode()
        elif field == 5:
            n.attribute.append(_parse_attribute(v))
    return n


def _parse_value_info(buf: bytes) -> ValueInfo:
    vi = ValueInfo()
    for field, wt, v in _fields(buf):
        if field == 1:
            vi.name = v.decode()
        elif field == 2:  # TypeProto
            for f2, _, v2 in _fields(v):
                if f2 == 1:  # TypeProto.Tensor
                    for f3, _, v3 in _fields(v2):
                        if f3 == 2:  # TensorShapeProto
                            dims: List[Optional[int]] = []
                            for f4, _, v4 in _fields(v3):
                                if f4 == 1:  # Dimension
                                    dv: Optional[int] = None
                                    for f5, _, v5 in _fields(v4):
                                        if f5 == 1:  # dim_value
                                            dv = v5
                                    dims.append(dv)
                            vi.shape = dims
    return vi


def _parse_graph(buf: bytes) -> GraphDef:
    g = GraphDef()
    for field, wt, v in _fields(buf):
        if field == 1:
            g.node.append(_parse_node(v))
        elif field == 5:
            g.initializer.append(_parse_tensor(v))
        elif field == 11:
            g.input.append(_parse_value_info(v))
        elif field == 12:
            g.output.append(_parse_value_info(v))
    return g


def load_model(src: Union[str, bytes]) -> ModelDef:
    """Parse a serialized ONNX ModelProto (path or bytes)."""
    if isinstance(src, str):
        with open(src, "rb") as fh:
            src = fh.read()
    m = ModelDef()
    for field, wt, v in _fields(src):
        if field == 7:
            m.graph = _parse_graph(v)
    return m


# ---------------------------------------------------------------------------
# encoder (fixture building / export)
# ---------------------------------------------------------------------------

def _varint(x: int) -> bytes:
    out = bytearray()
    x &= (1 << 64) - 1
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _ld(field: int, payload: bytes) -> bytes:
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


def _vi(field: int, value: int) -> bytes:
    return _varint(field << 3) + _varint(value)


def encode_tensor(name: str, arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    code = _DTYPE_CODES.get(arr.dtype)
    if code is None:
        raise ValueError(f"unsupported dtype {arr.dtype}")
    out = b"".join(_vi(1, d) for d in arr.shape)
    out += _vi(2, code)
    out += _ld(8, name.encode())
    out += _ld(9, arr.tobytes())
    return out


def encode_attribute(name: str, value) -> bytes:
    out = _ld(1, name.encode())
    if isinstance(value, float):
        out += _varint((2 << 3) | 5) + struct.pack("<f", value) + _vi(20, 1)
    elif isinstance(value, (bool, int, np.integer)):
        out += _vi(3, int(value)) + _vi(20, 2)
    elif isinstance(value, str):
        out += _ld(4, value.encode()) + _vi(20, 3)
    elif isinstance(value, np.ndarray):
        out += _ld(5, encode_tensor(name, value)) + _vi(20, 4)
    elif isinstance(value, (list, tuple)) and value \
            and isinstance(value[0], float):
        for f in value:
            out += _varint((7 << 3) | 5) + struct.pack("<f", f)
        out += _vi(20, 6)
    elif isinstance(value, (list, tuple)):
        for i in value:
            out += _vi(8, int(i))
        out += _vi(20, 7)
    else:
        raise ValueError(f"unsupported attribute value {value!r}")
    return out


def encode_node(op_type: str, inputs, outputs, name: str = "",
                **attrs) -> bytes:
    out = b"".join(_ld(1, s.encode()) for s in inputs)
    out += b"".join(_ld(2, s.encode()) for s in outputs)
    if name:
        out += _ld(3, name.encode())
    out += _ld(4, op_type.encode())
    for k, v in attrs.items():
        out += _ld(5, encode_attribute(k, v))
    return out


def _encode_value_info(name: str, shape=None) -> bytes:
    out = _ld(1, name.encode())
    if shape is not None:
        dims = b"".join(
            _ld(1, _vi(1, int(d)) if d is not None else b"") for d in shape
        )
        # TypeProto{ tensor_type{ elem_type=FLOAT, shape{dims} } }
        tensor_type = _vi(1, 1) + _ld(2, dims)
        out += _ld(2, _ld(1, tensor_type))
    return out


def encode_model(nodes: List[bytes], inputs, outputs,
                 initializers: Dict[str, np.ndarray]) -> bytes:
    """inputs/outputs: names, or (name, shape) pairs to record static
    tensor shapes (what InferenceEngine.from_onnx reads)."""

    def vi_bytes(entry) -> bytes:
        if isinstance(entry, str):
            return _encode_value_info(entry)
        return _encode_value_info(entry[0], entry[1])

    g = b"".join(_ld(1, n) for n in nodes)
    g += b"".join(
        _ld(5, encode_tensor(k, v)) for k, v in initializers.items()
    )
    g += b"".join(_ld(11, vi_bytes(s)) for s in inputs)
    g += b"".join(_ld(12, vi_bytes(s)) for s in outputs)
    return _vi(1, 8) + _ld(7, g)  # ir_version=8, graph
