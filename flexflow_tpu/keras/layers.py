"""Keras-style layers: symbolic graph nodes lowered to FFModel calls.

Reference: python/flexflow/keras/layers/** (core.py Dense/Flatten/
Dropout, convolutional.py Conv2D/pooling, merge.py Add/Concatenate,
normalization.py) — each reference layer wraps an FFModel method; same
mapping here via each layer's `lower(ff, inputs)`.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple, Union

from ..fftype import ActiMode

_ACTIVATIONS = {
    None: ActiMode.NONE,
    "linear": ActiMode.NONE,
    "relu": ActiMode.RELU,
    "sigmoid": ActiMode.SIGMOID,
    "tanh": ActiMode.TANH,
    "gelu": ActiMode.GELU,
}


def _act(activation) -> ActiMode:
    if isinstance(activation, ActiMode):
        return activation
    if activation in _ACTIVATIONS:
        return _ACTIVATIONS[activation]
    raise ValueError(f"unknown activation {activation!r}")


class KTensor:
    """Symbolic tensor flowing between keras layers."""

    def __init__(self, shape: Tuple[int, ...], dtype: str = "float32",
                 producer=None, producer_idx: int = 0):
        self.shape = tuple(shape)  # (batch?, ...) — batch dim excluded
        self.dtype = dtype
        self.producer = producer  # (_Node) or None for Input
        self.producer_idx = producer_idx


def Input(shape: Sequence[int], dtype: str = "float32", name: Optional[str] = None):
    """Functional-API entry point: a batchless-shape placeholder."""
    t = KTensor(tuple(shape), dtype)
    t.name = name
    t.is_input = True
    return t


class _Node:
    def __init__(self, layer: "Layer", inputs: List[KTensor]):
        self.layer = layer
        self.inputs = inputs


class Layer:
    """Base layer: calling it on KTensors records a graph node."""

    _count = [0]

    def __init__(self, name: Optional[str] = None):
        if name is None:
            Layer._count[0] += 1
            name = f"{type(self).__name__.lower()}_{Layer._count[0]}"
        self.name = name

    def __call__(self, inputs):
        single = not isinstance(inputs, (list, tuple))
        ins = [inputs] if single else list(inputs)
        node = _Node(self, ins)
        out_shapes = self.compute_output_shape([t.shape for t in ins])
        outs = [
            KTensor(s, ins[0].dtype, producer=node, producer_idx=i)
            for i, s in enumerate(out_shapes)
        ]
        node.outputs = outs
        return outs[0] if len(outs) == 1 else outs

    # -- to override -----------------------------------------------------
    def compute_output_shape(self, input_shapes):
        return [input_shapes[0]]

    def lower(self, ff, inputs):
        raise NotImplementedError


class Dense(Layer):
    def __init__(self, units: int, activation=None, use_bias: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.units = units
        # Keras allows activation="softmax" on Dense; it is a separate
        # op here (reference convention: model ends in a Softmax op)
        self.softmax = activation == "softmax"
        self.activation = _act(None if self.softmax else activation)
        self.use_bias = use_bias

    def compute_output_shape(self, input_shapes):
        return [tuple(input_shapes[0][:-1]) + (self.units,)]

    def lower(self, ff, inputs):
        out = ff.dense(inputs[0], self.units, activation=self.activation,
                       use_bias=self.use_bias, name=self.name)
        if self.softmax:
            out = ff.softmax(out)
        return out


def _pair(v) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


class Conv2D(Layer):
    """channels_first (NCHW): input shape (C, H, W)."""

    def __init__(self, filters: int, kernel_size, strides=(1, 1),
                 padding: str = "valid", activation=None,
                 use_bias: bool = True, groups: int = 1,
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = filters
        self.kernel = _pair(kernel_size)
        self.strides = _pair(strides)
        assert padding in ("valid", "same")
        self.padding = padding
        self.activation = _act(activation)
        self.use_bias = use_bias
        self.groups = groups

    def _pads(self, h, w):
        if self.padding == "valid":
            return 0, 0
        # 'same' with stride 1: symmetric padding (stride>1 'same' needs
        # asymmetric pads — reject to stay exact)
        assert self.strides == (1, 1), "'same' padding requires stride 1"
        return (self.kernel[0] - 1) // 2, (self.kernel[1] - 1) // 2

    def compute_output_shape(self, input_shapes):
        c, h, w = input_shapes[0]
        ph, pw = self._pads(h, w)
        oh = (h + 2 * ph - self.kernel[0]) // self.strides[0] + 1
        ow = (w + 2 * pw - self.kernel[1]) // self.strides[1] + 1
        return [(self.filters, oh, ow)]

    def lower(self, ff, inputs):
        h, w = inputs[0].shape.logical_shape[2:4]
        ph, pw = self._pads(h, w)
        return ff.conv2d(
            inputs[0], self.filters, self.kernel[0], self.kernel[1],
            self.strides[0], self.strides[1], ph, pw,
            activation=self.activation, groups=self.groups,
            use_bias=self.use_bias, name=self.name,
        )


class _Pool2D(Layer):
    kind = "max"

    def __init__(self, pool_size=(2, 2), strides=None, padding: str = "valid",
                 name: Optional[str] = None):
        super().__init__(name)
        self.pool = _pair(pool_size)
        self.strides = _pair(strides) if strides is not None else self.pool
        assert padding == "valid", "pooling supports 'valid' padding"

    def compute_output_shape(self, input_shapes):
        c, h, w = input_shapes[0]
        oh = (h - self.pool[0]) // self.strides[0] + 1
        ow = (w - self.pool[1]) // self.strides[1] + 1
        return [(c, oh, ow)]

    def lower(self, ff, inputs):
        return ff.pool2d(inputs[0], self.pool[0], self.pool[1],
                         self.strides[0], self.strides[1], 0, 0,
                         pool_type=self.kind, name=self.name)


class MaxPooling2D(_Pool2D):
    kind = "max"


class AveragePooling2D(_Pool2D):
    kind = "avg"


class Flatten(Layer):
    def compute_output_shape(self, input_shapes):
        n = 1
        for s in input_shapes[0]:
            n *= s
        return [(n,)]

    def lower(self, ff, inputs):
        return ff.flat(inputs[0], name=self.name)


class Dropout(Layer):
    def __init__(self, rate: float, name: Optional[str] = None):
        super().__init__(name)
        self.rate = rate

    def lower(self, ff, inputs):
        return ff.dropout(inputs[0], self.rate, name=self.name)


class Embedding(Layer):
    def __init__(self, input_dim: int, output_dim: int,
                 input_length: Optional[int] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.input_length = input_length

    def compute_output_shape(self, input_shapes):
        return [tuple(input_shapes[0]) + (self.output_dim,)]

    def lower(self, ff, inputs):
        return ff.embedding(inputs[0], self.input_dim, self.output_dim,
                            name=self.name)


class LSTM(Layer):
    """Keras-style LSTM over (seq, features) inputs (batch excluded from
    shapes per KTensor convention); wraps the fused lax.scan LSTM op
    (ops/recurrent.py) — goes beyond the reference's Keras frontend,
    which never exposed its legacy nmt/ LSTM."""

    def __init__(self, units: int, return_sequences: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.units = units
        self.return_sequences = return_sequences

    def compute_output_shape(self, input_shapes):
        (shape,) = input_shapes
        if len(shape) != 2:
            raise ValueError(f"LSTM expects (seq, features), got {shape}")
        if self.return_sequences:
            return [(shape[0], self.units)]
        return [(self.units,)]

    def lower(self, ff, inputs):
        return ff.lstm(inputs[0], self.units,
                       return_sequences=self.return_sequences, name=self.name)


class Activation(Layer):
    def __init__(self, activation, name: Optional[str] = None):
        super().__init__(name)
        self.activation = activation

    def lower(self, ff, inputs):
        x = inputs[0]
        if self.activation == "softmax":
            return ff.softmax(x, name=self.name)
        act = _act(self.activation)
        fn = {ActiMode.RELU: ff.relu, ActiMode.SIGMOID: ff.sigmoid,
              ActiMode.TANH: ff.tanh, ActiMode.GELU: ff.gelu,
              ActiMode.NONE: ff.identity}[act]
        return fn(x, name=self.name)


class BatchNormalization(Layer):
    def __init__(self, relu: bool = False, name: Optional[str] = None):
        super().__init__(name)
        self.relu = relu

    def lower(self, ff, inputs):
        return ff.batch_norm(inputs[0], relu=self.relu, name=self.name)


class LayerNormalization(Layer):
    def __init__(self, epsilon: float = 1e-5, name: Optional[str] = None):
        super().__init__(name)
        self.epsilon = epsilon

    def lower(self, ff, inputs):
        rank = inputs[0].shape.logical_rank
        return ff.layer_norm(inputs[0], [rank - 1], eps=self.epsilon,
                             name=self.name)


class Reshape(Layer):
    def __init__(self, target_shape: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.target = tuple(target_shape)

    def compute_output_shape(self, input_shapes):
        return [self.target]

    def lower(self, ff, inputs):
        batch = inputs[0].shape.logical_shape[0]
        return ff.reshape(inputs[0], (batch,) + self.target, name=self.name)


class Permute(Layer):
    def __init__(self, dims: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.dims = tuple(dims)  # keras convention: 1-indexed, no batch

    def compute_output_shape(self, input_shapes):
        s = input_shapes[0]
        return [tuple(s[d - 1] for d in self.dims)]

    def lower(self, ff, inputs):
        perm = (0,) + tuple(d for d in self.dims)
        return ff.transpose(inputs[0], perm, name=self.name)


class _Merge(Layer):
    def compute_output_shape(self, input_shapes):
        return [input_shapes[0]]


class Add(_Merge):
    def lower(self, ff, inputs):
        out = inputs[0]
        for t in inputs[1:]:
            out = ff.add(out, t, name=None)
        return out


class Subtract(_Merge):
    def lower(self, ff, inputs):
        assert len(inputs) == 2
        return ff.subtract(inputs[0], inputs[1], name=self.name)


class Multiply(_Merge):
    def lower(self, ff, inputs):
        out = inputs[0]
        for t in inputs[1:]:
            out = ff.multiply(out, t, name=None)
        return out


class Concatenate(Layer):
    def __init__(self, axis: int = -1, name: Optional[str] = None):
        super().__init__(name)
        self.axis = axis

    def compute_output_shape(self, input_shapes):
        # Keras axes are batch-INCLUSIVE; KTensor shapes exclude batch,
        # so batch-inclusive axis k maps to shape index k-1.
        full_rank = len(input_shapes[0]) + 1
        axis = self.axis if self.axis >= 0 else full_rank + self.axis
        if axis == 0:
            raise ValueError("Concatenate along the batch axis is not supported")
        out = list(input_shapes[0])
        out[axis - 1] = sum(s[axis - 1] for s in input_shapes)
        return [tuple(out)]

    def lower(self, ff, inputs):
        # FFModel axes include batch, matching Keras's convention directly
        return ff.concat(inputs, self.axis, name=self.name)
