"""Keras-style frontend.

Reference: python/flexflow/keras/** (~3.5k LoC) — Sequential +
functional `Model` over FlexFlow (models/base_model.py:31-541 with
compile/fit), layer classes, callbacks.  Same surface here, built as a
thin adapter that lowers the layer graph onto an FFModel at compile
time, so every keras-frontend model gets the full strategy search +
SPMD execution path.

Layout convention follows the reference's keras port: image tensors are
channels_first (NCHW), matching FFModel.conv2d.
"""
from .callbacks import (
    Callback,
    EarlyStopping,
    LearningRateScheduler,
    ProgbarLogger,
    VerifyMetrics,
)
from .layers import (
    Activation,
    Add,
    AveragePooling2D,
    BatchNormalization,
    Concatenate,
    Conv2D,
    Dense,
    Dropout,
    Embedding,
    Flatten,
    Input,
    LayerNormalization,
    LSTM,
    MaxPooling2D,
    Multiply,
    Permute,
    Reshape,
    Subtract,
)
from .models import Model, Sequential
from . import datasets, preprocessing

__all__ = [
    "Activation", "Add", "AveragePooling2D", "BatchNormalization",
    "Callback", "Concatenate", "Conv2D", "Dense", "Dropout",
    "EarlyStopping", "Embedding", "Flatten", "Input",
    "LayerNormalization", "LearningRateScheduler", "LSTM", "MaxPooling2D",
    "Model", "Multiply", "Permute", "ProgbarLogger", "Reshape",
    "Sequential", "Subtract", "VerifyMetrics", "datasets", "preprocessing",
]
