"""Keras-style Sequential + functional Model.

Reference: python/flexflow/keras/models/base_model.py:31-541 — compile
creates the FFModel/optimizer/loss/metrics, fit runs the training loop.
Here compile() lowers the recorded layer graph into an FFModel (running
the strategy search per FFConfig) and fit/evaluate/predict delegate to
the FFModel training surface.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from ..config import FFConfig
from ..fftype import LossType, MetricsType
from ..model import FFModel
from ..optimizer import AdamOptimizer, Optimizer, SGDOptimizer
from .layers import Input, KTensor, Layer, _Node

_LOSSES = {
    "categorical_crossentropy": LossType.CATEGORICAL_CROSSENTROPY,
    "sparse_categorical_crossentropy": LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
    "mse": LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
}

_METRICS = {
    "accuracy": MetricsType.ACCURACY,
    "sparse_categorical_crossentropy": MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY,
    "categorical_crossentropy": MetricsType.CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": MetricsType.MEAN_SQUARED_ERROR,
    "mse": MetricsType.MEAN_SQUARED_ERROR,
    "mean_absolute_error": MetricsType.MEAN_ABSOLUTE_ERROR,
    "mae": MetricsType.MEAN_ABSOLUTE_ERROR,
}

_OPTIMIZERS = {
    "sgd": lambda: SGDOptimizer(lr=0.01),
    "adam": lambda: AdamOptimizer(alpha=0.001),
}


class _BaseModel:
    def __init__(self, config: Optional[FFConfig] = None, name: str = "model"):
        self.name = name
        self.config = config
        self.ffmodel: Optional[FFModel] = None
        self._inputs: List[KTensor] = []
        self._outputs: List[KTensor] = []

    # ------------------------------------------------------------------
    def compile(
        self,
        optimizer: Union[str, Optimizer] = "sgd",
        loss: Union[str, LossType] = "sparse_categorical_crossentropy",
        metrics: Sequence[Union[str, MetricsType]] = ("accuracy",),
        batch_size: Optional[int] = None,
        devices: Optional[Sequence] = None,
    ):
        cfg = self.config or FFConfig()
        if batch_size is not None:
            cfg.batch_size = batch_size
        ff = FFModel(cfg)
        # lower the symbolic graph in dependency order
        tensor_map: Dict[int, object] = {}
        for kt in self._inputs:
            dims = [cfg.batch_size] + list(kt.shape)
            tensor_map[id(kt)] = ff.create_tensor(
                dims, dtype=kt.dtype, name=getattr(kt, "name", None)
            )

        def lower(kt: KTensor):
            if id(kt) in tensor_map:
                return tensor_map[id(kt)]
            node: _Node = kt.producer
            assert node is not None, "disconnected tensor (missing Input?)"
            ins = [lower(t) for t in node.inputs]
            result = node.layer.lower(ff, ins)
            outs = result if isinstance(result, (tuple, list)) else [result]
            for out_kt, ff_t in zip(node.outputs, outs):
                tensor_map[id(out_kt)] = ff_t
            return tensor_map[id(kt)]

        for out in self._outputs:
            lower(out)

        if isinstance(optimizer, str):
            optimizer = _OPTIMIZERS[optimizer.lower()]()
        if isinstance(loss, str):
            loss = _LOSSES[loss.lower()]
        metrics = [
            _METRICS[m.lower()] if isinstance(m, str) else m for m in metrics
        ]
        ff.compile(optimizer=optimizer, loss_type=loss, metrics=metrics,
                   devices=devices)
        self.ffmodel = ff
        return self

    # ------------------------------------------------------------------
    def fit(self, x, y, batch_size: Optional[int] = None,
            epochs: int = 1, callbacks: Sequence = (), verbose: bool = True):
        assert self.ffmodel is not None, "call compile() first"
        return self.ffmodel.fit(
            x, y, batch_size=batch_size, epochs=epochs,
            callbacks=[_adapt(cb, self) for cb in callbacks],
            verbose=verbose,
        )

    def evaluate(self, x, y, batch_size: Optional[int] = None):
        assert self.ffmodel is not None
        bs = batch_size or self.ffmodel.config.batch_size
        input_ops = self.ffmodel.layers.source_ops()
        xs = x if isinstance(x, dict) else {input_ops[0].name: x}
        n = len(y) // bs
        out = []
        for b in range(n):
            sl = slice(b * bs, (b + 1) * bs)
            out.append(self.ffmodel.eval_step(
                {k: v[sl] for k, v in xs.items()}, y[sl]
            ))
        return out

    def predict(self, x, batch_size: Optional[int] = None):
        assert self.ffmodel is not None
        input_ops = self.ffmodel.layers.source_ops()
        xs = x if isinstance(x, dict) else {input_ops[0].name: x}
        return np.asarray(self.ffmodel.forward(xs))

    def summary(self) -> str:
        lines = [f'Model: "{self.name}"', "_" * 60]
        seen = []

        def walk(kt):
            node = kt.producer
            if node is None or node in seen:
                return
            for t in node.inputs:
                walk(t)
            seen.append(node)
            lines.append(
                f"{node.layer.name:<30}{type(node.layer).__name__:<20}"
                f"{node.outputs[0].shape}"
            )

        for out in self._outputs:
            walk(out)
        return "\n".join(lines)


class Model(_BaseModel):
    """Functional API: Model(inputs=..., outputs=...)."""

    def __init__(self, inputs, outputs, config: Optional[FFConfig] = None,
                 name: str = "model"):
        super().__init__(config, name)
        self._inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self._inputs = list(self._inputs)
        self._outputs = (
            list(outputs) if isinstance(outputs, (list, tuple)) else [outputs]
        )


class Sequential(_BaseModel):
    """Stacked layers (reference keras Sequential)."""

    def __init__(self, layers: Optional[Sequence[Layer]] = None,
                 input_shape: Optional[Sequence[int]] = None,
                 config: Optional[FFConfig] = None, name: str = "sequential"):
        super().__init__(config, name)
        self._layers: List[Layer] = []
        self._input_shape = tuple(input_shape) if input_shape else None
        for l in layers or []:
            self.add(l)

    def add(self, layer: Layer):
        self._layers.append(layer)
        return self

    def compile(self, *args, input_shape: Optional[Sequence[int]] = None,
                **kwargs):
        shape = tuple(input_shape) if input_shape else self._input_shape
        from .layers import Embedding

        dtype = "float32"
        first = self._layers[0] if self._layers else None
        if isinstance(first, Embedding):
            # Keras convention: Embedding-first models take int token ids;
            # input_length supplies the shape when none was given
            dtype = "int32"
            if shape is None and first.input_length is not None:
                shape = (first.input_length,)
        assert shape is not None, (
            "Sequential needs input_shape (constructor or compile kwarg, "
            "or Embedding(input_length=...))"
        )
        x = Input(shape, dtype=dtype)
        self._inputs = [x]
        t = x
        for l in self._layers:
            t = l(t)
        self._outputs = [t]
        return super().compile(*args, **kwargs)


def _adapt(cb, keras_model):
    """Expose the keras model on callbacks that expect `.model`."""
    cb.model = keras_model
    return cb
