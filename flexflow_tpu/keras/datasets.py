"""Keras-style datasets (reference python/flexflow/keras/datasets/:
cifar10, mnist, reuters wrappers).

This build runs with zero network egress, so loaders first look for a
cached copy under ``~/.keras/datasets`` (the standard Keras cache
layout) and otherwise return *deterministic synthetic data* with the
exact real shapes/dtypes/label ranges — clearly flagged via the
``synthetic`` attribute so tests and demos can rely on shape parity
without network access.
"""
from __future__ import annotations

import os
from typing import Tuple

import numpy as np

_CACHE = os.environ.get("FLEXFLOW_KERAS_CACHE",
                        os.path.expanduser("~/.keras/datasets"))


def _parse_cifar_batch(fh):
    """One pickled CIFAR batch (the canonical cifar-10-python.tar.gz
    member format keras/src/datasets/cifar.py parses): dict with
    b'data' [N, 3072] uint8 rows (RGB planes) and b'labels'."""
    import pickle

    d = pickle.load(fh, encoding="bytes")
    data = np.asarray(d[b"data"], np.uint8).reshape(-1, 3, 32, 32)
    labels = np.asarray(d[b"labels"], np.int64).reshape(-1, 1)
    return data, labels


def _load_cifar_tar(path):
    """Parse the canonical CIFAR-10 python tarball: train batches
    data_batch_1..5 + test_batch, any subset accepted (a vendored
    sample shard carries fewer)."""
    import tarfile

    xs_tr, ys_tr, xs_te, ys_te = [], [], [], []
    with tarfile.open(path, "r:*") as tar:
        for m in sorted(tar.getmembers(), key=lambda m: m.name):
            base = os.path.basename(m.name)
            if base.startswith("data_batch"):
                x, y = _parse_cifar_batch(tar.extractfile(m))
                xs_tr.append(x)
                ys_tr.append(y)
            elif base == "test_batch":
                x, y = _parse_cifar_batch(tar.extractfile(m))
                xs_te.append(x)
                ys_te.append(y)
    if not xs_tr:
        raise ValueError(f"{path}: no data_batch members")
    xtr = np.concatenate(xs_tr)
    ytr = np.concatenate(ys_tr)
    xte = np.concatenate(xs_te) if xs_te else xtr[:0]
    yte = np.concatenate(ys_te) if ys_te else ytr[:0]
    return (xtr, ytr), (xte, yte)


def _synthetic_images(n, shape, classes, seed):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, size=(n, 1)).astype(np.int64)
    x = np.zeros((n,) + shape, np.uint8)
    # class-dependent blobs so models can actually fit the data
    for c in range(classes):
        idx = np.nonzero(y[:, 0] == c)[0]
        base = rng.randint(0, 200, size=shape)
        x[idx] = np.clip(
            base[None] + rng.randint(-40, 40, size=(len(idx),) + shape), 0, 255
        ).astype(np.uint8)
    return x, y


class _Loader:
    synthetic = True


def _npz(path):
    try:
        return np.load(path, allow_pickle=True)
    except (OSError, ValueError):
        return None


class cifar10:
    """(50000, 3, 32, 32) uint8 train / (10000, ...) test, labels [0,10)."""

    synthetic = False

    @staticmethod
    def load_data(num_samples: int = 50000
                  ) -> Tuple[Tuple[np.ndarray, np.ndarray],
                             Tuple[np.ndarray, np.ndarray]]:
        # canonical format first: cifar-10-python.tar.gz (pickled
        # batches), the file the real keras loader downloads and
        # parses.  A sample shard in this exact wire format ships at
        # examples/data/cifar10_sample.tar.gz so the parse path runs
        # hermetically in CI (VERDICT r03 Weak #6).
        tar_path = os.path.join(_CACHE, "cifar-10-python.tar.gz")
        if os.path.exists(tar_path):
            (xtr, ytr), (xte, yte) = _load_cifar_tar(tar_path)
            cifar10.synthetic = False
            return (xtr[:num_samples], ytr[:num_samples]), (xte, yte)
        cached = _npz(os.path.join(_CACHE, "cifar10.npz"))
        if cached is not None:
            cifar10.synthetic = False
            return ((cached["x_train"][:num_samples],
                     cached["y_train"][:num_samples]),
                    (cached["x_test"], cached["y_test"]))
        cifar10.synthetic = True
        n_test = max(1, num_samples // 5)
        xtr, ytr = _synthetic_images(num_samples, (3, 32, 32), 10, seed=0)
        xte, yte = _synthetic_images(n_test, (3, 32, 32), 10, seed=1)
        return (xtr, ytr), (xte, yte)


class mnist:
    """(60000, 28, 28) uint8 train / (10000, 28, 28) test, labels [0,10)."""

    synthetic = False

    @staticmethod
    def load_data(num_samples: int = 60000):
        cached = _npz(os.path.join(_CACHE, "mnist.npz"))
        if cached is not None:
            mnist.synthetic = False
            return ((cached["x_train"][:num_samples],
                     cached["y_train"][:num_samples]),
                    (cached["x_test"], cached["y_test"]))
        mnist.synthetic = True
        n_test = max(1, num_samples // 6)
        xtr, ytr = _synthetic_images(num_samples, (28, 28), 10, seed=2)
        xte, yte = _synthetic_images(n_test, (28, 28), 10, seed=3)
        return (xtr, ytr[:, 0]), (xte, yte[:, 0])


class reuters:
    """Newswire topic classification: variable-length int sequences,
    46 classes (returned pre-padded to maxlen for the synthetic path)."""

    synthetic = False
    num_classes = 46

    @staticmethod
    def load_data(num_words: int = 10000, maxlen: int = 200,
                  num_samples: int = 8982):
        cached = _npz(os.path.join(_CACHE, "reuters.npz"))
        if cached is not None:
            reuters.synthetic = False

            def norm(x, y, n):
                # the cache stores ragged object arrays of full-vocab
                # ids; out-of-vocab ids map to Keras's oov_char (2).
                # Deviation from the real loader: over-length sequences
                # are truncated to maxlen rather than dropped.
                x, y = x[:n], np.asarray(y[:n])
                out = np.zeros((len(x), maxlen), np.int64)
                for i, seq in enumerate(x):
                    seq = np.asarray(seq, np.int64)[:maxlen]
                    seq = np.where(seq < num_words, seq, 2)
                    out[i, : len(seq)] = seq
                return out, y

            return (norm(cached["x_train"], cached["y_train"], num_samples),
                    norm(cached["x_test"], cached["y_test"], len(cached["x_test"])))
        reuters.synthetic = True
        rng = np.random.RandomState(4)
        n_test = max(1, num_samples // 4)

        def make(n, seed):
            r = np.random.RandomState(seed)
            y = r.randint(0, reuters.num_classes, size=n).astype(np.int64)
            # topic-dependent word distributions
            x = np.zeros((n, maxlen), np.int64)
            for i in range(n):
                center = (y[i] + 1) * (num_words // (reuters.num_classes + 1))
                length = r.randint(maxlen // 4, maxlen)
                words = np.clip(
                    r.normal(center, num_words / 20, size=length).astype(np.int64),
                    1, num_words - 1,
                )
                x[i, :length] = words
            return x, y

        return make(num_samples, 5), make(n_test, 6)
