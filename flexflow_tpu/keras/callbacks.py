"""Training callbacks (reference python/flexflow/keras/callbacks.py:
Callback base, LearningRateScheduler, VerifyMetrics; plus EarlyStopping
as a quality-of-life addition)."""
from __future__ import annotations

from typing import Callable, Optional


class Callback:
    model = None  # set by Model.fit

    def on_train_begin(self, ffmodel):
        pass

    def on_epoch_end(self, ffmodel, epoch: int, metrics):
        pass

    def on_train_end(self, ffmodel):
        pass


class LearningRateScheduler(Callback):
    """schedule(epoch, current_lr) -> new_lr (reference
    callbacks.py LearningRateScheduler)."""

    def __init__(self, schedule: Callable[[int, float], float]):
        self.schedule = schedule

    def on_epoch_end(self, ffmodel, epoch: int, metrics):
        cur = ffmodel.optimizer.get_lr()
        new_lr = self.schedule(epoch + 1, cur)
        if new_lr != cur:
            ffmodel.set_learning_rate(new_lr)


class EarlyStopping(Callback):
    def __init__(self, monitor: str = "accuracy", patience: int = 2,
                 mode: str = "max"):
        self.monitor = monitor
        self.patience = patience
        self.mode = mode
        self.best: Optional[float] = None
        self.wait = 0
        self.stopped_epoch: Optional[int] = None

    def on_epoch_end(self, ffmodel, epoch: int, metrics):
        val = getattr(metrics, self.monitor)
        better = (
            self.best is None
            or (val > self.best if self.mode == "max" else val < self.best)
        )
        if better:
            self.best = val
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = epoch
                ffmodel._stop_training = True
