"""Training callbacks (reference python/flexflow/keras/callbacks.py:
Callback base, LearningRateScheduler, VerifyMetrics/EpochVerifyMetrics;
plus EarlyStopping and ProgbarLogger as quality-of-life additions)."""
from __future__ import annotations

import sys
import time
from typing import Callable, Optional


class Callback:
    model = None  # set by Model.fit

    def on_train_begin(self, ffmodel):
        pass

    def on_epoch_end(self, ffmodel, epoch: int, metrics):
        pass

    def on_train_end(self, ffmodel):
        pass


class LearningRateScheduler(Callback):
    """schedule(epoch, current_lr) -> new_lr (reference
    callbacks.py LearningRateScheduler)."""

    def __init__(self, schedule: Callable[[int, float], float]):
        self.schedule = schedule

    def on_epoch_end(self, ffmodel, epoch: int, metrics):
        cur = ffmodel.optimizer.get_lr()
        new_lr = self.schedule(epoch + 1, cur)
        if new_lr != cur:
            ffmodel.set_learning_rate(new_lr)


class ProgbarLogger(Callback):
    """Per-epoch metrics line (the reference keras port relies on the
    C++ runtime's epoch printout; here it is an explicit callback so
    `verbose=False` fits stay quiet unless asked)."""

    def __init__(self, stream=None):
        self.stream = stream or sys.stderr
        self._t0 = None

    def on_train_begin(self, ffmodel):
        self._t0 = time.perf_counter()

    def on_epoch_end(self, ffmodel, epoch: int, metrics):
        dt = time.perf_counter() - (self._t0 or time.perf_counter())
        print(f"epoch {epoch}: {metrics.summary()} [{dt:.1f}s elapsed]",
              file=self.stream)


class VerifyMetrics(Callback):
    """Assert a metric reaches a floor by the end of training
    (reference callbacks.py VerifyMetrics — its CI example scripts end
    with this check).  `each_epoch=True` is EpochVerifyMetrics: stop
    early once reached, fail only if never reached."""

    def __init__(self, monitor: str = "accuracy", floor: float = 0.9,
                 each_epoch: bool = False):
        self.monitor = monitor
        self.floor = floor
        self.each_epoch = each_epoch
        self._last: Optional[float] = None
        self._reached = False

    def on_train_begin(self, ffmodel):
        # a reused instance must re-verify: stale success from an
        # earlier fit() would mask a failing run
        self._last = None
        self._reached = False

    def on_epoch_end(self, ffmodel, epoch: int, metrics):
        self._last = float(getattr(metrics, self.monitor))
        if self.each_epoch and self._last >= self.floor:
            self._reached = True
            ffmodel._stop_training = True

    def on_train_end(self, ffmodel):
        if self._reached or (self._last is not None
                             and self._last >= self.floor):
            return
        raise AssertionError(
            f"{self.monitor} = {self._last} below required {self.floor}"
        )


class EarlyStopping(Callback):
    def __init__(self, monitor: str = "accuracy", patience: int = 2,
                 mode: str = "max"):
        self.monitor = monitor
        self.patience = patience
        self.mode = mode
        self.best: Optional[float] = None
        self.wait = 0
        self.stopped_epoch: Optional[int] = None

    def on_epoch_end(self, ffmodel, epoch: int, metrics):
        val = getattr(metrics, self.monitor)
        better = (
            self.best is None
            or (val > self.best if self.mode == "max" else val < self.best)
        )
        if better:
            self.best = val
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = epoch
                ffmodel._stop_training = True
