"""Text preprocessing (the keras_preprocessing.text API the reference
re-exports, implemented dependency-free)."""
from __future__ import annotations

import collections
from typing import Dict, List, Optional

import numpy as np

_FILTERS = '!"#$%&()*+,-./:;<=>?@[\\]^_`{|}~\t\n'


def text_to_word_sequence(text: str, filters: str = _FILTERS,
                          lower: bool = True, split: str = " ") -> List[str]:
    if lower:
        text = text.lower()
    text = text.translate(str.maketrans(filters, split * len(filters)))
    return [w for w in text.split(split) if w]


def one_hot(text: str, n: int, filters: str = _FILTERS, lower: bool = True,
            split: str = " ") -> List[int]:
    """Hashing-trick word ids in [1, n) (collisions possible, as in the
    keras original)."""
    words = text_to_word_sequence(text, filters, lower, split)
    return [(hash(w) % (n - 1)) + 1 for w in words]


class Tokenizer:
    """Corpus vocabulary fitting + text -> id-sequence conversion.

    Word index is 1-based (0 is reserved for padding); when `num_words`
    is set, only the num_words-1 most frequent words convert, matching
    the keras contract the reuters/imdb pipelines rely on."""

    def __init__(self, num_words: Optional[int] = None,
                 filters: str = _FILTERS, lower: bool = True,
                 split: str = " ", oov_token: Optional[str] = None):
        self.num_words = num_words
        self.filters = filters
        self.lower = lower
        self.split = split
        self.oov_token = oov_token
        self.word_counts: "collections.OrderedDict[str, int]" = (
            collections.OrderedDict())
        self.word_docs: Dict[str, int] = collections.defaultdict(int)
        self.word_index: Dict[str, int] = {}
        self.index_word: Dict[int, str] = {}
        self.index_docs: Dict[int, int] = {}
        self.document_count = 0

    def fit_on_texts(self, texts):
        for text in texts:
            self.document_count += 1
            words = text_to_word_sequence(text, self.filters, self.lower,
                                          self.split)
            for w in words:
                self.word_counts[w] = self.word_counts.get(w, 0) + 1
            for w in set(words):
                self.word_docs[w] += 1
        by_freq = sorted(self.word_counts.items(),
                         key=lambda kv: (-kv[1], kv[0]))
        vocab = [w for w, _ in by_freq]
        if self.oov_token is not None:
            vocab = [self.oov_token] + vocab
        self.word_index = {w: i + 1 for i, w in enumerate(vocab)}
        self.index_word = {i: w for w, i in self.word_index.items()}
        self.index_docs = {
            self.word_index[w]: c for w, c in self.word_docs.items()
        }

    def _id(self, w: str) -> Optional[int]:
        i = self.word_index.get(w)
        if i is None or (self.num_words and i >= self.num_words):
            if self.oov_token is not None:
                return self.word_index[self.oov_token]
            return None
        return i

    def texts_to_sequences(self, texts) -> List[List[int]]:
        out = []
        for text in texts:
            ids = [self._id(w) for w in text_to_word_sequence(
                text, self.filters, self.lower, self.split)]
            out.append([i for i in ids if i is not None])
        return out

    def texts_to_matrix(self, texts, mode: str = "binary") -> np.ndarray:
        if mode not in ("binary", "count", "freq", "tfidf"):
            raise ValueError(f"unknown mode {mode!r}")
        n = self.num_words or (len(self.word_index) + 1)
        seqs = self.texts_to_sequences(texts)
        m = np.zeros((len(seqs), n), np.float32)
        for r, seq in enumerate(seqs):
            if not seq:
                continue
            counts = collections.Counter(seq)
            for idx, c in counts.items():
                if mode == "binary":
                    m[r, idx] = 1.0
                elif mode == "count":
                    m[r, idx] = c
                elif mode == "freq":
                    m[r, idx] = c / len(seq)
                else:  # tfidf: idf from FIT-TIME document frequencies,
                    # so featurization is batch-independent (keras
                    # semantics: document_count/index_docs at fit)
                    tf = 1.0 + np.log(c)
                    df = self.index_docs.get(idx, 0)
                    m[r, idx] = tf * np.log(
                        1 + self.document_count / (1.0 + df))
        return m
