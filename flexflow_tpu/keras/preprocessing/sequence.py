"""Sequence preprocessing (the keras_preprocessing.sequence API the
reference re-exports at python/flexflow/keras/preprocessing/
sequence.py:8-13, implemented here dependency-free)."""
from __future__ import annotations

import random
from typing import List, Optional, Sequence

import numpy as np


def pad_sequences(sequences, maxlen: Optional[int] = None,
                  dtype: str = "int32", padding: str = "pre",
                  truncating: str = "pre", value=0.0) -> np.ndarray:
    """Pad/truncate a list of token-id lists to a [n, maxlen] array —
    the fixed-shape batch XLA needs (dynamic sequence lengths would
    force one compile per length)."""
    if padding not in ("pre", "post") or truncating not in ("pre", "post"):
        raise ValueError(
            f"padding/truncating must be 'pre' or 'post', got "
            f"{padding!r}/{truncating!r}"
        )
    seqs = [list(s) for s in sequences]
    if maxlen is None:
        maxlen = max((len(s) for s in seqs), default=0)
    out = np.full((len(seqs), maxlen), value, dtype=dtype)
    for i, s in enumerate(seqs):
        if not s:
            continue
        trunc = s[-maxlen:] if truncating == "pre" else s[:maxlen]
        if padding == "pre":
            out[i, maxlen - len(trunc):] = trunc
        else:
            out[i, :len(trunc)] = trunc
    return out


def make_sampling_table(size: int, sampling_factor: float = 1e-5) -> np.ndarray:
    """Word-rank -> keep-probability table for skipgram subsampling
    (Mikolov et al. 2013 frequency-based subsampling under a Zipf
    assumption, the keras_preprocessing formula)."""
    gamma = 0.577
    rank = np.arange(size)
    rank[0] = 1
    inv_fq = rank * (np.log(rank) + gamma) + 0.5 - 1.0 / (12.0 * rank)
    f = sampling_factor * inv_fq
    return np.minimum(1.0, f / np.sqrt(f))


def skipgrams(sequence: Sequence[int], vocabulary_size: int,
              window_size: int = 4, negative_samples: float = 1.0,
              shuffle: bool = True, categorical: bool = False,
              sampling_table: Optional[np.ndarray] = None,
              seed: Optional[int] = None):
    """(couples, labels) skipgram pairs with sampled negatives."""
    couples: List[List[int]] = []
    labels: List = []
    for i, wi in enumerate(sequence):
        if not wi:
            continue
        if sampling_table is not None:
            if sampling_table[wi] < random.random():
                continue
        lo = max(0, i - window_size)
        for j in range(lo, min(len(sequence), i + window_size + 1)):
            if j == i:
                continue
            wj = sequence[j]
            if not wj:
                continue
            couples.append([wi, wj])
            labels.append([0, 1] if categorical else 1)
    if negative_samples > 0:
        num_neg = int(len(labels) * negative_samples)
        words = [c[0] for c in couples]
        random.shuffle(words)
        couples += [
            [words[i % len(words)], random.randint(1, vocabulary_size - 1)]
            for i in range(num_neg)
        ]
        labels += [[1, 0] if categorical else 0] * num_neg
    if shuffle:
        if seed is None:
            seed = random.randint(0, 10**6)
        random.Random(seed).shuffle(couples)
        random.Random(seed).shuffle(labels)
    return couples, labels
