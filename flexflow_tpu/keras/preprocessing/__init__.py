"""Keras-style data preprocessing.

The reference's python/flexflow/keras/preprocessing/ re-exports the
`keras_preprocessing` package (sequence.py:8-13, text.py); this image
doesn't bake that dependency in, so these are self-contained numpy
implementations of the same API surface.
"""
from . import sequence, text
from .sequence import make_sampling_table, pad_sequences, skipgrams
from .text import Tokenizer, one_hot, text_to_word_sequence

__all__ = [
    "sequence", "text", "pad_sequences", "make_sampling_table",
    "skipgrams", "Tokenizer", "one_hot", "text_to_word_sequence",
]
