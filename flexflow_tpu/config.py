"""Runtime configuration — TPU-native analogue of the reference FFConfig.

Reference: /root/reference/include/flexflow/config.h:92-160 and the
hand-rolled parse_args at src/runtime/model.cc:3556-3720 (~40 CLI flags:
training -e/-b/--lr/--wd, Legion -ll:* resource flags, search flags,
simulator/machine-model flags, --fusion, control replication).

TPU translation: the Legion resource flags (-ll:gpu/-ll:fsize/-ll:zsize)
become mesh/device-count + HBM-budget settings; NCCL vs PS becomes the
ParameterSyncType hint consumed by the simulator; control replication is
inherent to SPMD.
"""
from __future__ import annotations

import argparse
import dataclasses
from typing import Dict, List, Optional

from .fftype import ParameterSyncType

# single source of truth for the flash-attention crossover (see the
# flash_min_seq field comment); attention ops fall back to this when
# used outside FFModel.compile.  Measured on-chip (fwd+bwd, both
# directions now real Pallas kernels, best-of-trials under a noisy
# tunnel): seq 512/1024 XLA and flash tie within noise; seq 2048 flash
# ~= XLA with none of the [s,s] score HBM traffic; seq 8192 flash wins
# ~9x (63-124 ms vs 758-822 ms — XLA falls off the HBM cliff when the
# score matrix stops fitting in fused form).  jax's bundled
# pallas.ops.tpu.flash_attention measured 4-10x slower than this
# kernel at every length on the same chip.
DEFAULT_FLASH_MIN_SEQ = 2048

# valid FFConfig.nan_policy values (consumed by the resilience
# supervisor's step-health handling, resilience/supervisor.py).
# "off" disables the per-step health check: check_step_health returns
# without touching the device value, so callers that don't otherwise
# consume the loss pay no sync for it (the supervisor itself still
# syncs once per step to record the loss in its report).
NAN_POLICIES = ("raise", "skip_step", "restore", "off")

# valid FFConfig.serving_mode values (serving/, docs/SERVING.md):
# "continuous" = iteration-level batching on the paged KV pool
# (serving/scheduler.py); "static" = the whole-scan GenerationBatcher
# fallback (one program per coalesced batch, dense per-slot caches).
SERVING_MODES = ("continuous", "static")

# valid FFConfig.paged_kernel values (docs/SERVING.md "Fused paged
# attention"): "gather" = the dense block-gather formulation, the
# bit-identity reference oracle; "pallas" = the fused PagedAttention
# kernel reading KV blocks in place (ops/pallas/paged_attention.py).
PAGED_KERNELS = ("gather", "pallas")

# valid FFConfig.kv_transfer values (serving/kv_transfer.py): the
# fabric a disaggregated fleet streams KV blocks over — "inproc" =
# same-host handoff, "blob" = store-tier hop (store/blobstore.py).
KV_TRANSFER_FABRICS = ("inproc", "blob")

# valid FFConfig.spec_decode values (docs/SERVING.md "Speculative
# decoding"): "off" = one dispatch per generated token; "ngram" =
# prompt-lookup drafter mining the request's own tokens; "draft" = a
# smaller GPT from the same builder drafting through its own paged
# decode engine.  Both verify through the chunk-twin program and
# accept greedily, so output stays token-identical to "off".
SPEC_DECODE_MODES = ("off", "ngram", "draft")


class ConfigError(ValueError):
    """A configuration that can never run in this build/runtime —
    raised at BUILD time with the fix spelled out, so a bad flag never
    surfaces as a deep ImportError mid-compile."""


def resolve_paged_kernel(paged_kernel: str) -> str:
    """Validate the paged-attention formulation choice against this
    runtime.  The "pallas" kernel needs jax.experimental.pallas; when
    it is missing, selecting the kernel raises ConfigError HERE — at
    engine build time — instead of an ImportError from inside a trace.
    Returns the validated value."""
    if paged_kernel not in PAGED_KERNELS:
        raise ConfigError(
            f"paged_kernel must be one of {PAGED_KERNELS}, "
            f"got {paged_kernel!r}")
    if paged_kernel == "pallas":
        from .ops.pallas.paged_attention import have_paged_kernel

        if not have_paged_kernel():
            raise ConfigError(
                "--paged-kernel pallas needs jax.experimental.pallas, "
                "which this jax build does not provide — use "
                "--paged-kernel gather (the reference formulation) or "
                "install a jax with Pallas support")
    return paged_kernel


def resolve_serving_tp(
    tp: int,
    num_heads: Optional[int] = None,
    visible_devices: Optional[int] = None,
) -> int:
    """Validate a replica's tensor-parallel degree at BUILD time
    (docs/SERVING.md "Tensor-parallel replicas").  A tp that cannot
    shard the model raises ConfigError here, with the fix spelled out —
    never a shape error from inside a GSPMD trace.  Returns the
    validated degree."""
    tp = int(tp)
    if tp < 1:
        raise ConfigError(
            f"--serving-tp must be >= 1 (1 = single-chip replica), "
            f"got {tp}")
    if num_heads is not None and num_heads % tp != 0:
        raise ConfigError(
            f"--serving-tp {tp} does not divide the attention head "
            f"count ({num_heads}) — the KV pool shards the head axis "
            f"over the 'model' mesh axis, so tp must divide num_heads "
            f"(try one of "
            f"{[d for d in range(1, num_heads + 1) if num_heads % d == 0]})")
    if visible_devices is None and tp > 1:
        try:
            import jax

            visible_devices = len(jax.devices())
        except Exception:
            visible_devices = None
    if visible_devices is not None and tp > visible_devices:
        raise ConfigError(
            f"--serving-tp {tp} exceeds the {visible_devices} visible "
            f"device(s) — a replica's mesh spans tp chips, so tp must "
            f"be <= the device count available to it")
    return tp


def resolve_spec_decode(
    spec_decode: str,
    spec_k: int,
    beam_size: int = 1,
) -> str:
    """Validate a speculative-decoding configuration at BUILD time
    (docs/SERVING.md "Speculative decoding").  Returns the validated
    mode.  Speculation verifies drafts by accepting the longest
    GREEDY-matching prefix, which is only meaningful for single-path
    decoding — a beam consumer (gpt_beam_search_cached keeps multiple
    live hypotheses per step) must pass its beam_size here so the
    incompatible combination raises ConfigError with the fix spelled
    out instead of silently decoding the wrong thing."""
    if spec_decode not in SPEC_DECODE_MODES:
        raise ConfigError(
            f"--spec-decode must be one of {SPEC_DECODE_MODES}, "
            f"got {spec_decode!r}")
    if spec_decode != "off":
        if int(spec_k) < 1:
            raise ConfigError(
                f"--spec-k must be >= 1 when --spec-decode is "
                f"{spec_decode!r}, got {spec_k}")
        if int(beam_size) > 1:
            raise ConfigError(
                f"--spec-decode {spec_decode!r} cannot be combined "
                f"with beam search (beam_size={beam_size}): "
                f"verification accepts the longest greedy-matching "
                f"draft prefix, which has no analogue across beam "
                f"hypotheses — use --spec-decode off for beam decoding")
    return spec_decode


@dataclasses.dataclass
class FFConfig:
    # -- training (reference: -e, -b, --lr, --wd, parse_args model.cc:3560-3600)
    epochs: int = 1
    batch_size: int = 64
    learning_rate: float = 0.01
    weight_decay: float = 1e-4
    seed: int = 0

    # -- machine resources (reference: -ll:gpu/-ll:cpu/-ll:fsize/-ll:zsize)
    num_devices: int = -1  # -1 = all visible jax devices
    num_nodes: int = 1
    memory_per_device: int = 16 * 1024**3  # HBM budget (reference fsize, MB→bytes)

    # -- strategy search (reference: --budget/--alpha/--enable-*-parallel/
    #    --only-data-parallel/--search-num-nodes/--substitution-json/--memory-search)
    search_budget: int = 0
    search_alpha: float = 0.05
    search_algo: str = "unity"  # "unity" (default, OSDI'22 path) | "mcmc" (SysML'19 legacy)
    # MCMC propagate move (reference FF_USE_PROPAGATE, model.cc:3180-
    # 3258): a rewrite may spread to structurally identical ops — big
    # convergence win on deep nets with repeated layers
    search_propagate: bool = True
    # incremental strategy evaluation (pcg/evaluator.py): memoize
    # revisited candidates and delta-simulate single-op moves instead of
    # re-simulating the whole graph.  Off = the always-full-eval path
    # (delta_eval == full_eval is a tested invariant, so this is a
    # debugging escape hatch, not a correctness knob).
    search_eval_cache: bool = True
    # rewrite enumeration breadth in the Unity search: how many rewrite
    # steps deep and how many graph variants per subproblem.  The
    # defaults keep default-config searches cheap; raise them when
    # hunting catalog wins (scripts/inception_taso_ab.py uses 3/16)
    rewrite_depth: int = 2
    rewrite_max_variants: int = 8
    only_data_parallel: bool = False
    enable_parameter_parallel: bool = False
    enable_attribute_parallel: bool = False
    # partition a non-batch sample dim across a 'sample' mesh axis
    # (reference config.h:134); consumed by UnitySearch._sample_candidates
    enable_sample_parallel: bool = False
    # NOTE: the reference's --enable-inplace-optimizations
    # (model.cc:2884-2919, in-place relu buffers) has no analogue here:
    # XLA buffer assignment + donated weight/opt-state buffers subsume it
    # entirely, so the flag is intentionally NOT carried.
    # credit gradient sync as mostly hidden behind remaining backward
    # compute in search costing (reference config.h:130)
    search_overlap_backward_update: bool = False
    # TASO catalog (JSON or binary .pb, auto-detected).  None = default-
    # on: resolve via rewrite.default_substitution_catalog() ($env, an
    # in-repo substitutions/ dir, a colocated reference checkout);
    # ""/"none" = explicitly off.
    substitution_json: Optional[str] = None
    # calibrate search costs by timing real jitted kernels on the chip
    # (reference inner_measure_operator_cost, model.cu:38-75).
    # None = auto: on when a real TPU backend is present, off on CPU
    # meshes (where the analytic roofline is the right proxy).
    search_calibrate: Optional[bool] = None
    # measured (node_key -> seconds) cache persisted across runs
    op_cost_cache_file: Optional[str] = None
    memory_search: bool = False
    memory_lambda: float = 1.0
    export_strategy_file: Optional[str] = None
    import_strategy_file: Optional[str] = None
    # persistent strategy + compile artifact store (store/,
    # docs/STORE.md): searched strategies keyed by (graph signature,
    # mesh fingerprint, simulator version) survive the process, so a
    # preempted worker, an elastic re-search on a degraded mesh, or a
    # new serving replica restores instead of re-searching.  None =
    # fall through to $FLEXFLOW_TPU_STORE_DIR (fleet deployments);
    # ""/"none" = explicitly off (the substitution_json pattern).
    strategy_store: Optional[str] = None
    # JAX persistent compilation cache dir so the compiled step
    # function itself survives process death: a path, or "auto" =
    # <strategy store root>/xla_cache.  None = off.
    compilation_cache: Optional[str] = None

    # -- simulator / machine model (reference: --machine-model-version/-file,
    #    --simulator-segment-size)
    machine_model_version: int = 0
    machine_model_file: Optional[str] = None
    # -- multi-slice topology (topology/, docs/TOPOLOGY.md): slices > 1
    #    models a pod of identical slices — fast ICI inside each slice,
    #    slow DCN between.  The machine model becomes a SliceHierarchy,
    #    *placement* (which mesh axis spans the DCN boundary) becomes a
    #    searched strategy dimension, and the executor lowers the
    #    cross-slice grad reduction to the hierarchical form on a
    #    two-level mesh.  1 slice (the default) is exactly the flat
    #    pre-topology behavior — same costs, and the slice/DCN knobs
    #    never enter a flat run's store key.
    slices: int = 1
    dcn_bandwidth: float = 25e9   # bytes/s per host across slices
    dcn_latency: float = 10e-6    # seconds per cross-slice hop
    # per-slice ICI torus shape, e.g. "4x4" or "2,2,2"; None = a 1-D
    # ring of num_devices/slices chips
    slice_topology: Optional[str] = None
    # DCN grad-sync coalescing bucket (MB): the cost model amortizes a
    # weight leaf's DCN all-reduce LATENCY term over the fraction of a
    # bucket its DCN-leg bytes fill (real runtimes coalesce grad
    # all-reduces into ~25MB buckets), so many-leaf models stop paying
    # the per-leaf DCN launch latency on dp-crossing placements.
    # Bandwidth/byte terms are untouched.  Only consulted on
    # multi-slice (SliceHierarchy) machines — flat runs have no DCN leg
    # and their store keys carry no bucket field.
    dcn_bucket_mb: float = 25.0
    # bounds per-region search enumeration (its reference role: cap
    # per-segment simulation work); can only lower the built-in cap
    simulator_segment_size: int = 16777216

    # -- execution
    # ZeRO ladder stage (docs/PERF.md "The ZeRO ladder"; ZeRO-1 is
    # Xu et al. arXiv:2004.13336, stages 2-3 are Rajbhandari et al.
    # arXiv:1910.02054):
    #   0 = replicated update (every replica runs the full optimizer
    #       pass and keeps full grads/slots/master weights);
    #   1 = sharded update: reduce-scatter grads along `wus_axis`, run
    #       the update on the 1/N shard where the slots permanently
    #       live, all-gather the updated weights back (slot HBM / N);
    #   2 = stage 1 + gradients stay reduce-scattered THROUGH the
    #       update — the per-device gradient buffer is the 1/N shard
    #       (grad HBM / N);
    #   3 = stage 2 + master weights live permanently sharded along
    #       `wus_axis` with just-in-time per-layer all-gather on use
    #       and double-buffered prefetch (FSDP: weight-resident
    #       HBM / N, per-layer all-gather traffic).
    # Every stage is numerically equivalent to stage 0 and is a costed
    # simulator mode (sim/simulator.py zero_stage); with
    # --memory-search the searches CHOOSE the stage per model
    # (pcg/mcmc.py search_stage_candidates).
    zero_stage: int = 0
    # DEPRECATED alias for zero_stage=1 (the pre-ladder knob): True
    # maps to stage 1 in __post_init__; after init it always mirrors
    # `zero_stage >= 1` so existing consumers keep working.
    weight_update_sharding: bool = False
    wus_axis: str = "data"  # mesh axis the update shards over
    # reference --fusion (apply_fusion model.cc:2495): fold trailing
    # activations into producers at compile; XLA fuses kernels anyway,
    # this shrinks the PCG/search space
    perform_fusion: bool = False
    # rematerialise segment internals in backward (jax.checkpoint at
    # single-tensor-boundary cuts): trades recompute FLOPs for HBM —
    # a TPU-native capability the reference cannot express
    remat: bool = False
    profiling: bool = False
    # gradient-sync cost model: ALL_REDUCE rings vs PS flat 2*size/BW
    # (reference ParameterSyncType config.h:55-59, simulator.cc:786-813)
    parameter_sync: ParameterSyncType = ParameterSyncType.ALL_REDUCE
    compute_dtype: str = "float32"  # bf16 on TPU for perf runs
    # use the Pallas flash-attention kernel only at KV length >= this;
    # 0 forces flash everywhere (see DEFAULT_FLASH_MIN_SEQ above)
    flash_min_seq: int = DEFAULT_FLASH_MIN_SEQ

    # -- exports (reference: --taskgraph/--compgraph/--include-costs-dot-graph)
    export_taskgraph_file: Optional[str] = None
    export_compgraph_file: Optional[str] = None
    include_costs_dot_graph: bool = False

    # -- resilience (resilience/supervisor.py): checkpoint cadence,
    #    restart budget, retry backoff, and non-finite-loss policy.
    #    The reference has no analogue — it leans on Legion for fault
    #    handling; these knobs drive the TPU-native supervisor.
    checkpoint_every: int = 0  # steps between periodic checkpoints; 0 = off
    checkpoint_dir: Optional[str] = None
    checkpoint_keep: int = 3   # keep-last-k retention
    # async verified saves: the step boundary stalls only for the
    # device->host snapshot; serialize/fsync/verify/publish run on a
    # background writer (checkpoint.py + resilience/async_writer.py)
    checkpoint_async: bool = False
    # hung-step watchdog: per-step device sync deadline in seconds
    # (resilience/watchdog.py); 0 disables the watchdog entirely
    step_timeout: float = 0.0
    # SIGTERM/SIGINT preemption grace: emergency checkpoint at the next
    # step boundary instead of dying checkpoint-less
    preempt_grace: bool = True
    max_restarts: int = 3      # restore-and-retry budget per run
    retry_backoff: float = 0.1  # base backoff seconds (exponential, jittered)
    nan_policy: str = "raise"  # raise | skip_step | restore | off
    # -- durable offload tier (resilience/offload.py, store/blobstore.py;
    #    docs/RESILIENCE.md "Durable offload & host-loss recovery"):
    #    mirror every verified local checkpoint — and the strategy
    #    store — to an object store so a FULL HOST LOSS keeps a restore
    #    target.  URI: file:///path or a bare path (filesystem backend;
    #    an NFS mount used this way is a production deployment);
    #    gs://... names the cloud backend once its SDK is provisioned.
    #    None/"none" = offload off (single-tier, pre-PR-9 behavior).
    remote_store: Optional[str] = None
    offload_every: int = 1   # mirror every Nth verified local checkpoint
    remote_keep: int = 3     # keep-last-k retention in the remote tier
    # how long a preempted worker waits for its peers' barrier posts
    # before committing the best agreement so far — size it WELL below
    # the platform's preemption grace window, since the emergency save
    # only starts after the rendezvous returns
    barrier_timeout: float = 30.0

    # -- observability (obs/, docs/OBSERVABILITY.md).  trace_dir turns
    #    on the full telemetry pipeline and names where the artifacts
    #    land (trace.json Chrome trace + run_telemetry.jsonl metrics);
    #    telemetry=True records in memory without writing files (drain
    #    via FFModel.telemetry).  Disabled (the default) is zero-cost on
    #    the step hot path: no span objects are ever allocated.
    trace_dir: Optional[str] = None
    telemetry: bool = False
    # jax.profiler.trace device capture around a step window,
    # "start:count" (e.g. "3:2" profiles steps 3 and 4); needs trace_dir
    profile_steps: Optional[str] = None
    # per-request serving trace sampling probability
    # (obs/reqtrace.py, docs/OBSERVABILITY.md "Request tracing"):
    # 1.0 traces every admitted request (tests/smoke), loadgen/prod
    # runs rate-limit by sampling down; 0.0 disables request tracing
    # even with telemetry on
    trace_sample: float = 1.0

    # -- serving (serving/, docs/SERVING.md): generation tier mode and
    #    paged KV-cache pool geometry.  Consumed by the serving entry
    #    points (examples/serve_gpt.py, bench serving leg) — training
    #    never reads these.
    serving_mode: str = "continuous"  # continuous | static (fallback)
    kv_page_size: int = 16     # tokens per KV block (must divide max_seq)
    kv_pool_blocks: int = 0    # physical blocks incl. scratch; 0 = auto
    serving_slots: int = 8     # continuous decode batch slots
    # prefix cache & chunked prefill (docs/SERVING.md "Prefix cache &
    # chunked prefill"): copy-on-write sharing of block-aligned prompt
    # prefixes in the KV pool, and a second [slots, C] compiled step
    # that prefills C prompt tokens per dispatch (0/1 = one-token
    # prefill, the PR 6 path).  Both preserve greedy token-identity.
    prefill_chunk: int = 8
    prefix_cache: bool = True
    # paged-attention read formulation (docs/SERVING.md "Fused paged
    # attention"): "gather" keeps the dense block-gather view — the
    # bit-identity reference oracle; "pallas" runs the fused
    # PagedAttention kernel that streams KV blocks in place through
    # the block table, so per-step HBM reads scale with live tokens
    # instead of decode_max_seq.  Validated against the runtime at
    # engine build time (resolve_paged_kernel).
    paged_kernel: str = "gather"
    # replicated front (serving/front.py, docs/SERVING.md "Replicated
    # front"): N supervised ContinuousScheduler replicas behind one
    # admission queue.  1 = single supervised replica (still gains the
    # watchdog + restart supervision); the decode-step watchdog is off
    # at 0 like the training step_timeout.
    serving_replicas: int = 1
    serving_step_timeout: float = 0.0  # decode-step watchdog deadline, s
    serving_max_restarts: int = 3      # per-replica restart budget
    request_retry_limit: int = 2       # requeues before a 503 retriable
    # SLO-driven autoscaling (serving/autoscaler.py, docs/SERVING.md
    # "Autoscaling & drain lifecycle"): the fleet sizes itself between
    # [min, max] from the queue-depth / p99-TTFT / KV-occupancy gauges;
    # scale-down DRAINS (graceful, token-identical) instead of killing.
    # max = 0 leaves autoscaling off (static --serving-replicas fleet).
    serving_min_replicas: int = 1
    serving_max_replicas: int = 0
    autoscale_interval: float = 1.0    # control-loop tick period, s
    autoscale_cooldown: float = 5.0    # hold-off after any scale action
    serving_slo_ttft: float = 0.0      # p99 TTFT target, s (0 = ignore)
    serving_drain_timeout: float = 30.0  # wedged-drain force bound, s
    # overload admission control: shed at admission when predicted TTFT
    # (backlog / measured service rate) exceeds this many seconds
    # (0 = off; per-request deadline_s overrides)
    admission_deadline_s: float = 0.0
    # tensor-parallel degree of ONE serving replica (docs/SERVING.md
    # "Tensor-parallel replicas"): each replica spans tp chips under
    # GSPMD — attention heads and the paged KV block pools shard over a
    # 'model' mesh axis, so per-chip KV bytes are 1/tp and a replica
    # can hold a model bigger than one chip.  Must divide the head
    # count and fit the visible devices (resolve_serving_tp validates
    # at build time).  1 = single-chip replicas (prior behavior).
    serving_tp: int = 1
    # total chips the serving fleet may hold (0 = unbounded): the front
    # refuses an add_replica that would push
    # len(replicas) * serving_tp past the budget, and the autoscaler
    # counts the refusal as a spawn failure instead of flapping
    serving_chip_budget: int = 0
    # disaggregated prefill/decode fleet (serving/disagg.py,
    # docs/SERVING.md "Disaggregated fleet"): per-replica role spec
    # "prefill=N,decode=M[,mixed=K]" — counts must include at least one
    # decode-capable replica; "" = colocated fleet (every replica
    # mixed, the prior behavior).  Validated by parse_serving_roles.
    serving_roles: str = ""
    # KV block streaming fabric between replicas: "inproc" (same-host
    # handoff) or "blob" (store tier hop — inherits the blob fault
    # matrix, so torn streams degrade to re-prefill)
    kv_transfer: str = "inproc"
    # migrate iff migrate_time <= cap * reprefill_time (the dispatcher
    # costs each handoff with the topology interconnect terms); lower
    # caps migrate less, must be > 0
    migration_cost_cap: float = 1.0
    # predictive autoscaling: project the admission queue forward from
    # the measured admission-rate slope and scale BEFORE the reactive
    # queue threshold breaches (serving/autoscaler.py)
    autoscale_predictive: bool = False
    # speculative decoding (serving/speculative.py, docs/SERVING.md
    # "Speculative decoding"): propose up to spec_k draft tokens per
    # eligible slot per round and verify them in ONE chunk-twin
    # dispatch, accepting the longest greedy-matching prefix plus the
    # first corrected token — token-identical to "off" by
    # construction.  "ngram" mines the request's own prompt+generated
    # tokens (no second model); "draft" runs a smaller GPT through its
    # own paged decode engine (needs a draft model at engine build).
    # Acceptance-rate-adaptive k shrinks toward 1 when drafts miss, so
    # the feature is never worse than one-token decode.
    spec_decode: str = "off"
    spec_k: int = 4
    # resumable mid-decode handoff (serving/handoff.py, docs/SERVING.md
    # "Mid-decode handoff"): with the flag on, a DRAINING / terminating
    # / rebalanced replica pauses its in-flight generations (resume
    # record + optional live KV-block stream) and the front resumes
    # them on a surviving replica, token-identically.  Off keeps the
    # classic drain semantics (every slot runs to completion).
    serving_handoff: bool = False
    # hot-replica rebalance threshold: a live replica whose KV-pool
    # occupancy exceeds this fraction (while a peer sits below half of
    # it) hands one generation off via the autoscaler's tick.  0 = off;
    # needs --serving-handoff.
    serving_rebalance_kv: float = 0.0

    def __post_init__(self):
        if self.serving_mode not in SERVING_MODES:
            raise ValueError(
                f"serving_mode must be one of {SERVING_MODES}, "
                f"got {self.serving_mode!r}"
            )
        if self.kv_page_size < 1:
            raise ValueError(
                f"kv_page_size must be >= 1, got {self.kv_page_size}"
            )
        if self.kv_pool_blocks < 0:
            raise ValueError(
                f"kv_pool_blocks must be >= 0 (0 = auto), "
                f"got {self.kv_pool_blocks}"
            )
        if self.serving_slots < 1:
            raise ValueError(
                f"serving_slots must be >= 1, got {self.serving_slots}"
            )
        if self.prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk must be >= 0 (0 = one-token prefill), "
                f"got {self.prefill_chunk}"
            )
        if self.paged_kernel not in PAGED_KERNELS:
            raise ValueError(
                f"paged_kernel must be one of {PAGED_KERNELS}, "
                f"got {self.paged_kernel!r}"
            )
        if self.serving_replicas < 1:
            raise ValueError(
                f"serving_replicas must be >= 1, got {self.serving_replicas}"
            )
        if self.serving_step_timeout < 0:
            raise ValueError(
                f"serving_step_timeout must be >= 0 (0 = watchdog off), "
                f"got {self.serving_step_timeout}"
            )
        if self.serving_max_restarts < 0:
            raise ValueError(
                f"serving_max_restarts must be >= 0, "
                f"got {self.serving_max_restarts}"
            )
        if self.request_retry_limit < 0:
            raise ValueError(
                f"request_retry_limit must be >= 0, "
                f"got {self.request_retry_limit}"
            )
        if self.serving_min_replicas < 1:
            raise ValueError(
                f"serving_min_replicas must be >= 1, "
                f"got {self.serving_min_replicas}"
            )
        if (self.serving_max_replicas != 0
                and self.serving_max_replicas < self.serving_min_replicas):
            raise ValueError(
                f"serving_max_replicas ({self.serving_max_replicas}) must "
                f"be 0 (autoscaling off) or >= serving_min_replicas "
                f"({self.serving_min_replicas})"
            )
        if self.autoscale_interval <= 0:
            raise ValueError(
                f"autoscale_interval must be > 0, "
                f"got {self.autoscale_interval}"
            )
        if self.autoscale_cooldown < 0:
            raise ValueError(
                f"autoscale_cooldown must be >= 0, "
                f"got {self.autoscale_cooldown}"
            )
        if self.serving_slo_ttft < 0:
            raise ValueError(
                f"serving_slo_ttft must be >= 0 (0 = ignore), "
                f"got {self.serving_slo_ttft}"
            )
        if self.serving_drain_timeout <= 0:
            raise ValueError(
                f"serving_drain_timeout must be > 0, "
                f"got {self.serving_drain_timeout}"
            )
        if self.admission_deadline_s < 0:
            raise ValueError(
                f"admission_deadline_s must be >= 0 (0 = off), "
                f"got {self.admission_deadline_s}"
            )
        if self.serving_tp < 1:
            raise ValueError(
                f"serving_tp must be >= 1 (1 = single-chip replicas), "
                f"got {self.serving_tp}"
            )
        if self.serving_chip_budget < 0:
            raise ValueError(
                f"serving_chip_budget must be >= 0 (0 = unbounded), "
                f"got {self.serving_chip_budget}"
            )
        if self.serving_roles:
            # full spec validation (role names, counts, decode-capable
            # floor) lives with the parser the front consumes
            from .serving.disagg import parse_serving_roles

            parse_serving_roles(self.serving_roles)
        if self.kv_transfer not in KV_TRANSFER_FABRICS:
            raise ValueError(
                f"kv_transfer must be one of {KV_TRANSFER_FABRICS}, "
                f"got {self.kv_transfer!r}"
            )
        if self.migration_cost_cap <= 0:
            raise ValueError(
                f"migration_cost_cap must be > 0, "
                f"got {self.migration_cost_cap}"
            )
        if self.spec_decode not in SPEC_DECODE_MODES:
            raise ValueError(
                f"spec_decode must be one of {SPEC_DECODE_MODES}, "
                f"got {self.spec_decode!r}"
            )
        if self.spec_k < 1:
            raise ValueError(
                f"spec_k must be >= 1, got {self.spec_k}"
            )
        if not 0.0 <= self.serving_rebalance_kv < 1.0:
            raise ValueError(
                f"serving_rebalance_kv must be in [0, 1) (occupancy "
                f"fraction; 0 = off), got {self.serving_rebalance_kv}"
            )
        if self.serving_rebalance_kv > 0 and not self.serving_handoff:
            raise ValueError(
                "serving_rebalance_kv needs --serving-handoff: the "
                "rebalance trigger pauses generations onto the "
                "handoff path"
            )
        if not 0.0 <= self.trace_sample <= 1.0:
            raise ValueError(
                f"trace_sample must be in [0.0, 1.0], got "
                f"{self.trace_sample}"
            )
        if self.nan_policy not in NAN_POLICIES:
            raise ValueError(
                f"nan_policy must be one of {NAN_POLICIES}, "
                f"got {self.nan_policy!r}"
            )
        if self.checkpoint_every < 0:
            raise ValueError(
                f"checkpoint_every must be >= 0, got {self.checkpoint_every}"
            )
        if self.checkpoint_keep < 1:
            raise ValueError(
                f"checkpoint_keep must be >= 1, got {self.checkpoint_keep}"
            )
        if self.max_restarts < 0:
            raise ValueError(
                f"max_restarts must be >= 0, got {self.max_restarts}"
            )
        if self.retry_backoff < 0:
            raise ValueError(
                f"retry_backoff must be >= 0, got {self.retry_backoff}"
            )
        if self.step_timeout < 0:
            raise ValueError(
                f"step_timeout must be >= 0 (0 = watchdog off), "
                f"got {self.step_timeout}"
            )
        if self.offload_every < 1:
            raise ValueError(
                f"offload_every must be >= 1, got {self.offload_every}"
            )
        if self.remote_keep < 1:
            raise ValueError(
                f"remote_keep must be >= 1, got {self.remote_keep}"
            )
        if self.barrier_timeout <= 0:
            raise ValueError(
                f"barrier_timeout must be > 0, got {self.barrier_timeout}"
            )
        if self.slices < 1:
            raise ValueError(f"slices must be >= 1, got {self.slices}")
        if self.dcn_bandwidth <= 0:
            raise ValueError(
                f"dcn_bandwidth must be > 0 bytes/s, got {self.dcn_bandwidth}"
            )
        if self.dcn_latency < 0:
            raise ValueError(
                f"dcn_latency must be >= 0 seconds, got {self.dcn_latency}"
            )
        if self.slice_topology is not None:
            from .topology.hierarchy import parse_slice_topology

            parse_slice_topology(self.slice_topology)  # raises on bad spec
        if self.dcn_bucket_mb <= 0:
            raise ValueError(
                f"dcn_bucket_mb must be > 0 MB, got {self.dcn_bucket_mb}"
            )
        if self.zero_stage not in (0, 1, 2, 3):
            raise ValueError(
                f"zero_stage must be one of (0, 1, 2, 3), "
                f"got {self.zero_stage!r}"
            )
        # deprecation shim: the pre-ladder --weight-update-sharding
        # flag is exactly stage 1; after normalization the bool always
        # mirrors the stage so old consumers stay correct
        if self.weight_update_sharding and self.zero_stage == 0:
            self.zero_stage = 1
        self.weight_update_sharding = self.zero_stage >= 1
        if not self.wus_axis:
            raise ValueError("wus_axis must be a non-empty mesh axis name")
        if self.compilation_cache is not None and not str(
            self.compilation_cache
        ).strip():
            raise ValueError(
                "compilation_cache must be a directory path or 'auto' "
                "(None disables it)"
            )
        if self.profile_steps is not None:
            from .obs import parse_profile_steps

            parse_profile_steps(self.profile_steps)  # raises on bad spec
            if not self.trace_dir:
                raise ValueError(
                    "profile_steps needs trace_dir set (the jax profiler "
                    "capture is written under it)"
                )

    def resolve_store_dir(self) -> Optional[str]:
        """Effective strategy-store root (None = store off); resolution
        rules live with the store (store.resolve_store_dir)."""
        from .store import resolve_store_dir

        return resolve_store_dir(self)

    def should_calibrate(self) -> bool:
        """Resolve search_calibrate's auto mode: measured costs when a
        real accelerator backend is live, analytic roofline otherwise."""
        if self.search_calibrate is not None:
            return self.search_calibrate
        try:
            import jax

            return jax.default_backend() not in ("cpu",)
        except Exception:
            return False

    def resolve_num_devices(self) -> int:
        if self.num_devices > 0:
            return self.num_devices
        import jax

        return len(jax.devices())

    @property
    def workers_per_node(self) -> int:
        return max(1, self.resolve_num_devices() // max(1, self.num_nodes))

    # ------------------------------------------------------------------
    @classmethod
    def from_args(cls, argv: Optional[List[str]] = None) -> "FFConfig":
        """Parse the reference's CLI flag set (model.cc:3556-3720 names kept)."""
        p = argparse.ArgumentParser(add_help=False)
        p.add_argument("-e", "--epochs", type=int, default=1)
        p.add_argument("-b", "--batch-size", type=int, default=64)
        p.add_argument("--lr", "--learning-rate", dest="lr", type=float, default=0.01)
        p.add_argument("--wd", "--weight-decay", dest="wd", type=float, default=1e-4)
        p.add_argument("--seed", type=int, default=0)
        p.add_argument("-ll:gpu", "--num-devices", dest="num_devices", type=int, default=-1)
        p.add_argument("--nodes", type=int, default=1)
        p.add_argument("-ll:fsize", dest="fsize_mb", type=int, default=16384)
        p.add_argument("--budget", "--search-budget", dest="budget", type=int, default=0)
        p.add_argument("--alpha", "--search-alpha", dest="alpha", type=float, default=0.05)
        p.add_argument("--no-propagate", dest="search_propagate",
                       action="store_false", default=True)
        p.add_argument("--no-search-eval-cache", dest="search_eval_cache",
                       action="store_false", default=True)
        p.add_argument("--search-algo", dest="search_algo", type=str, default="unity",
                       choices=("unity", "mcmc"))
        p.add_argument("--only-data-parallel", action="store_true")
        p.add_argument("--enable-parameter-parallel", action="store_true")
        p.add_argument("--enable-attribute-parallel", action="store_true")
        p.add_argument("--enable-sample-parallel", action="store_true")
        p.add_argument("--search-overlap-backward-update", "--overlap",
                       dest="overlap_backward_update", action="store_true")
        p.add_argument("--parameter-sync", dest="parameter_sync", type=str,
                       default="all_reduce", choices=("none", "ps", "all_reduce"))
        p.add_argument("--substitution-json", type=str, default=None)
        p.add_argument("--rewrite-depth", type=int, default=2)
        p.add_argument("--rewrite-max-variants", type=int, default=8)
        p.add_argument("--search-calibrate", dest="search_calibrate",
                       action="store_true", default=None)
        p.add_argument("--no-search-calibrate", dest="search_calibrate",
                       action="store_false")
        p.add_argument("--op-cost-cache", dest="op_cost_cache", type=str,
                       default=None)
        p.add_argument("--memory-search", action="store_true")
        p.add_argument("--machine-model-version", type=int, default=0)
        p.add_argument("--machine-model-file", type=str, default=None)
        p.add_argument("--simulator-segment-size", type=int, default=16777216)
        p.add_argument("--slices", dest="slices", type=int, default=1)
        p.add_argument("--dcn-bandwidth", dest="dcn_bandwidth", type=float,
                       default=25e9)
        p.add_argument("--dcn-latency", dest="dcn_latency", type=float,
                       default=10e-6)
        p.add_argument("--slice-topology", dest="slice_topology", type=str,
                       default=None)
        p.add_argument("--dcn-bucket-mb", dest="dcn_bucket_mb", type=float,
                       default=25.0)
        # default None so an EXPLICIT --zero-stage 0 is distinguishable
        # from the default: the explicit stage wins over the deprecated
        # flag below (including 0), the shim only fills the default
        p.add_argument("--zero-stage", dest="zero_stage", type=int,
                       default=None, choices=(0, 1, 2, 3))
        # deprecated: equivalent to --zero-stage 1 (shim in __post_init__)
        p.add_argument("--weight-update-sharding", dest="weight_update_sharding",
                       action="store_true")
        p.add_argument("--wus-axis", dest="wus_axis", type=str, default="data")
        p.add_argument("--fusion", action="store_true")
        p.add_argument("--remat", action="store_true")
        p.add_argument("--profiling", action="store_true")
        p.add_argument("--flash-min-seq", dest="flash_min_seq", type=int,
                       default=DEFAULT_FLASH_MIN_SEQ)
        p.add_argument("--export-strategy", dest="export_strategy", type=str, default=None)
        p.add_argument("--import-strategy", dest="import_strategy", type=str, default=None)
        p.add_argument("--strategy-store", dest="strategy_store", type=str,
                       default=None)
        p.add_argument("--no-strategy-store", dest="strategy_store",
                       action="store_const", const="none")
        p.add_argument("--compilation-cache", dest="compilation_cache",
                       type=str, nargs="?", const="auto", default=None)
        p.add_argument("--taskgraph", type=str, default=None)
        p.add_argument("--compgraph", type=str, default=None)
        p.add_argument("--include-costs-dot-graph", action="store_true")
        p.add_argument("--checkpoint-every", dest="checkpoint_every",
                       type=int, default=0)
        p.add_argument("--checkpoint-dir", dest="checkpoint_dir", type=str,
                       default=None)
        p.add_argument("--checkpoint-keep", dest="checkpoint_keep", type=int,
                       default=3)
        p.add_argument("--checkpoint-async", dest="checkpoint_async",
                       action="store_true")
        p.add_argument("--step-timeout", dest="step_timeout", type=float,
                       default=0.0)
        p.add_argument("--no-preempt-grace", dest="preempt_grace",
                       action="store_false", default=True)
        p.add_argument("--max-restarts", dest="max_restarts", type=int,
                       default=3)
        p.add_argument("--retry-backoff", dest="retry_backoff", type=float,
                       default=0.1)
        p.add_argument("--nan-policy", dest="nan_policy", type=str,
                       default="raise", choices=NAN_POLICIES)
        p.add_argument("--remote-store", dest="remote_store", type=str,
                       default=None)
        p.add_argument("--no-remote-store", dest="remote_store",
                       action="store_const", const="none")
        p.add_argument("--offload-every", dest="offload_every", type=int,
                       default=1)
        p.add_argument("--remote-keep", dest="remote_keep", type=int,
                       default=3)
        p.add_argument("--barrier-timeout", dest="barrier_timeout",
                       type=float, default=30.0)
        p.add_argument("--trace-dir", dest="trace_dir", type=str, default=None)
        p.add_argument("--telemetry", dest="telemetry", action="store_true")
        p.add_argument("--profile-steps", dest="profile_steps", type=str,
                       default=None)
        p.add_argument("--trace-sample", dest="trace_sample", type=float,
                       default=1.0)
        p.add_argument("--serving-mode", dest="serving_mode", type=str,
                       default="continuous", choices=SERVING_MODES)
        p.add_argument("--kv-page-size", dest="kv_page_size", type=int,
                       default=16)
        p.add_argument("--kv-pool-blocks", dest="kv_pool_blocks",
                       type=int, default=0)
        p.add_argument("--serving-slots", dest="serving_slots", type=int,
                       default=8)
        p.add_argument("--prefill-chunk", dest="prefill_chunk",
                       type=int, default=8)
        p.add_argument("--no-prefix-cache", dest="prefix_cache",
                       action="store_false")
        p.add_argument("--paged-kernel", dest="paged_kernel", type=str,
                       default="gather", choices=PAGED_KERNELS)
        p.add_argument("--serving-replicas", dest="serving_replicas",
                       type=int, default=1)
        p.add_argument("--serving-step-timeout",
                       dest="serving_step_timeout", type=float,
                       default=0.0)
        p.add_argument("--serving-max-restarts",
                       dest="serving_max_restarts", type=int, default=3)
        p.add_argument("--request-retry-limit",
                       dest="request_retry_limit", type=int, default=2)
        p.add_argument("--serving-min-replicas",
                       dest="serving_min_replicas", type=int, default=1)
        p.add_argument("--serving-max-replicas",
                       dest="serving_max_replicas", type=int, default=0)
        p.add_argument("--autoscale-interval",
                       dest="autoscale_interval", type=float,
                       default=1.0)
        p.add_argument("--autoscale-cooldown",
                       dest="autoscale_cooldown", type=float,
                       default=5.0)
        p.add_argument("--serving-slo-ttft", dest="serving_slo_ttft",
                       type=float, default=0.0)
        p.add_argument("--serving-drain-timeout",
                       dest="serving_drain_timeout", type=float,
                       default=30.0)
        p.add_argument("--admission-deadline",
                       dest="admission_deadline_s", type=float,
                       default=0.0)
        p.add_argument("--serving-tp", dest="serving_tp", type=int,
                       default=1)
        p.add_argument("--serving-chip-budget",
                       dest="serving_chip_budget", type=int, default=0)
        p.add_argument("--serving-roles", dest="serving_roles", type=str,
                       default="")
        p.add_argument("--kv-transfer", dest="kv_transfer", type=str,
                       default="inproc", choices=KV_TRANSFER_FABRICS)
        p.add_argument("--migration-cost-cap", dest="migration_cost_cap",
                       type=float, default=1.0)
        p.add_argument("--autoscale-predictive",
                       dest="autoscale_predictive", action="store_true")
        p.add_argument("--spec-decode", dest="spec_decode", type=str,
                       default="off", choices=SPEC_DECODE_MODES)
        p.add_argument("--spec-k", dest="spec_k", type=int, default=4)
        p.add_argument("--serving-handoff", dest="serving_handoff",
                       action="store_true")
        p.add_argument("--serving-rebalance-kv",
                       dest="serving_rebalance_kv", type=float,
                       default=0.0)
        args, _ = p.parse_known_args(argv)
        return cls(
            epochs=args.epochs,
            batch_size=args.batch_size,
            learning_rate=args.lr,
            weight_decay=args.wd,
            seed=args.seed,
            num_devices=args.num_devices,
            num_nodes=args.nodes,
            memory_per_device=args.fsize_mb * 1024**2,
            search_budget=args.budget,
            search_alpha=args.alpha,
            search_propagate=args.search_propagate,
            search_eval_cache=args.search_eval_cache,
            search_algo=args.search_algo,
            only_data_parallel=args.only_data_parallel,
            enable_parameter_parallel=args.enable_parameter_parallel,
            enable_attribute_parallel=args.enable_attribute_parallel,
            enable_sample_parallel=args.enable_sample_parallel,
            search_overlap_backward_update=args.overlap_backward_update,
            parameter_sync=ParameterSyncType(args.parameter_sync),
            substitution_json=args.substitution_json,
            rewrite_depth=args.rewrite_depth,
            rewrite_max_variants=args.rewrite_max_variants,
            search_calibrate=args.search_calibrate,
            op_cost_cache_file=args.op_cost_cache,
            memory_search=args.memory_search,
            machine_model_version=args.machine_model_version,
            machine_model_file=args.machine_model_file,
            simulator_segment_size=args.simulator_segment_size,
            slices=args.slices,
            dcn_bandwidth=args.dcn_bandwidth,
            dcn_latency=args.dcn_latency,
            slice_topology=args.slice_topology,
            dcn_bucket_mb=args.dcn_bucket_mb,
            zero_stage=(args.zero_stage if args.zero_stage is not None
                        else (1 if args.weight_update_sharding else 0)),
            weight_update_sharding=(args.weight_update_sharding
                                    if args.zero_stage is None else False),
            wus_axis=args.wus_axis,
            perform_fusion=args.fusion,
            remat=args.remat,
            profiling=args.profiling,
            flash_min_seq=args.flash_min_seq,
            export_strategy_file=args.export_strategy,
            import_strategy_file=args.import_strategy,
            strategy_store=args.strategy_store,
            compilation_cache=args.compilation_cache,
            export_taskgraph_file=args.taskgraph,
            export_compgraph_file=args.compgraph,
            include_costs_dot_graph=args.include_costs_dot_graph,
            checkpoint_every=args.checkpoint_every,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_keep=args.checkpoint_keep,
            checkpoint_async=args.checkpoint_async,
            step_timeout=args.step_timeout,
            preempt_grace=args.preempt_grace,
            max_restarts=args.max_restarts,
            retry_backoff=args.retry_backoff,
            nan_policy=args.nan_policy,
            remote_store=args.remote_store,
            offload_every=args.offload_every,
            remote_keep=args.remote_keep,
            barrier_timeout=args.barrier_timeout,
            trace_dir=args.trace_dir,
            telemetry=args.telemetry,
            profile_steps=args.profile_steps,
            trace_sample=args.trace_sample,
            serving_mode=args.serving_mode,
            kv_page_size=args.kv_page_size,
            kv_pool_blocks=args.kv_pool_blocks,
            serving_slots=args.serving_slots,
            prefill_chunk=args.prefill_chunk,
            prefix_cache=args.prefix_cache,
            paged_kernel=args.paged_kernel,
            serving_replicas=args.serving_replicas,
            serving_step_timeout=args.serving_step_timeout,
            serving_max_restarts=args.serving_max_restarts,
            request_retry_limit=args.request_retry_limit,
            serving_min_replicas=args.serving_min_replicas,
            serving_max_replicas=args.serving_max_replicas,
            autoscale_interval=args.autoscale_interval,
            autoscale_cooldown=args.autoscale_cooldown,
            serving_slo_ttft=args.serving_slo_ttft,
            serving_drain_timeout=args.serving_drain_timeout,
            admission_deadline_s=args.admission_deadline_s,
            serving_tp=args.serving_tp,
            serving_chip_budget=args.serving_chip_budget,
            serving_roles=args.serving_roles,
            kv_transfer=args.kv_transfer,
            migration_cost_cap=args.migration_cost_cap,
            autoscale_predictive=args.autoscale_predictive,
            spec_decode=args.spec_decode,
            spec_k=args.spec_k,
            serving_handoff=args.serving_handoff,
            serving_rebalance_kv=args.serving_rebalance_kv,
        )


@dataclasses.dataclass
class FFIterationConfig:
    """Per-iteration config threaded through forward/backward.

    Reference: config.h:162-167 — carries seq_length for early truncation
    (consumed by BatchMatmul/attention; model.cc:2415-2419).
    """

    seq_length: int = -1

    def reset(self):
        self.seq_length = -1
