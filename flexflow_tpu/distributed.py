"""Multi-host bring-up: the package-level equivalent of the reference's
mpirun launcher path (/root/reference/python/flexflow/driver.py spawns
`mpirun ... flexflow_python`; tests/multinode_helpers/mpi_wrapper1.sh
wires per-rank env).  TPU-native there is no launcher to exec: every
host runs the same script, `jax.distributed` joins them into one
runtime, and XLA SPMD spans all chips.  This module owns that join plus
the per-host batch-feeding helper the docs previously asked users to
hand-write (docs/MULTI-NODE.md).
This module also owns the cross-host *preemption barrier*
(`preemption_barrier`): a blob-store rendezvous keyed by run id so that
when a preemption notice lands, every worker's SIGTERM emergency
checkpoint commits the SAME step — the first cross-host coordination
primitive on the path to pod-scale placement (docs/RESILIENCE.md
"Durable offload & host-loss recovery").
"""
from __future__ import annotations

import logging
import os
import time
from typing import Callable, Dict, Optional, Sequence

import numpy as np

_log = logging.getLogger("flexflow_tpu.distributed")

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> bool:
    """Join this process into the multi-host jax runtime.

    Call first thing in the training script, before any other jax use.
    Resolution order mirrors the launch recipes users actually have:

      1. explicit args (manual bring-up / custom schedulers);
      2. env vars ``FLEXFLOW_COORDINATOR`` / ``FLEXFLOW_NUM_PROCS`` /
         ``FLEXFLOW_PROC_ID``, or the standard OMPI rank vars when
         launched under mpirun (the reference's launcher convention);
      3. no information at all -> ``jax.distributed.initialize()``,
         which autodetects on Cloud TPU pods and is skipped entirely
         when that autodetection cannot apply (single-process dev).

    Returns True when a multi-process runtime was initialized, False
    for the harmless single-process fallback.  Idempotent.
    """
    global _initialized
    import jax

    if _initialized:
        return jax.process_count() > 1

    if coordinator_address is None:
        coordinator_address = os.environ.get("FLEXFLOW_COORDINATOR")
    if num_processes is None:
        np_env = os.environ.get(
            "FLEXFLOW_NUM_PROCS", os.environ.get("OMPI_COMM_WORLD_SIZE")
        )
        num_processes = int(np_env) if np_env else None
    if process_id is None:
        pid_env = os.environ.get(
            "FLEXFLOW_PROC_ID", os.environ.get("OMPI_COMM_WORLD_RANK")
        )
        process_id = int(pid_env) if pid_env is not None else None

    if coordinator_address is None and num_processes is not None \
            and num_processes > 1:
        raise ValueError(
            "multi-process launch needs a coordinator: set "
            "FLEXFLOW_COORDINATOR=<worker0-host:port> (or pass "
            "coordinator_address=)"
        )
    if coordinator_address is not None:
        # explicit configuration: a failure here must NOT degrade to N
        # disjoint single-process runs — let it raise
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
        _initialized = True
        return jax.process_count() > 1
    try:
        # TPU-pod autodetection path; no-op away from a pod
        jax.distributed.initialize()
        _initialized = True
    except Exception:
        # single-process dev environment (no cluster metadata): fine
        _initialized = True
        return False
    return jax.process_count() > 1


def preemption_barrier(
    blob,
    run_id: str,
    step: int,
    *,
    host_id: Optional[int] = None,
    num_hosts: Optional[int] = None,
    timeout_s: float = 30.0,
    poll_s: float = 0.05,
    sleep: Callable[[float], None] = time.sleep,
) -> int:
    """Blob-store rendezvous for coordinated emergency checkpoints.

    When the TPU runtime preempts a slice it SIGTERMs every host; each
    host's supervisor finishes its in-flight step and must then write
    an emergency checkpoint.  Without coordination the hosts can name
    DIFFERENT steps (one was a step ahead when the signal landed) and
    the resume target becomes ambiguous.  This barrier has every worker
    post its boundary step under `barrier/<run_id>/host_<i>` and wait
    for the full quorum; the agreed commit step is the MAXIMUM posted.
    Hosts behind the maximum can always reach it — the step loop is
    deterministic and their data is local — so the supervisor runs a
    lagging host FORWARD to the agreed step before its emergency save,
    and every host commits the same (newest) state.

    `host_id`/`num_hosts` default to the jax runtime's process index
    and count; a single-process run returns `step` immediately.  The
    deadline is hard: a quorum that never completes (a peer died before
    posting) times out and returns the best agreement so far — during a
    preemption, waiting forever loses the checkpoint entirely, which is
    strictly worse than an unagreed step name.  Deterministic `sleep`
    injection keeps the barrier testable without wall-clock waits.

    Posts persist after agreement (deleting them would race slower
    readers out of their quorum), so every supervisor run() clears
    `barrier/<run_id>/` before training starts — see
    `clear_preemption_barrier` — and `run_id` must be unique per
    logical run on a shared blob root.

    Implementation: one caller of the generalized cross-slice
    rendezvous (topology/rendezvous.py post_and_agree — MAX reduction:
    the newest state any host holds; laggards run deterministically
    forward, never backward), under this barrier's legacy
    `barrier/<run_id>/` key layout.
    """
    from .topology.rendezvous import post_and_agree

    if host_id is None or num_hosts is None:
        import jax

        host_id = jax.process_index() if host_id is None else host_id
        num_hosts = jax.process_count() if num_hosts is None else num_hosts
    if num_hosts <= 1:
        return int(step)
    return post_and_agree(
        blob, run_id, "preemption", int(step),
        host_id=host_id, num_hosts=num_hosts, reduce=max,
        timeout_s=timeout_s, poll_s=poll_s, sleep=sleep,
        prefix=f"barrier/{run_id}/", field="step",
    )


def clear_preemption_barrier(blob, run_id: str) -> int:
    """Remove every post under `barrier/<run_id>/` — called by the
    supervisor at the START of each run so a previous incarnation's
    rendezvous (the preemption this run is resuming from) can never
    satisfy a future quorum with stale steps.  Returns the count
    removed; failures are swallowed (an unreachable store just means
    nothing to clear or a degraded later barrier)."""
    from .store.blobstore import BlobStoreError

    removed = 0
    try:
        for k in blob.list(f"barrier/{run_id}/"):
            if blob.delete(k):
                removed += 1
    except BlobStoreError as e:
        _log.info("preemption-barrier clear failed (%s)", e)
    return removed


def shard_host_batch(
    global_batch: Dict[str, np.ndarray],
    shardings: Dict[str, object],
    global_batch_size: Optional[int] = None,
):
    """Assemble global device arrays from per-host data.

    For batch-sharded inputs each process passes only the rows its
    devices own (`local_batch_slice`); for replicated tensors (e.g.
    labels when the sink keeps them whole) it passes the full array.
    `jax.make_array_from_process_local_data` builds the global array
    either way without cross-host copies — `global_batch_size` (the
    GLOBAL row count) disambiguates the two in multi-process runs.
    Single-host this degenerates to a plain device_put.
    Returns {name: global jax.Array}.
    """
    import jax

    out = {}
    for name, arr in global_batch.items():
        sharding = shardings[name]
        if jax.process_count() == 1:
            out[name] = jax.device_put(arr, sharding)
            continue
        if global_batch_size is None:
            raise ValueError(
                "multi-process shard_host_batch needs global_batch_size "
                "(the GLOBAL row count) to tell host-local slices from "
                "replicated full arrays"
            )
        gshape = (global_batch_size,) + tuple(arr.shape[1:])
        out[name] = jax.make_array_from_process_local_data(
            sharding, arr, global_shape=gshape
        )
    return out


def local_batch_slice(global_batch_size: int, sharding=None) -> slice:
    """Row range of the global batch this host should load (contiguous
    batch-major layout, the SingleDataLoader convention): host i of P
    feeds rows [i*B/P, (i+1)*B/P).

    Pass the tensor's sharding to get the right answer for
    batch-unsharded inputs too: when the BATCH dim is not partitioned
    (this framework's INPUT tensors are replicated — the repartition
    parallel op inside the graph does the sharding — and a
    tensor-parallel input can shard features but not rows), every host
    must feed the full batch and the slice is [0, B)."""
    import jax

    if sharding is not None:
        spec = getattr(sharding, "spec", None)
        batch_unsharded = (
            spec is None or len(spec) == 0 or spec[0] is None
        )
        if batch_unsharded or getattr(
            sharding, "is_fully_replicated", False
        ):
            return slice(0, global_batch_size)
    p, i = jax.process_count(), jax.process_index()
    if global_batch_size % p != 0:
        raise ValueError(
            f"global batch {global_batch_size} is not divisible by "
            f"{p} processes — rows would be silently dropped"
        )
    per = global_batch_size // p
    return slice(i * per, (i + 1) * per)
