"""Multi-host bring-up: the package-level equivalent of the reference's
mpirun launcher path (/root/reference/python/flexflow/driver.py spawns
`mpirun ... flexflow_python`; tests/multinode_helpers/mpi_wrapper1.sh
wires per-rank env).  TPU-native there is no launcher to exec: every
host runs the same script, `jax.distributed` joins them into one
runtime, and XLA SPMD spans all chips.  This module owns that join plus
the per-host batch-feeding helper the docs previously asked users to
hand-write (docs/MULTI-NODE.md).
"""
from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import numpy as np

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> bool:
    """Join this process into the multi-host jax runtime.

    Call first thing in the training script, before any other jax use.
    Resolution order mirrors the launch recipes users actually have:

      1. explicit args (manual bring-up / custom schedulers);
      2. env vars ``FLEXFLOW_COORDINATOR`` / ``FLEXFLOW_NUM_PROCS`` /
         ``FLEXFLOW_PROC_ID``, or the standard OMPI rank vars when
         launched under mpirun (the reference's launcher convention);
      3. no information at all -> ``jax.distributed.initialize()``,
         which autodetects on Cloud TPU pods and is skipped entirely
         when that autodetection cannot apply (single-process dev).

    Returns True when a multi-process runtime was initialized, False
    for the harmless single-process fallback.  Idempotent.
    """
    global _initialized
    import jax

    if _initialized:
        return jax.process_count() > 1

    if coordinator_address is None:
        coordinator_address = os.environ.get("FLEXFLOW_COORDINATOR")
    if num_processes is None:
        np_env = os.environ.get(
            "FLEXFLOW_NUM_PROCS", os.environ.get("OMPI_COMM_WORLD_SIZE")
        )
        num_processes = int(np_env) if np_env else None
    if process_id is None:
        pid_env = os.environ.get(
            "FLEXFLOW_PROC_ID", os.environ.get("OMPI_COMM_WORLD_RANK")
        )
        process_id = int(pid_env) if pid_env is not None else None

    if coordinator_address is None and num_processes is not None \
            and num_processes > 1:
        raise ValueError(
            "multi-process launch needs a coordinator: set "
            "FLEXFLOW_COORDINATOR=<worker0-host:port> (or pass "
            "coordinator_address=)"
        )
    if coordinator_address is not None:
        # explicit configuration: a failure here must NOT degrade to N
        # disjoint single-process runs — let it raise
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
        _initialized = True
        return jax.process_count() > 1
    try:
        # TPU-pod autodetection path; no-op away from a pod
        jax.distributed.initialize()
        _initialized = True
    except Exception:
        # single-process dev environment (no cluster metadata): fine
        _initialized = True
        return False
    return jax.process_count() > 1


def shard_host_batch(
    global_batch: Dict[str, np.ndarray],
    shardings: Dict[str, object],
):
    """Assemble global device arrays from per-host data.

    Each process holds (at least) the rows of the global batch that its
    local devices own; `jax.make_array_from_process_local_data` takes
    this host's slice and the global sharding and builds the global
    array without any cross-host copy.  Single-host this degenerates to
    a plain device_put.  Returns {name: global jax.Array}.
    """
    import jax

    out = {}
    for name, arr in global_batch.items():
        sharding = shardings[name]
        if jax.process_count() == 1:
            out[name] = jax.device_put(arr, sharding)
        else:
            out[name] = jax.make_array_from_process_local_data(
                sharding, arr
            )
    return out


def local_batch_slice(global_batch_size: int) -> slice:
    """Row range of the global batch this host should load (contiguous
    batch-major layout, the SingleDataLoader convention): host i of P
    feeds rows [i*B/P, (i+1)*B/P)."""
    import jax

    p, i = jax.process_count(), jax.process_index()
    if global_batch_size % p != 0:
        raise ValueError(
            f"global batch {global_batch_size} is not divisible by "
            f"{p} processes — rows would be silently dropped"
        )
    per = global_batch_size // p
    return slice(i * per, (i + 1) * per)
