"""Data loading: full-dataset host ingest + prefetched sharded batches.

Counterpart of the reference's SingleDataLoader
(python/flexflow_dataloader.h:34-116, .cc/.cu): the reference stages the
whole numpy dataset into zero-copy memory once, then per batch launches
index tasks that copy sample slices to each GPU.  TPU-native: batches
are assembled host-side (native C++ gather when available — see
native/dataloader.cc — else numpy) and `jax.device_put` with the
executor's input NamedShardings; a background thread keeps a bounded
queue of device-resident batches so host assembly and the H2D transfer
overlap the jitted step.
"""
from __future__ import annotations

import ctypes
import queue
import threading
from typing import Dict, Iterator, Optional, Tuple, Union

import numpy as np

from .native import get_lib


def _native_shuffle(n: int, seed: int) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None or not hasattr(lib, "ffdl_shuffle_indices"):
        return None
    idx = np.empty(n, dtype=np.int64)
    lib.ffdl_shuffle_indices(
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(n),
        ctypes.c_uint64(seed),
    )
    return idx


def _py_shuffle(n: int, seed: int) -> np.ndarray:
    """Python mirror of ffdl_shuffle_indices (same xorshift64 PRNG)."""
    idx = np.arange(n, dtype=np.int64)
    s = np.uint64(seed if seed else 0x9E3779B97F4A7C15)
    mask = np.uint64(0xFFFFFFFFFFFFFFFF)
    for i in range(n - 1, 0, -1):
        s = (s ^ (s << np.uint64(13))) & mask
        s = s ^ (s >> np.uint64(7))
        s = (s ^ (s << np.uint64(17))) & mask
        j = int(s % np.uint64(i + 1))
        idx[i], idx[j] = idx[j], idx[i]
    return idx


def shuffle_indices(n: int, seed: int) -> np.ndarray:
    out = _native_shuffle(n, seed)
    return out if out is not None else _py_shuffle(n, seed)


def _gather(src: np.ndarray, indices: np.ndarray) -> np.ndarray:
    """Batch-assembly gather; native path releases the GIL."""
    src = np.ascontiguousarray(src)
    lib = get_lib()
    if lib is not None and hasattr(lib, "ffdl_gather_rows") and src.ndim >= 1:
        row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
        dst = np.empty((len(indices),) + src.shape[1:], dtype=src.dtype)
        rc = lib.ffdl_gather_rows(
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64(src.shape[0]),
            ctypes.c_int64(row_bytes),
            np.ascontiguousarray(indices, dtype=np.int64).ctypes.data_as(
                ctypes.POINTER(ctypes.c_int64)
            ),
            ctypes.c_int64(len(indices)),
            dst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        if rc == 0:
            return dst
    return np.take(src, indices, axis=0)


class SingleDataLoader:
    """Batched, optionally shuffled, prefetching loader bound to a
    compiled FFModel's input shardings.

    API parity: num_samples/next_batch/reset
    (flexflow_dataloader.h:34-116); adds `__iter__` epochs and
    background device prefetch (capability the reference gets from
    Legion's async index tasks).
    """

    def __init__(
        self,
        ff,
        x: Union[np.ndarray, Dict[str, np.ndarray]],
        y: np.ndarray,
        batch_size: Optional[int] = None,
        shuffle: bool = False,
        seed: int = 0,
        prefetch: int = 2,
    ):
        self.ff = ff
        input_ops = ff.layers.source_ops()
        if isinstance(x, dict):
            self.x_map = {k: np.ascontiguousarray(v) for k, v in x.items()}
        else:
            self.x_map = {input_ops[0].name: np.ascontiguousarray(x)}
        self.y = np.ascontiguousarray(y)
        self.num_samples = len(self.y)
        for k, v in self.x_map.items():
            if len(v) != self.num_samples:
                raise ValueError(
                    f"input {k} has {len(v)} samples, labels have {self.num_samples}"
                )
        self.batch_size = batch_size or ff.config.batch_size
        self.num_batches = self.num_samples // self.batch_size
        if self.num_batches == 0:
            raise ValueError(
                f"dataset of {self.num_samples} samples smaller than batch "
                f"size {self.batch_size}"
            )
        self.shuffle = shuffle
        self.seed = seed
        self.prefetch = max(1, prefetch)
        self._epoch = -1  # first reset() brings it to 0
        self._order = np.arange(self.num_samples, dtype=np.int64)
        self._queue: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._next_index = 0
        self.reset()

    # -- reference API --------------------------------------------------
    def reset(self):
        """Start the next epoch (flexflow_dataloader.h:50) — each call
        advances the shuffle order."""
        self._stop_worker()
        self._epoch += 1
        if self.shuffle:
            self._order = shuffle_indices(
                self.num_samples, self.seed + self._epoch + 1
            )
        self._next_index = 0
        self._queue = queue.Queue(maxsize=self.prefetch)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._worker, args=(self._queue, self._stop), daemon=True
        )
        self._thread.start()

    def next_batch(self) -> Tuple[Dict[str, object], object]:
        """Device-resident (inputs, labels) for the next batch."""
        if self._next_index >= self.num_batches:
            raise StopIteration
        self._next_index += 1
        item = self._queue.get()
        if isinstance(item, Exception):
            raise item
        return item

    # -- iteration ------------------------------------------------------
    def __iter__(self) -> Iterator[Tuple[Dict[str, object], object]]:
        if self._next_index > 0 or self._thread is None:
            self.reset()
        for _ in range(self.num_batches):
            yield self.next_batch()

    def __len__(self) -> int:
        return self.num_batches

    # -- internals ------------------------------------------------------
    def _worker(self, out_queue: "queue.Queue", stop: threading.Event):
        import jax

        try:
            in_sh = self.ff.executor.input_shardings()
            lab_sh = self.ff.executor.label_sharding()
            for b in range(self.num_batches):
                if stop.is_set():
                    return
                idx = self._order[b * self.batch_size:(b + 1) * self.batch_size]
                inputs = {
                    k: jax.device_put(_gather(v, idx), in_sh[k])
                    for k, v in self.x_map.items()
                }
                labels = jax.device_put(_gather(self.y, idx), lab_sh)
                while not stop.is_set():
                    try:
                        out_queue.put((inputs, labels), timeout=0.1)
                        break
                    except queue.Full:
                        pass
        except Exception as e:  # surfaced on next_batch
            out_queue.put(e)

    def _stop_worker(self):
        t = self._thread
        if t is not None and t.is_alive():
            # signal cancellation — the worker exits after at most the
            # one batch it is currently assembling
            self._stop.set()
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=30.0)
        self._thread = None
