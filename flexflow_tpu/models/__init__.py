from .alexnet import build_alexnet
from .candle_uno import build_candle_uno
from .dlrm import build_dlrm, build_xdl
from .inception import build_inception_v3
from .mlp import build_mlp_unify
from .moe import build_moe_encoder, build_moe_mlp
from .nmt import build_nmt
from .resnet import build_resnet50, build_resnext50
from .transformer import build_bert, build_gpt, build_transformer
