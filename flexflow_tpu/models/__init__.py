from .alexnet import build_alexnet
from .transformer import build_bert, build_transformer
