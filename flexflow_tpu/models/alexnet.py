"""AlexNet (CIFAR-10 variant).

Mirrors the reference's bootcamp demo / C++ example
(/root/reference/examples/cpp/AlexNet/alexnet.cc,
bootcamp_demo/ff_alexnet_cifar10.py) — the minimum-slice model of
BASELINE.md (pure DP, loss decreases).
"""
from __future__ import annotations

from ..fftype import ActiMode
from ..model import FFModel


def build_alexnet(ff: FFModel, batch_size: int = 64, num_classes: int = 10,
                  image_size: int = 32):
    t = ff.create_tensor([batch_size, 3, image_size, image_size], name="input")
    t = ff.conv2d(t, 64, 11, 11, 4, 4, 2, 2, activation=ActiMode.RELU, name="conv1")
    t = ff.pool2d(t, 3, 3, 2, 2, name="pool1")
    t = ff.conv2d(t, 192, 5, 5, 1, 1, 2, 2, activation=ActiMode.RELU, name="conv2")
    t = ff.pool2d(t, 3, 3, 2, 2, name="pool2")
    t = ff.conv2d(t, 384, 3, 3, 1, 1, 1, 1, activation=ActiMode.RELU, name="conv3")
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, activation=ActiMode.RELU, name="conv4")
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, activation=ActiMode.RELU, name="conv5")
    t = ff.pool2d(t, 3, 3, 2, 2, name="pool5")
    t = ff.flat(t, name="flat")
    t = ff.dense(t, 4096, activation=ActiMode.RELU, name="fc6")
    t = ff.dense(t, 4096, activation=ActiMode.RELU, name="fc7")
    t = ff.dense(t, num_classes, name="fc8")
    t = ff.softmax(t, name="softmax")
    return t
