"""DLRM and XDL recommender builders.

Parity with /root/reference/examples/cpp/DLRM/dlrm.cc:44-170 and
/root/reference/examples/cpp/XDL/xdl.cc:40-145.  The reference shards
the big embedding tables over devices via attribute parallelism
(embedding.cc:132-141); in the TPU build that is ShardConfig's
attribute degree on the vocab dim, lowering to an all-to-all over ICI.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

from ..fftype import ActiMode, AggrMode
from ..initializer import UniformInitializer
from ..model import FFModel


def _mlp(ff: FFModel, t, dims: Sequence[int], sigmoid_layer: int,
         prefix: str):
    """create_mlp (dlrm.cc:44-70, xdl.cc:38-59): ReLU stack with one
    sigmoid layer.  `dims` lists output widths only (the reference's `ln`
    includes the input dim, so its layer i == our i)."""
    for i, d in enumerate(dims):
        act = ActiMode.SIGMOID if i == sigmoid_layer else ActiMode.RELU
        t = ff.dense(t, d, activation=act, use_bias=False, name=f"{prefix}_{i}")
    return t


def _embedding(ff: FFModel, input, vocab: int, dim: int, name: str):
    # create_emb (dlrm.cc:72-82): uniform +/- sqrt(1/vocab)
    rng = math.sqrt(1.0 / vocab)
    init = UniformInitializer(minv=-rng, maxv=rng)
    return ff.embedding(input, vocab, dim, aggr=AggrMode.SUM,
                        kernel_initializer=init, name=name)


def build_dlrm(
    ff: FFModel,
    batch_size: int = 64,
    embedding_size: Sequence[int] = (1000000, 1000000, 1000000, 1000000),
    embedding_bag_size: int = 1,
    sparse_feature_size: int = 64,
    dense_feature_dim: int = 64,
    mlp_bot: Optional[Sequence[int]] = None,
    mlp_top: Optional[Sequence[int]] = None,
):
    """dense MLP-bot + per-table embeddings -> concat interaction -> MLP-top
    with sigmoid on the final layer (dlrm.cc:84-170, interaction 'cat',
    LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE)."""
    # reference defaults dlrm.cc:26-29: ln vectors include the input dim,
    # so mlp_top {64,64,2} is the 2-layer width list [64, 2]
    mlp_bot = list(mlp_bot or [sparse_feature_size, sparse_feature_size])
    mlp_top = list(mlp_top or [64, 2])

    sparse_inputs = [
        ff.create_tensor([batch_size, embedding_bag_size], dtype="int32",
                         name=f"sparse_input_{i}")
        for i in range(len(embedding_size))
    ]
    dense_input = ff.create_tensor([batch_size, dense_feature_dim],
                                   name="dense_input")

    x = _mlp(ff, dense_input, mlp_bot, sigmoid_layer=-1, prefix="bot")
    ly: List = [
        _embedding(ff, si, embedding_size[i], sparse_feature_size,
                   name=f"embedding_{i}")
        for i, si in enumerate(sparse_inputs)
    ]
    z = ff.concat([x] + ly, axis=-1, name="interact_cat")
    # reference passes mlp_top.size()-2, the last index of its ln-based
    # loop — i.e. the final layer is the sigmoid one
    p = _mlp(ff, z, mlp_top, sigmoid_layer=len(mlp_top) - 1, prefix="top")
    return p


def build_xdl(
    ff: FFModel,
    batch_size: int = 64,
    embedding_size: Sequence[int] = (1000000, 1000000, 1000000, 1000000),
    embedding_bag_size: int = 1,
    sparse_feature_size: int = 64,
    mlp_dims: Optional[Sequence[int]] = None,
):
    """XDL: concat(embeddings) -> MLP with sigmoid final layer
    (xdl.cc:120-145, LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE)."""
    # xdl.cc mlp {256,256,256,2} includes the input dim -> widths [256,256,2]
    mlp_dims = list(mlp_dims or [256, 256, 2])
    sparse_inputs = [
        ff.create_tensor([batch_size, embedding_bag_size], dtype="int32",
                         name=f"sparse_input_{i}")
        for i in range(len(embedding_size))
    ]
    ly = [
        _embedding(ff, si, embedding_size[i], sparse_feature_size,
                   name=f"embedding_{i}")
        for i, si in enumerate(sparse_inputs)
    ]
    t = ff.concat(ly, axis=-1, name="concat")
    return _mlp(ff, t, mlp_dims, sigmoid_layer=len(mlp_dims) - 1, prefix="mlp")
