"""Transformer / BERT model builders.

`build_transformer` mirrors the reference's Transformer example
(/root/reference/examples/cpp/Transformer/transformer.cc:112-215 —
create_attention_encoder: multihead_attention + two dense layers, no
norm/residual; default cfg at transformer.cc:79-85).

`build_bert` is the BERT-base north-star config (BASELINE.md): post-LN
encoder blocks (attention + residual, then layernorm; 4x GELU FFN),
which is both the real workload and the TP/SP search target.
"""
from __future__ import annotations

from ..fftype import ActiMode
from ..model import FFModel


def build_transformer(
    ff: FFModel,
    batch_size: int = 8,
    seq_length: int = 512,
    hidden_size: int = 1024,
    num_layers: int = 12,
    num_heads: int = 16,
):
    """The reference example: N x (attention -> dense(relu) -> dense)."""
    t = ff.create_tensor([batch_size, seq_length, hidden_size], name="input")
    for i in range(num_layers):
        a = ff.multihead_attention(
            t, t, t, hidden_size, num_heads, name=f"attn_{i}"
        )
        h = ff.dense(a, hidden_size, activation=ActiMode.RELU, name=f"ffn1_{i}")
        t = ff.dense(h, hidden_size, name=f"ffn2_{i}")
    out = ff.dense(t, 1, name="lm_head")
    return out


def build_bert(
    ff: FFModel,
    batch_size: int = 32,
    seq_length: int = 128,
    hidden_size: int = 768,
    num_layers: int = 12,
    num_heads: int = 12,
    intermediate_size: int = 3072,
    vocab_size: int = 30522,
    num_classes: int = 2,
    dropout: float = 0.0,
    from_token_ids: bool = False,
):
    """BERT-base encoder stack with a classification head."""
    if from_token_ids:
        ids = ff.create_tensor([batch_size, seq_length], dtype="int32", name="input")
        t = ff.embedding(ids, vocab_size, hidden_size, name="tok_embed")
    else:
        t = ff.create_tensor([batch_size, seq_length, hidden_size], name="input")
    for i in range(num_layers):
        # attention block (post-LN, BERT style)
        a = ff.multihead_attention(
            t, t, t, hidden_size, num_heads, dropout=dropout, name=f"attn_{i}"
        )
        t = ff.add(t, a, name=f"attn_res_{i}")
        t = ff.layer_norm(t, axes=[-1], name=f"attn_ln_{i}")
        # FFN block
        h = ff.dense(t, intermediate_size, activation=ActiMode.GELU, name=f"ffn1_{i}")
        h = ff.dense(h, hidden_size, name=f"ffn2_{i}")
        t = ff.add(t, h, name=f"ffn_res_{i}")
        t = ff.layer_norm(t, axes=[-1], name=f"ffn_ln_{i}")
    # classifier on mean-pooled sequence
    pooled = ff.mean(t, axes=[1], name="pool")
    logits = ff.dense(pooled, num_classes, name="classifier")
    return logits


def bert_tp_strategy(num_devices: int, tp: int = 2, num_layers: int = 12):
    """Hybrid DP x TP strategy for build_bert: attention heads and FFN
    out-channels column-parallel on the model axis, second FFN matmul
    row-parallel automatically, batch data-parallel."""
    from ..ops.op import ShardConfig
    from ..strategy import Strategy

    dp = num_devices // tp
    s = Strategy(mesh_axes={"data": dp, "model": tp})
    s.edge_ops["__inputs__"] = [("repartition", {"dim": 0, "degree": dp})]
    for i in range(num_layers):
        s.shard_configs[f"attn_{i}"] = ShardConfig(channel=tp)
        s.shard_configs[f"ffn1_{i}"] = ShardConfig(channel=tp)
    return s


def bert_sp_strategy(num_devices: int, sp: int = 4):
    """Hybrid DP x SP (context-parallel) strategy: the sequence dim of
    every activation is sharded over the "seq" axis and attention runs
    as ring attention over ICI (parallel/ring_attention.py) — the
    long-context capability slot the reference lacks (SURVEY §5)."""
    from ..strategy import Strategy

    if sp < 1 or num_devices % sp != 0:
        raise ValueError(
            f"num_devices {num_devices} not divisible by sp degree {sp}"
        )
    dp = num_devices // sp
    s = Strategy(mesh_axes={"data": dp, "seq": sp})
    chain = []
    if dp > 1:
        chain.append(("repartition", {"dim": 0, "degree": dp}))
    chain.append(("repartition", {"dim": 1, "degree": sp}))
    s.edge_ops["__inputs__"] = chain
    return s


def build_gpt(
    ff: FFModel,
    batch_size: int = 8,
    seq_length: int = 1024,
    hidden_size: int = 768,
    num_layers: int = 12,
    num_heads: int = 12,
    intermediate_size: int = 3072,
    vocab_size: int = 50257,
    dropout: float = 0.0,
    max_positions: int = 0,
    decode_max_seq: int = 0,
    kv_page_size: int = 0,
    kv_num_blocks: int = 0,
    kv_kernel: str = "gather",
):
    """Decoder-only causal LM (pre-LN GPT-2 shape) — a model family
    BEYOND the reference's zoo (its transformer example is encoder-only,
    examples/cpp/Transformer/transformer.cc): token ids + position ids
    -> embeddings -> N x [LN -> causal attention -> residual;
    LN -> GELU MLP -> residual] -> final LN -> untied LM head.

    Layer names reuse the attn_{i}/ffn1_{i} convention so
    bert_tp_strategy/bert_sp_strategy apply unchanged (causal ring
    attention handles the sharded-sequence case).  Train with
    labels = ids shifted left one position (next-token prediction);
    the sparse-CE loss consumes [b, s, vocab] logits and [b, s] ids.
    """
    ids = ff.create_tensor([batch_size, seq_length], dtype="int32",
                           name="input")
    pos = ff.create_tensor([batch_size, seq_length], dtype="int32",
                           name="positions")
    t = ff.embedding(ids, vocab_size, hidden_size, name="tok_embed")
    # max_positions decouples the position table from the graph's seq
    # length so a seq-1 KV-cache decode graph shares the trained table
    pe = ff.embedding(pos, max_positions or seq_length, hidden_size,
                      name="pos_embed")
    t = ff.add(t, pe, name="embed_sum")
    for i in range(num_layers):
        a = ff.layer_norm(t, axes=[-1], name=f"ln1_{i}")
        a = ff.multihead_attention(
            a, a, a, hidden_size, num_heads, dropout=dropout,
            causal=True, name=f"attn_{i}",
            decode_max_seq=decode_max_seq,
            kv_page_size=kv_page_size, kv_num_blocks=kv_num_blocks,
            kv_kernel=kv_kernel,
        )
        t = ff.add(t, a, name=f"attn_res_{i}")
        h = ff.layer_norm(t, axes=[-1], name=f"ln2_{i}")
        h = ff.dense(h, intermediate_size, activation=ActiMode.GELU,
                     name=f"ffn1_{i}")
        h = ff.dense(h, hidden_size, name=f"ffn2_{i}")
        t = ff.add(t, h, name=f"ffn_res_{i}")
    t = ff.layer_norm(t, axes=[-1], name="final_ln")
    logits = ff.dense(t, vocab_size, use_bias=False, name="lm_head")
    return logits


def gpt_generate(ff: FFModel, prompt_ids, max_new_tokens: int,
                 temperature: float = 0.0, seed: int = 0,
                 top_k: int = 0, top_p: float = 0.0):
    """Autoregressive generation with the compiled fixed-shape GPT
    graph: right-pad the prompt to the model's seq_length, re-run the
    forward per emitted token, and feed back the sampled id
    (temperature 0 = greedy argmax).  The causal mask makes padding
    beyond the current position irrelevant to the next-token logits.
    O(T^2) utility loop like models/nmt.greedy_decode — correct, not a
    KV-cache serving path.

    Sampling controls compose the usual way: logits/temperature, then
    top_k (keep the k most likely ids, 0 = off), then top_p nucleus
    filtering (smallest sorted prefix with mass >= top_p, 0 = off);
    both apply only when temperature > 0.

    prompt_ids: [batch, prompt_len] ints.  Returns [batch,
    prompt_len + max_new_tokens] (truncated at the model's seq_length).
    """
    import numpy as np

    prompt_ids = np.asarray(prompt_ids, np.int32)
    validate_sampling(top_k, top_p)
    ids_src = next(op for op in ff.layers.source_ops()
                   if op.name == "input")
    seq_len = ids_src.outputs[0].shape.logical_shape[1]
    prompt_ids = prompt_ids[:, :seq_len]  # docstring contract
    batch, plen = prompt_ids.shape
    if plen < 1:
        raise ValueError("gpt_generate needs a non-empty prompt")
    total = min(seq_len, plen + max_new_tokens)
    buf = np.zeros((batch, seq_len), np.int32)
    buf[:, :plen] = prompt_ids
    pos = np.tile(np.arange(seq_len, dtype=np.int32), (batch, 1))
    rng = np.random.RandomState(seed)
    for t in range(plen, total):
        logits = np.asarray(
            ff.forward({"input": buf, "positions": pos}), np.float32)
        step = logits[:, t - 1]  # next-token distribution at position t-1
        buf[:, t] = sample_next(step, temperature, rng, top_k, top_p)
    return buf[:, :total]


def validate_sampling(top_k: int, top_p: float):
    if top_k < 0 or not 0.0 <= top_p <= 1.0:
        raise ValueError(f"invalid sampling filter: top_k={top_k} "
                         f"top_p={top_p}")


def sample_next(step_logits, temperature: float, rng, top_k: int = 0,
                top_p: float = 0.0):
    """Sample next-token ids from [batch, vocab] logits (numpy host
    path shared by gpt_generate and the KV-cache decoder): temperature,
    then top_k, then top_p nucleus; temperature 0 = greedy."""
    import numpy as np

    if temperature <= 0.0:
        return step_logits.argmax(-1).astype(np.int32)
    # float32, matching the pre-extraction inline path: seeded runs
    # recorded against it stay reproducible (np.random.choice converts
    # p to double internally, so the f32 sum-to-1 rounding is tolerated)
    z = np.asarray(step_logits, np.float32) / temperature
    if top_k and top_k < z.shape[-1]:
        # keep the k most likely ids per row
        kth = np.partition(z, -top_k, axis=-1)[:, -top_k, None]
        z = np.where(z < kth, -np.inf, z)
    z = z - z.max(-1, keepdims=True)
    p = np.exp(z)
    p /= p.sum(-1, keepdims=True)
    if top_p and 0.0 < top_p < 1.0:
        # nucleus: smallest sorted prefix with mass >= top_p
        order = np.argsort(-p, axis=-1)
        sp = np.take_along_axis(p, order, -1)
        drop_sorted = np.cumsum(sp, axis=-1) - sp >= top_p
        drop = np.zeros_like(drop_sorted)
        np.put_along_axis(drop, order, drop_sorted, -1)
        p = np.where(drop, 0.0, p)
        p /= p.sum(-1, keepdims=True)
    return np.array([rng.choice(p.shape[-1], p=p[b])
                     for b in range(p.shape[0])], np.int32)


def gpt_beam_search(ff: FFModel, prompt_ids, max_new_tokens: int,
                    beam_size: int = 4, length_penalty: float = 0.0,
                    eos_id: int = -1):
    """Beam-search decoding on the compiled fixed-shape GPT graph
    (beyond the reference: its legacy nmt/ decoder is greedy-only).

    O(T^2) reference implementation: it re-runs the full forward per
    emitted token and takes one prompt.  The serving path is
    decoding.gpt_beam_search_cached — O(T) on the KV-cache decode twin,
    batched over prompts, equality-tested against this function.

    Beams ride the model's batch dimension: all `beam_size` hypotheses
    of one prompt decode in a single forward per step, so the compiled
    batch size must be >= beam_size (extra rows are padding).  Scores
    are summed token log-probs; `length_penalty` applies the GNMT
    normalization ((5+len)/6)^lp to final scores; `eos_id` >= 0
    freezes finished beams (they compete with their frozen score).

    prompt_ids: [prompt_len] or [1, prompt_len] ints (single prompt).
    Returns (tokens [total_len], score float).
    """
    import numpy as np

    prompt_ids = np.asarray(prompt_ids, np.int32).reshape(1, -1)
    ids_src = next(op for op in ff.layers.source_ops()
                   if op.name == "input")
    model_batch = ids_src.outputs[0].shape.logical_shape[0]
    seq_len = ids_src.outputs[0].shape.logical_shape[1]
    if beam_size > model_batch:
        raise ValueError(
            f"beam_size {beam_size} exceeds compiled batch {model_batch}")
    prompt_ids = prompt_ids[:, :seq_len]
    plen = prompt_ids.shape[1]
    if plen < 1:
        raise ValueError("gpt_beam_search needs a non-empty prompt")
    total = min(seq_len, plen + max_new_tokens)

    buf = np.zeros((model_batch, seq_len), np.int32)
    buf[:beam_size, :plen] = prompt_ids  # every beam starts from the prompt
    pos = np.tile(np.arange(seq_len, dtype=np.int32), (model_batch, 1))
    scores = np.full(beam_size, -np.inf, np.float64)
    scores[0] = 0.0  # step 1: only one distinct hypothesis exists
    alive = np.ones(beam_size, bool)
    gen_len = np.zeros(beam_size, np.int64)  # emitted tokens per beam

    for t in range(plen, total):
        logits = np.asarray(
            ff.forward({"input": buf, "positions": pos}), np.float32)
        step = logits[:beam_size, t - 1]
        z = step - step.max(-1, keepdims=True)
        lp = z - np.log(np.exp(z).sum(-1, keepdims=True))  # [beam, vocab]
        vocab = lp.shape[-1]
        cand = scores[:, None] + np.where(alive[:, None], lp, -np.inf)
        if eos_id >= 0 and not alive.all():
            # a finished beam competes as one stay-put candidate
            cand[~alive, :] = -np.inf
            cand[~alive, 0] = scores[~alive]
        flat = cand.reshape(-1)
        top = np.argsort(-flat)[:beam_size]
        src_beam, tok = top // vocab, (top % vocab).astype(np.int32)
        new_buf = buf[:beam_size][src_beam].copy()
        new_alive = alive[src_beam].copy()
        new_buf[new_alive, t] = tok[new_alive]  # frozen beams keep padding
        gen_len = gen_len[src_beam] + new_alive  # explicit per-beam length
        if eos_id >= 0:
            new_alive &= tok != eos_id
        buf[:beam_size] = new_buf
        scores = flat[top]
        alive = new_alive
        if eos_id >= 0 and not alive.any():
            break
    if length_penalty > 0.0:
        norm = ((5.0 + np.maximum(gen_len, 1).astype(np.float64)) / 6.0) \
            ** length_penalty
        best = int(np.argmax(scores / norm))
    else:
        best = int(np.argmax(scores))
    return buf[best, :total].copy(), float(scores[best])
