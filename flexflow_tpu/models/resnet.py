"""ResNet-50 and ResNeXt-50 builders.

Parity with the reference C++ examples
(/root/reference/examples/cpp/ResNet/resnet.cc:38-113,
/root/reference/examples/cpp/resnext50/resnext.cc:13-88) expressed
through the FFModel layer API; convs lower to
`lax.conv_general_dilated` so XLA tiles them onto the MXU.

Builders are size-parameterized: default configs match the reference
(input 3x229x229 / 3x224x224, [3,4,6,3] stages); tests pass tiny
image sizes and stage depths.
"""
from __future__ import annotations

from typing import Sequence

from ..fftype import ActiMode
from ..model import FFModel


def _channels(t) -> int:
    return t.shape.logical_shape[1]  # NCHW


def bottleneck_block(ff: FFModel, input, out_channels: int, stride: int):
    """1x1 -> 3x3(stride) -> 1x1(4x) with projection shortcut
    (resnet.cc:38-55)."""
    t = ff.conv2d(input, out_channels, 1, 1, 1, 1, 0, 0)
    t = ff.conv2d(t, out_channels, 3, 3, stride, stride, 1, 1)
    t = ff.conv2d(t, 4 * out_channels, 1, 1, 1, 1, 0, 0)
    if stride > 1 or _channels(input) != 4 * out_channels:
        input = ff.conv2d(input, 4 * out_channels, 1, 1, stride, stride, 0, 0)
    t = ff.add(input, t)
    return ff.relu(t, inplace=False)


def build_resnet50(
    ff: FFModel,
    batch_size: int = 64,
    num_classes: int = 10,
    image_size: int = 229,
    stage_blocks: Sequence[int] = (3, 4, 6, 3),
    base_channels: int = 64,
):
    t = ff.create_tensor([batch_size, 3, image_size, image_size], name="input")
    t = ff.conv2d(t, base_channels, 7, 7, 2, 2, 3, 3, name="stem_conv")
    t = ff.pool2d(t, 3, 3, 2, 2, 1, 1, name="stem_pool")
    ch = base_channels
    for stage, blocks in enumerate(stage_blocks):
        for i in range(blocks):
            stride = 2 if (stage > 0 and i == 0) else 1
            t = bottleneck_block(ff, t, ch, stride)
        ch *= 2
    h = t.shape.logical_shape[2]
    w = t.shape.logical_shape[3]
    t = ff.pool2d(t, h, w, 1, 1, 0, 0, pool_type="avg", name="head_pool")
    t = ff.flat(t)
    t = ff.dense(t, num_classes, name="fc")
    return ff.softmax(t, name="softmax")


def resnext_block(ff: FFModel, input, stride: int, out_channels: int,
                  groups: int, has_residual: bool = False):
    """Grouped 3x3 bottleneck (resnext.cc:13-32)."""
    t = ff.conv2d(input, out_channels, 1, 1, 1, 1, 0, 0, activation=ActiMode.RELU)
    t = ff.conv2d(t, out_channels, 3, 3, stride, stride, 1, 1,
                  activation=ActiMode.RELU, groups=groups)
    t = ff.conv2d(t, 2 * out_channels, 1, 1, 1, 1, 0, 0)
    if (stride > 1 or _channels(input) != 2 * out_channels) and has_residual:
        input = ff.conv2d(input, 2 * out_channels, 1, 1, stride, stride, 0, 0,
                          activation=ActiMode.RELU)
        t = ff.relu(ff.add(input, t), inplace=False)
    return t


def build_resnext50(
    ff: FFModel,
    batch_size: int = 16,
    num_classes: int = 1000,
    image_size: int = 224,
    stage_blocks: Sequence[int] = (3, 4, 6, 3),
    groups: int = 32,
    base_channels: int = 128,
):
    """ResNeXt-50 (32x4d) per resnext.cc:55-88."""
    t = ff.create_tensor([batch_size, 3, image_size, image_size], name="input")
    t = ff.conv2d(t, 64, 7, 7, 2, 2, 3, 3, activation=ActiMode.RELU, name="stem_conv")
    t = ff.pool2d(t, 3, 3, 2, 2, 1, 1, name="stem_pool")
    ch = base_channels
    for stage, blocks in enumerate(stage_blocks):
        for i in range(blocks):
            stride = 2 if (stage > 0 and i == 0) else 1
            t = resnext_block(ff, t, stride, ch, groups)
        ch *= 2
    t = ff.relu(t, inplace=False)
    h = t.shape.logical_shape[2]
    w = t.shape.logical_shape[3]
    t = ff.pool2d(t, h, w, 1, 1, 0, 0, pool_type="avg", name="head_pool")
    t = ff.flat(t)
    t = ff.dense(t, num_classes, name="fc")
    return ff.softmax(t, name="softmax")
