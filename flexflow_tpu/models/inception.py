"""InceptionV3 builder.

Parity with /root/reference/examples/cpp/InceptionV3/inception.cc:26-176.
The asymmetric 1x7/7x1 factorized convs are kept — XLA fuses the relu
into the conv epilogue and tiles each onto the MXU; concat along the
channel dim stays a pure layout op.

`channel_scale` shrinks every channel count for tiny test configs.
"""
from __future__ import annotations

from ..fftype import ActiMode
from ..model import FFModel

RELU = ActiMode.RELU


def _c(scale: float, n: int) -> int:
    return max(1, int(n * scale))


def inception_a(ff: FFModel, x, pool_features: int, s: float = 1.0):
    t1 = ff.conv2d(x, _c(s, 64), 1, 1, 1, 1, 0, 0, activation=RELU)
    t2 = ff.conv2d(x, _c(s, 48), 1, 1, 1, 1, 0, 0, activation=RELU)
    t2 = ff.conv2d(t2, _c(s, 64), 5, 5, 1, 1, 2, 2, activation=RELU)
    t3 = ff.conv2d(x, _c(s, 64), 1, 1, 1, 1, 0, 0, activation=RELU)
    t3 = ff.conv2d(t3, _c(s, 96), 3, 3, 1, 1, 1, 1, activation=RELU)
    t3 = ff.conv2d(t3, _c(s, 96), 3, 3, 1, 1, 1, 1, activation=RELU)
    t4 = ff.pool2d(x, 3, 3, 1, 1, 1, 1, pool_type="avg")
    t4 = ff.conv2d(t4, _c(s, pool_features), 1, 1, 1, 1, 0, 0, activation=RELU)
    return ff.concat([t1, t2, t3, t4], axis=1)


def inception_b(ff: FFModel, x, s: float = 1.0):
    t1 = ff.conv2d(x, _c(s, 384), 3, 3, 2, 2, 0, 0)
    t2 = ff.conv2d(x, _c(s, 64), 1, 1, 1, 1, 0, 0)
    t2 = ff.conv2d(t2, _c(s, 96), 3, 3, 1, 1, 1, 1)
    t2 = ff.conv2d(t2, _c(s, 96), 3, 3, 2, 2, 0, 0)
    t3 = ff.pool2d(x, 3, 3, 2, 2, 0, 0)
    return ff.concat([t1, t2, t3], axis=1)


def inception_c(ff: FFModel, x, channels: int, s: float = 1.0):
    c = _c(s, channels)
    t1 = ff.conv2d(x, _c(s, 192), 1, 1, 1, 1, 0, 0)
    t2 = ff.conv2d(x, c, 1, 1, 1, 1, 0, 0)
    t2 = ff.conv2d(t2, c, 1, 7, 1, 1, 0, 3)
    t2 = ff.conv2d(t2, _c(s, 192), 7, 1, 1, 1, 3, 0)
    t3 = ff.conv2d(x, c, 1, 1, 1, 1, 0, 0)
    t3 = ff.conv2d(t3, c, 7, 1, 1, 1, 3, 0)
    t3 = ff.conv2d(t3, c, 1, 7, 1, 1, 0, 3)
    t3 = ff.conv2d(t3, c, 7, 1, 1, 1, 3, 0)
    t3 = ff.conv2d(t3, _c(s, 192), 1, 7, 1, 1, 0, 3)
    t4 = ff.pool2d(x, 3, 3, 1, 1, 1, 1, pool_type="avg")
    t4 = ff.conv2d(t4, _c(s, 192), 1, 1, 1, 1, 0, 0)
    return ff.concat([t1, t2, t3, t4], axis=1)


def inception_d(ff: FFModel, x, s: float = 1.0):
    t1 = ff.conv2d(x, _c(s, 192), 1, 1, 1, 1, 0, 0)
    t1 = ff.conv2d(t1, _c(s, 320), 3, 3, 2, 2, 0, 0)
    t2 = ff.conv2d(x, _c(s, 192), 1, 1, 1, 1, 0, 0)
    t2 = ff.conv2d(t2, _c(s, 192), 1, 7, 1, 1, 0, 3)
    t2 = ff.conv2d(t2, _c(s, 192), 7, 1, 1, 1, 3, 0)
    t2 = ff.conv2d(t2, _c(s, 192), 3, 3, 2, 2, 0, 0)
    t3 = ff.pool2d(x, 3, 3, 2, 2, 0, 0)
    return ff.concat([t1, t2, t3], axis=1)


def inception_e(ff: FFModel, x, s: float = 1.0):
    t1 = ff.conv2d(x, _c(s, 320), 1, 1, 1, 1, 0, 0)
    t2i = ff.conv2d(x, _c(s, 384), 1, 1, 1, 1, 0, 0)
    t2 = ff.conv2d(t2i, _c(s, 384), 1, 3, 1, 1, 0, 1)
    t3 = ff.conv2d(t2i, _c(s, 384), 3, 1, 1, 1, 1, 0)
    t3i = ff.conv2d(x, _c(s, 448), 1, 1, 1, 1, 0, 0)
    t3i = ff.conv2d(t3i, _c(s, 384), 3, 3, 1, 1, 1, 1)
    t4 = ff.conv2d(t3i, _c(s, 384), 1, 3, 1, 1, 0, 1)
    t5 = ff.conv2d(t3i, _c(s, 384), 3, 1, 1, 1, 1, 0)
    t6 = ff.pool2d(x, 3, 3, 1, 1, 1, 1, pool_type="avg")
    t6 = ff.conv2d(t6, _c(s, 192), 1, 1, 1, 1, 0, 0)
    return ff.concat([t1, t2, t3, t4, t5, t6], axis=1)


def build_inception_v3(
    ff: FFModel,
    batch_size: int = 64,
    num_classes: int = 10,
    image_size: int = 299,
    channel_scale: float = 1.0,
):
    """Full stem + A/B/C/D/E tower (inception.cc:152-176)."""
    s = channel_scale
    t = ff.create_tensor([batch_size, 3, image_size, image_size], name="input")
    t = ff.conv2d(t, _c(s, 32), 3, 3, 2, 2, 0, 0, activation=RELU, name="stem1")
    t = ff.conv2d(t, _c(s, 32), 3, 3, 1, 1, 0, 0, activation=RELU, name="stem2")
    t = ff.conv2d(t, _c(s, 64), 3, 3, 1, 1, 1, 1, activation=RELU, name="stem3")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.conv2d(t, _c(s, 80), 1, 1, 1, 1, 0, 0, activation=RELU, name="stem4")
    t = ff.conv2d(t, _c(s, 192), 3, 3, 1, 1, 1, 1, activation=RELU, name="stem5")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)

    t = inception_a(ff, t, 32, s)
    t = inception_a(ff, t, 64, s)
    t = inception_a(ff, t, 64, s)
    t = inception_b(ff, t, s)
    t = inception_c(ff, t, 128, s)
    t = inception_c(ff, t, 160, s)
    t = inception_c(ff, t, 160, s)
    t = inception_c(ff, t, 192, s)
    t = inception_d(ff, t, s)
    t = inception_e(ff, t, s)
    t = inception_e(ff, t, s)
    h = t.shape.logical_shape[2]
    w = t.shape.logical_shape[3]
    t = ff.pool2d(t, h, w, 1, 1, 0, 0, pool_type="avg", name="head_pool")
    t = ff.flat(t)
    t = ff.dense(t, num_classes, name="fc")
    return ff.softmax(t, name="softmax")
