"""CANDLE-Uno builder (cancer drug response MLP ensemble).

Parity with /root/reference/examples/cpp/candle_uno/candle_uno.cc:27-129:
multiple input feature towers through shared-shape dense stacks, concat,
deep joint MLP, scalar regression head (MSE loss).
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..fftype import ActiMode
from ..model import FFModel


def _feature_tower(ff: FFModel, t, dims: Sequence[int], prefix: str):
    for i, d in enumerate(dims):
        t = ff.dense(t, d, activation=ActiMode.RELU, use_bias=False,
                     name=f"{prefix}_{i}")
    return t


def build_candle_uno(
    ff: FFModel,
    batch_size: int = 64,
    input_dims: Optional[Sequence[int]] = None,
    dense_layers: Optional[Sequence[int]] = None,
    dense_feature_layers: Optional[Sequence[int]] = None,
):
    """Defaults mirror candle_uno.cc:27-36 (4192-wide stacks; shrunk via
    arguments for tests).  input_dims: one entry per feature tower —
    reference uses gene/drug feature sets (candle_uno.cc:105-121)."""
    input_dims = list(input_dims or [942, 5270, 2048])
    dense_layers = list(dense_layers or [4192] * 4)
    dense_feature_layers = list(dense_feature_layers or [4192] * 8)

    encoded = []
    for i, in_dim in enumerate(input_dims):
        inp = ff.create_tensor([batch_size, in_dim], name=f"input_{i}")
        encoded.append(
            _feature_tower(ff, inp, dense_feature_layers, prefix=f"tower_{i}")
        )
    out = ff.concat(encoded, axis=-1, name="concat")
    for i, d in enumerate(dense_layers):
        out = ff.dense(out, d, activation=ActiMode.RELU, use_bias=False,
                       name=f"joint_{i}")
    out = ff.dense(out, 1, use_bias=False, name="head")
    return out
