"""Mixture-of-Experts example builders.

Parity with /root/reference/examples/cpp/mixture_of_experts/moe.cc:
`build_moe_mlp` is the flat MoE classifier (moe.cc:158-165) and
`build_moe_encoder` the transformer encoder with MoE FFN blocks
(moe.cc:100-130).  Expert parallelism comes from sharding the stacked
expert dim of the grouped FFN (ShardConfig.expert -> mesh 'ep' axis);
dispatch/combine are the Pallas/TPU-sort based group_by/aggregate ops.
"""
from __future__ import annotations

from ..fftype import ActiMode
from ..model import FFModel


def build_moe_mlp(
    ff: FFModel,
    batch_size: int = 64,
    input_dim: int = 784,
    num_classes: int = 10,
    num_exp: int = 5,
    num_select: int = 2,
    hidden_size: int = 64,
    alpha: float = 2.0,
    lambda_bal: float = 0.04,
):
    t = ff.create_tensor([batch_size, input_dim], name="input")
    t = ff.moe(t, num_exp, num_select, hidden_size, alpha, lambda_bal)
    t = ff.dense(t, num_classes, activation=ActiMode.RELU, name="head")
    return ff.softmax(t, name="softmax")


def build_moe_encoder(
    ff: FFModel,
    batch_size: int = 8,
    seq_length: int = 128,
    hidden_size: int = 64,
    num_layers: int = 6,
    num_heads: int = 16,
    num_exp: int = 5,
    num_select: int = 2,
    alpha: float = 2.0,
    lambda_bal: float = 0.04,
    num_classes: int = 10,
):
    """Attention + MoE-FFN encoder stack (moe.cc:100-130)."""
    x = ff.create_tensor([batch_size, seq_length, hidden_size], name="input")
    for i in range(num_layers):
        attn = ff.multihead_attention(x, x, x, hidden_size, num_heads,
                                      name=f"attn_{i}")
        x = ff.layer_norm(ff.add(attn, x), axes=[-1], name=f"ln_attn_{i}")
        m = ff.moe(x, num_exp, num_select, hidden_size, alpha, lambda_bal,
                   name=f"moe_{i}")
        x = ff.layer_norm(ff.add(m, x), axes=[-1], name=f"ln_moe_{i}")
    x = ff.dense(x, num_classes, name="head")
    return ff.softmax(x, name="softmax")
