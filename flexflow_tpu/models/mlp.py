"""MLP_Unify builder — the Unity two-tower MLP benchmark.

Parity with /root/reference/examples/cpp/MLP_Unify/mlp.cc:36-57: two
inputs through parallel 8x8192 dense towers, summed, softmaxed.  The
Unity search discovers the alternating data/model-parallel strategy for
the wide denses; on TPU those are 'channel' ShardConfig degrees that
keep each 8192-wide GEMM MXU-resident per shard.
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..fftype import ActiMode
from ..model import FFModel


def build_mlp_unify(
    ff: FFModel,
    batch_size: int = 64,
    input_dim: int = 1024,
    hidden_dims: Optional[Sequence[int]] = None,
):
    hidden_dims = list(hidden_dims or [8192] * 8)
    t1 = ff.create_tensor([batch_size, input_dim], name="input1")
    t2 = ff.create_tensor([batch_size, input_dim], name="input2")
    for i, d in enumerate(hidden_dims):
        act = ActiMode.NONE if i + 1 == len(hidden_dims) else ActiMode.RELU
        t1 = ff.dense(t1, d, activation=act, use_bias=False, name=f"t1_dense_{i}")
        t2 = ff.dense(t2, d, activation=act, use_bias=False, name=f"t2_dense_{i}")
    t = ff.add(t1, t2, name="add")
    return ff.softmax(t, name="softmax")
