"""Seq2seq NMT builder (reference legacy nmt/ subtree: standalone LSTM
encoder-decoder machine translation with hand-written parallel ops,
nmt/rnn.h, nmt/nmt.cc — pre-FFModel code rebuilt here on the layer
API).

Teacher-forced training: source tokens -> embed -> encoder LSTM stack;
target tokens -> embed -> decoder LSTM stack (conditioned on the
encoder's final context by feature concat) -> vocab projection.
"""
from __future__ import annotations

from ..fftype import AggrMode
from ..model import FFModel


def build_nmt(
    ff: FFModel,
    batch_size: int = 64,
    src_len: int = 16,
    tgt_len: int = 16,
    src_vocab: int = 8000,
    tgt_vocab: int = 8000,
    embed_dim: int = 64,
    hidden_size: int = 128,
    num_layers: int = 2,
):
    src = ff.create_tensor([batch_size, src_len], dtype="int32", name="src")
    tgt = ff.create_tensor([batch_size, tgt_len], dtype="int32", name="tgt")

    enc = ff.embedding(src, src_vocab, embed_dim, aggr=AggrMode.NONE,
                       name="src_embed")
    for i in range(num_layers):
        enc = ff.lstm(enc, hidden_size, return_sequences=True,
                      name=f"enc_lstm_{i}")
    # context: mean over source positions -> broadcast to target length
    ctx = ff.mean(enc, axes=[1], keepdims=True, name="enc_context")

    dec = ff.embedding(tgt, tgt_vocab, embed_dim, aggr=AggrMode.NONE,
                       name="tgt_embed")
    for i in range(num_layers):
        dec = ff.lstm(dec, hidden_size, return_sequences=True,
                      name=f"dec_lstm_{i}")
    # condition decoder states on encoder context (broadcast add)
    dec = ff.add(dec, ctx, name="condition")
    logits = ff.dense(dec, tgt_vocab, name="vocab_proj")
    return ff.softmax(logits, name="softmax")
