"""Seq2seq NMT builder (reference legacy nmt/ subtree: standalone LSTM
encoder-decoder machine translation, nmt/rnn.h + nmt/nmt.cc — per-cell
Legion ops there, rebuilt here on the layer API with fused lax.scan
LSTMs).

Teacher-forced training: source tokens -> embed -> encoder LSTM stack
producing per-position states; target tokens -> embed -> decoder LSTM
stack; Luong-style dot-product attention over the encoder states
(scores = dec @ enc^T -> softmax -> context; concat + tanh projection)
-> vocab projection.  The reference's own nmt/ has no attention (it
predates it); attention here is built from first-class PCG ops
(batch_matmul/softmax/concat/dense), so the strategy search sees and
shards it like any other subgraph.  `greedy_decode` provides the
inference loop (the reference only ships the training path).
"""
from __future__ import annotations

import numpy as np

from ..fftype import ActiMode, AggrMode
from ..model import FFModel


def build_nmt(
    ff: FFModel,
    batch_size: int = 64,
    src_len: int = 16,
    tgt_len: int = 16,
    src_vocab: int = 8000,
    tgt_vocab: int = 8000,
    embed_dim: int = 64,
    hidden_size: int = 128,
    num_layers: int = 2,
    attention: bool = True,
):
    src = ff.create_tensor([batch_size, src_len], dtype="int32", name="src")
    tgt = ff.create_tensor([batch_size, tgt_len], dtype="int32", name="tgt")

    enc = ff.embedding(src, src_vocab, embed_dim, aggr=AggrMode.NONE,
                       name="src_embed")
    for i in range(num_layers):
        enc = ff.lstm(enc, hidden_size, return_sequences=True,
                      name=f"enc_lstm_{i}")
    # summary context (the reference's encoder->decoder hand-off role):
    # mean over source positions, broadcast onto decoder states
    ctx = ff.mean(enc, axes=[1], keepdims=True, name="enc_context")

    dec = ff.embedding(tgt, tgt_vocab, embed_dim, aggr=AggrMode.NONE,
                       name="tgt_embed")
    for i in range(num_layers):
        dec = ff.lstm(dec, hidden_size, return_sequences=True,
                      name=f"dec_lstm_{i}")
    dec = ff.add(dec, ctx, name="condition")

    if attention:
        # Luong dot-product attention over encoder states, in PCG ops:
        # [B,T,H] @ [B,H,S] -> [B,T,S] -> softmax_S -> @ [B,S,H]
        enc_t = ff.transpose(enc, [0, 2, 1], name="enc_T")
        scores = ff.batch_matmul(dec, enc_t, name="attn_scores")
        attn = ff.softmax(scores, axis=-1, name="attn_weights")
        context = ff.batch_matmul(attn, enc, name="attn_context")
        comb = ff.concat([dec, context], axis=2, name="attn_concat")
        dec = ff.dense(comb, hidden_size, activation=ActiMode.TANH,
                       name="attn_comb")

    logits = ff.dense(dec, tgt_vocab, name="vocab_proj")
    return ff.softmax(logits, name="softmax")


def greedy_decode(ff: FFModel, src_tokens, bos_id: int = 1,
                  tgt_len: int = None) -> np.ndarray:
    """Greedy autoregressive decoding with the compiled training graph:
    re-runs the fixed-shape forward per step, feeding back argmaxes
    (an O(T^2) utility loop — correct, not the serving path)."""
    src_tokens = np.asarray(src_tokens, np.int32)
    batch = src_tokens.shape[0]
    if tgt_len is None:
        tgt_len = next(
            op for op in ff.layers.source_ops() if op.name == "tgt"
        ).outputs[0].shape.logical_shape[1]
    buf = np.zeros((batch, tgt_len), np.int32)
    buf[:, 0] = bos_id
    for t in range(1, tgt_len):
        probs = np.asarray(
            ff.forward({"src": src_tokens, "tgt": buf}), np.float32)
        buf[:, t] = probs[:, t - 1].argmax(-1).astype(np.int32)
    return buf
