"""Base operator class for the PCG.

Fresh design replacing the reference's `Op` base
(/root/reference/include/flexflow/operator.h:51-196) and its 4-part
per-op pattern (params struct / graph-time ctor / Legion launches /
CUDA task bodies — exemplar src/ops/linear.cc).  Here each op is:

  1. a frozen **params dataclass** (hashable — node-dedup key for the
     search, like linear_params.h + model.h:676-704 get_or_create_node);
  2. a **shape rule** `infer_output_shapes` that propagates both logical
     sizes and partition degrees (replacing the reference's
     parallel-dim-mapping records, operator.h:53-121);
  3. a pure **jax forward** `forward(...)` on logical (global) arrays —
     XLA SPMD shards it according to the tensors' machine views, and
     `jax.grad` supplies backward (no hand-written backward tasks);
  4. **cost hooks** (`flops`, `memory_bytes`) consumed by the simulator
     in place of cudaEvent timing (model.cu:38-75).

Op-level parallelism choices that the reference expresses through each
op's MachineView + weight replica dims (e.g. linear out-channel
partition, attention head partition, embedding vocab partition) live in
a per-op `ShardConfig`, mutated by the strategy search.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..fftype import DataType, OperatorType
from ..initializer import Initializer
from ..tensor import ParallelTensor, ParallelTensorShape

_op_guid = [2000]


@dataclasses.dataclass(frozen=True)
class ShardConfig:
    """Op-internal parallelism degrees (strategy-search mutable).

    channel: shard the op's weight/output channel dim (linear out-channels,
        attention heads via head_degree alias, conv out-channels).
    reduction: shard the contraction dim (linear in-channels) — output
        becomes partial-sum with replica degree = reduction; a Reduction
        parallel op (or XLA's automatic all-reduce under SPMD) collapses it.
    attribute: shard an attribute dim (embedding vocab, conv in-channel
        attribute parallelism; reference --enable-attribute-parallel).
    expert: expert parallelism degree for MoE ops.
    """

    channel: int = 1
    reduction: int = 1
    attribute: int = 1
    expert: int = 1

    def is_trivial(self) -> bool:
        return self.channel == self.reduction == self.attribute == self.expert == 1


@dataclasses.dataclass(frozen=True)
class WeightSpec:
    name: str
    shape: ParallelTensorShape
    initializer: Optional[Initializer] = None


class Op:
    """A node in the parallel computation graph."""

    op_type: OperatorType = OperatorType.NOOP

    def __init__(
        self,
        params,
        inputs: Sequence[ParallelTensor],
        name: str = "",
        shard: ShardConfig = ShardConfig(),
    ):
        _op_guid[0] += 1
        self.guid = _op_guid[0]
        self.params = params
        self.inputs: List[ParallelTensor] = list(inputs)
        self.shard = shard
        self.name = name or f"{self.op_type.value}_{self.guid}"
        self.machine_view = None  # assigned by strategy lowering
        # Shape inference + weight/output creation
        out_shapes = self.infer_output_shapes([t.shape for t in inputs])
        self.outputs: List[ParallelTensor] = [
            ParallelTensor(s, owner_op=self, owner_idx=i, name=f"{self.name}.out{i}")
            for i, s in enumerate(out_shapes)
        ]
        self.weight_specs: List[WeightSpec] = self.make_weight_specs(
            [t.shape for t in inputs]
        )
        self.weights: List[ParallelTensor] = [
            ParallelTensor(ws.shape, owner_op=self, owner_idx=i,
                           name=f"{self.name}.{ws.name}")
            for i, ws in enumerate(self.weight_specs)
        ]

    # -- to override ----------------------------------------------------
    def ctor_kwargs(self) -> dict:
        """Extra constructor kwargs a reconstruction must pass.  Ops are
        re-instantiated as type(op)(params, inputs, name=, shard=,
        **ctor_kwargs()) by apply_strategy / clone_op / search variant
        enumeration; ops carrying construction-time flags beyond
        (params, shard) override this (MultiHeadAttention decode mode)."""
        return {}

    def infer_output_shapes(
        self, input_shapes: Sequence[ParallelTensorShape]
    ) -> List[ParallelTensorShape]:
        raise NotImplementedError

    def make_weight_specs(
        self, input_shapes: Sequence[ParallelTensorShape]
    ) -> List[WeightSpec]:
        return []

    def forward(
        self,
        inputs: Sequence[jax.Array],
        weights: Sequence[jax.Array],
        *,
        training: bool = False,
        rng: Optional[jax.Array] = None,
    ) -> List[jax.Array]:
        raise NotImplementedError

    # -- cost hooks (simulator) -----------------------------------------
    def flops(self) -> float:
        """Forward FLOPs for one full (unsharded) application."""
        return 0.0

    def memory_bytes(self) -> int:
        total = sum(t.shape.size_bytes() for t in self.outputs)
        total += sum(w.shape.size_bytes() for w in self.weights)
        return total

    def is_parallel_op(self) -> bool:
        return self.op_type.is_parallel_op()

    # -- search support --------------------------------------------------
    def with_shard(self, shard: ShardConfig) -> "ShardConfig":
        return shard

    def node_key(self) -> Tuple:
        """Hashable dedup key (reference get_or_create_node, model.h:676)."""
        return (
            self.op_type,
            self.params,
            self.shard,
            tuple(t.shape for t in self.inputs),
        )

    def __repr__(self) -> str:
        ins = ",".join(str(t.shape) for t in self.inputs)
        outs = ",".join(str(t.shape) for t in self.outputs)
        return f"{self.name}({ins} -> {outs})"


# ---------------------------------------------------------------------------
# Shared shape-rule helpers
# ---------------------------------------------------------------------------

def elementwise_shape(
    shape: ParallelTensorShape, dtype: Optional[DataType] = None
) -> ParallelTensorShape:
    return ParallelTensorShape(shape.dims, dtype or shape.dtype)


def check_no_partition(shape: ParallelTensorShape, dim_idx: int, opname: str):
    dims = [d for d in shape.dims if not d.is_replica_dim]
    if dims[dim_idx].degree != 1:
        raise ShapeError(
            f"{opname}: dim {dim_idx} (size {dims[dim_idx].size}) may not be "
            f"partitioned (degree {dims[dim_idx].degree})"
        )


class ShapeError(ValueError):
    """Raised when an op cannot accept the given input parallel shapes —
    the search treats this as an illegal strategy candidate."""


def trainable_weight_count(op: Op) -> int:
    """Weights [0:n] are trainable; the rest are op state (BatchNorm
    running stats).  Ops opt in via a num_trainable_weights method."""
    fn = getattr(op, "num_trainable_weights", None)
    return fn() if fn is not None else len(op.weight_specs)
