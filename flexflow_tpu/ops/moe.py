"""Mixture-of-Experts ops: TopK, GroupBy, Aggregate, AggregateSpec, Cache.

Reference: src/ops/topk.cu (custom heap kernel), src/ops/group_by.cu
(data-dependent scatter with capacity factor, group_by.cu:1-206),
src/ops/aggregate.cu (combine with load-balance loss, lambda_bal),
src/ops/aggregate_spec.cu (speculative variant — replicated labels,
model.cc:2875), src/ops/cache.cc (expert-activation cache with score_f
trigger driving recompilation).

TPU-first re-design: the reference's scatter/gather dispatch is replaced
by the standard TPU dense formulation — capacity-bounded **one-hot
dispatch/combine einsums** (GShard/Switch style) that XLA maps onto the
MXU with static shapes (no data-dependent control flow).  GroupBy emits
a single stacked [experts, capacity, dim] tensor whose expert dim is the
expert-parallel shardable dim (ShardConfig.expert); per-expert FFNs run
as batched einsums over that dim, so expert parallelism = sharding dim 0
over the "expert" mesh axis and the dispatch einsum lowers to an
all-to-all over ICI.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..fftype import DataType, OperatorType
from ..tensor import ParallelDim, ParallelTensorShape
from .op import Op, ShapeError


def _data_dims(shape):
    return [d for d in shape.dims if not d.is_replica_dim]


@dataclasses.dataclass(frozen=True)
class TopKParams:
    k: int
    sorted: bool = False


class TopK(Op):
    """values, indices = topk(x, k) along the last dim."""

    op_type = OperatorType.TOPK

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        dd = _data_dims(ishape)
        if dd[-1].degree != 1:
            raise ShapeError(f"{self.name}: topk axis is partitioned")
        if self.params.k > dd[-1].size:
            raise ShapeError(f"{self.name}: k > dim size")
        dims = tuple(ParallelDim(d.size, d.degree) for d in dd[:-1]) + (
            ParallelDim(self.params.k, 1),
            ParallelDim(1, ishape.replica_degree, is_replica_dim=True),
        )
        return [
            ParallelTensorShape(dims, ishape.dtype),
            ParallelTensorShape(dims, DataType.INT32),
        ]

    def forward(self, inputs, weights, *, training=False, rng=None):
        values, indices = jax.lax.top_k(inputs[0], self.params.k)
        return [values, indices.astype(jnp.int32)]


def _capacity(batch: int, k: int, n: int, alpha: float) -> int:
    return max(1, int(math.ceil(alpha * k * batch / n)))


def _dispatch_mask(assign: jax.Array, n: int, capacity: int) -> jax.Array:
    """[b, k] expert ids -> bool dispatch mask [b, n, capacity].

    Flattens (b, k) in priority order, computes each token's position in
    its expert's queue by cumsum, and drops tokens beyond capacity —
    mirroring the reference's capacity-bounded scatter (group_by.cu) with
    static shapes.
    """
    b, k = assign.shape
    flat = assign.reshape(-1)  # [b*k], row-major: sample-major priority
    onehot = jax.nn.one_hot(flat, n, dtype=jnp.int32)  # [bk, n]
    pos = jnp.cumsum(onehot, axis=0) - 1  # position within expert queue
    pos = jnp.sum(pos * onehot, axis=-1)  # [bk]
    keep = pos < capacity
    pos_oh = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)  # [bk, cap]
    disp = (
        onehot.astype(jnp.float32)[:, :, None]
        * pos_oh[:, None, :]
        * keep.astype(jnp.float32)[:, None, None]
    )  # [bk, n, cap]
    return disp.reshape(b, k, n, capacity)


@dataclasses.dataclass(frozen=True)
class GroupByParams:
    n: int  # number of experts
    alpha: float  # capacity factor


class GroupBy(Op):
    op_type = OperatorType.GROUP_BY

    def infer_output_shapes(self, input_shapes):
        data, assign = input_shapes
        dd = _data_dims(data)
        ad = _data_dims(assign)
        if len(dd) != 2 or len(ad) != 2:
            raise ShapeError(f"{self.name}: expect data [b,d], assign [b,k]")
        if dd[0].size != ad[0].size:
            raise ShapeError(f"{self.name}: batch mismatch")
        cap = _capacity(dd[0].size, ad[1].size, self.params.n, self.params.alpha)
        dims = (
            ParallelDim(self.params.n, self.shard.expert),
            ParallelDim(cap, 1),
            ParallelDim(dd[1].size, dd[1].degree),
            ParallelDim(1, data.replica_degree, is_replica_dim=True),
        )
        return [ParallelTensorShape(dims, data.dtype)]

    def forward(self, inputs, weights, *, training=False, rng=None):
        from .moe_dispatch import sort_group_by

        data, assign = inputs
        p: GroupByParams = self.params
        b, k = assign.shape
        cap = _capacity(b, k, p.n, p.alpha)
        return [sort_group_by(data, assign, p.n, cap)]


@dataclasses.dataclass(frozen=True)
class AggregateParams:
    n: int
    lambda_bal: float = 0.0
    alpha: float = 1.0


class Aggregate(Op):
    """Combine expert outputs weighted by (renormalized) gate scores.

    Inputs: gate_scores [b,k], assign [b,k] (int), gate_logits_softmax
    [b,n] (for the load-balance aux loss), expert_out [n,cap,e].
    The aux loss (lambda_bal · n · Σ_e fraction_e · prob_e, Switch-style —
    functional stand-in for the reference's lambda_bal gradient injection
    in aggregate.cu) is exposed via `aux_loss` on the forward result.
    """

    op_type = OperatorType.AGGREGATE

    def infer_output_shapes(self, input_shapes):
        gate_scores, assign, gate_full, expert_out = input_shapes
        ed = _data_dims(expert_out)
        bd = _data_dims(gate_scores)
        dims = (
            ParallelDim(bd[0].size, bd[0].degree),
            ParallelDim(ed[-1].size, ed[-1].degree),
            ParallelDim(1, expert_out.replica_degree, is_replica_dim=True),
        )
        return [ParallelTensorShape(dims, expert_out.dtype)]

    def forward(self, inputs, weights, *, training=False, rng=None):
        from .moe_dispatch import sort_combine

        gate_scores, assign, gate_full, expert_out = inputs
        p: AggregateParams = self.params
        n, cap, e = expert_out.shape
        b, k = assign.shape
        denom = jnp.sum(gate_scores, axis=-1, keepdims=True) + 1e-9
        norm_scores = gate_scores / denom
        rows, _ = sort_combine(expert_out, assign, cap)  # [bk, e]
        y = jnp.sum(rows.reshape(b, k, e) * norm_scores[:, :, None], axis=1)
        self._last_aux = self._balance_loss(assign, gate_full, n, p.lambda_bal)
        return [y.astype(expert_out.dtype)]

    @staticmethod
    def _balance_loss(assign, gate_full, n, lambda_bal):
        if lambda_bal == 0.0:
            return None
        counts = jnp.sum(jax.nn.one_hot(assign[:, 0], n), axis=0)
        frac = counts / assign.shape[0]
        prob = jnp.mean(gate_full, axis=0)
        return lambda_bal * n * jnp.sum(frac * prob)


class AggregateSpec(Aggregate):
    """Speculative aggregate: emit each assigned expert's prediction as a
    separate sample — output [k·b, e]; the framework replicates labels k×
    to match (reference model.cc:2875)."""

    op_type = OperatorType.AGGREGATE_SPEC

    def infer_output_shapes(self, input_shapes):
        gate_scores, assign, gate_full, expert_out = input_shapes
        ed = _data_dims(expert_out)
        bd = _data_dims(assign)
        dims = (
            ParallelDim(bd[0].size * bd[1].size, bd[0].degree),
            ParallelDim(ed[-1].size, ed[-1].degree),
            ParallelDim(1, expert_out.replica_degree, is_replica_dim=True),
        )
        return [ParallelTensorShape(dims, expert_out.dtype)]

    def forward(self, inputs, weights, *, training=False, rng=None):
        from .moe_dispatch import sort_combine

        gate_scores, assign, gate_full, expert_out = inputs
        p: AggregateParams = self.params
        n, cap, e = expert_out.shape
        b, k = assign.shape
        # per-(sample, slot) prediction rows [bk, e]
        preds, _ = sort_combine(expert_out, assign, cap)
        self._last_aux = self._balance_loss(assign, gate_full, n, p.lambda_bal)
        return [preds.astype(expert_out.dtype)]


@dataclasses.dataclass(frozen=True)
class CacheParams:
    num_batches: int
    seed: int = 0


def default_cache_score(cached_score, input_arr, cached_arr, vol):
    """Reference default_score (cache.cc:38-55): EMA (gamma 0.99) of
    exact batch-vs-cached equality — 1-ish if batches repeat, decaying
    to 0 as they drift."""
    gamma = 0.99
    cached_score = cached_score * gamma
    if np.array_equal(input_arr, cached_arr):
        cached_score += 1.0 - gamma
    return cached_score


class Cache(Op):
    """Expert-activation cache (reference src/ops/cache.cc).

    Keeps a host-side ring of the last `num_batches` input batches.
    Every training batch, a score function
    ``score_f(cached_score, input, cached, vol) -> new score`` (the
    reference's signature; default = exact-match EMA, cache.cc:38-55;
    the MoE example's set-compare scorer moe.cc:40-63 drops in) is
    folded over the batch vs its cached slot, then the slot is
    refreshed — producing the staleness score that feeds
    recompile_on_condition (cache_update task, cache.cc:180-231).

    Forward is identity; with ``use_cached(True)`` the op instead
    replays the CACHED batch for the current slot (the reference's
    load_cached forward, cache.cc:214-231), fed into the jitted step as
    an extra input."""

    op_type = OperatorType.CACHE

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.score_history = []
        self.cache_score = 0.0
        self.batch_ctr = 0
        self._ring = [None] * self.params.num_batches
        self._load_cached = False
        self.score_fn = None  # legacy model-level fn OR 4-arg score_f

    def infer_output_shapes(self, input_shapes):
        return [input_shapes[0]]

    def forward(self, inputs, weights, *, training=False, rng=None):
        return [inputs[0]]

    # -- host-side cache accounting (reference cache_update task) ------
    def _score_f(self):
        import inspect

        fn = self.score_fn
        if fn is not None:
            try:
                if len(inspect.signature(fn).parameters) >= 4:
                    return fn
            except (TypeError, ValueError):
                pass
        return default_cache_score

    def update(self, batch: np.ndarray):
        """Fold one training batch into the cache: score vs the cached
        copy of this slot, then refresh the slot."""
        batch = np.asarray(batch)
        slot = self.batch_ctr
        cached = self._ring[slot]
        if cached is not None and cached.shape == batch.shape:
            self.cache_score = float(
                self._score_f()(self.cache_score, batch, cached, batch.size)
            )
        self._ring[slot] = batch.copy()
        self.batch_ctr = (self.batch_ctr + 1) % self.params.num_batches
        self.update_score(self.cache_score)

    def cached_value(self) -> np.ndarray:
        """The cached batch the load_cached forward replays."""
        v = self._ring[self.batch_ctr]
        if v is None:
            return np.zeros(self.outputs[0].shape.logical_shape,
                            self.outputs[0].dtype.np_dtype)
        return v

    def use_cached(self, c: bool):
        """Reference Cache::use_cached (cache.cc:259)."""
        self._load_cached = bool(c)

    def _is_legacy_score(self) -> bool:
        """True for the round-1 model-level `score_fn(ff)` convention
        (polled in fit); reference-style 4-arg scorers run in update()."""
        import inspect

        fn = self.score_fn
        if fn is None:
            return False
        try:
            return len(inspect.signature(fn).parameters) < 4
        except (TypeError, ValueError):
            return True

    def update_score(self, score: float):
        self.score_history.append(float(score))
        if len(self.score_history) > self.params.num_batches:
            self.score_history.pop(0)

    @property
    def trigger(self) -> float:
        """Latest staleness score (the reference's cache_score EMA)."""
        if not self.score_history:
            return 0.0
        return self.score_history[-1]
