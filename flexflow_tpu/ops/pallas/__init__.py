"""Pallas TPU kernels for the hot ops (SURVEY §7: flash/ring attention,
MoE dispatch).  Each kernel has a pure-jnp reference fallback used on
CPU meshes and as the autodiff backward where a hand-written backward
kernel is not warranted."""
