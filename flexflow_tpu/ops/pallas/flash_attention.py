"""Flash attention kernels (Pallas TPU): forward AND backward.

TPU-native replacement for the reference's monolithic
cudnnMultiHeadAttnForward (/root/reference/src/ops/attention.cu:35): a
blockwise online-softmax attention kernel that never materializes the
[s, s] score matrix in HBM — scores live in VMEM tiles feeding the MXU.

Design:
  * layout [batch*heads, seq, head_dim]; grid (bh, q_blocks); K/V for
    one bh slice stay in VMEM (fine up to ~8k seq at d=64..128);
  * online softmax with running (m, l, acc) in f32, output written once;
  * causal masking skips fully-masked KV blocks via the loop bound;
  * backward: two Pallas kernels sharing the forward's tiling — a dq
    kernel (grid over q blocks, loop over kv) and a dkv kernel (grid
    over kv blocks, loop over q), both recomputing probabilities in
    VMEM from the saved log-sum-exp plus the precomputed
    delta = rowsum(dO * O), so no [s, s] residual ever touches HBM.

Falls back to a pure-jnp implementation off-TPU (CPU test meshes) or
for shapes the tiling cannot cover.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Per-kernel preferred (block_q, block_k): r5 on-chip ASYMMETRIC sweep
# (v5e, bh=96, d=64, seq2048, scan-chained timing so tunnel dispatch
# is amortized — scripts/flash_ceiling_probe.py, table in docs/PERF.md).
# Each kernel wants the LOOPED axis wide (fewer grid revisits of the
# resident operand) and the GRID axis narrow:
#   fwd  (grid q, loop kv): (512, 2048) — 4.41ms vs 5.55 at 1024x1024;
#   dq   (grid q, loop kv): (512, 1024) — 5.79ms vs 7.10;
#   dkv  (grid kv, loop q): (2048, 512) — 7.35ms vs 7.57.
# bq=2048 tiles fail to compile for fwd/dq (f32 score tile + q-block
# accumulators crowd VMEM); the dkv kernel fits them because its
# per-cell state is [bk, d].
_PREFERRED = {"fwd": (512, 2048), "dq": (512, 1024), "dkv": (2048, 512)}

_NEG_INF = -1e30


def _largest_dividing(s: int, cap: int) -> Optional[int]:
    b = cap
    while b >= 128:
        if s % b == 0 and s >= b:
            return b
        b //= 2
    return None


def _pick_block(s: int) -> Optional[int]:
    """Generic feasibility tile (supportedness checks); per-kernel
    choices come from _pick_blocks."""
    return _largest_dividing(s, 1024)


def _pick_blocks(kernel: str, sq: int, sk: int) -> Tuple[int, int]:
    cap_q, cap_k = _PREFERRED[kernel]
    return _largest_dividing(sq, cap_q), _largest_dividing(sk, cap_k)


def _ref_attention(q, k, v, scale: float, causal: bool):
    """Reference jnp path: q,k,v [bh, s, d] -> out [bh, sq, d]."""
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool))  # absolute positions: q_i sees k_0..k_i
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p.astype(v.dtype), v)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                scale: float, causal: bool, seq_k: int):
    q = q_ref[0]  # [bq, d] — native dtype feeds the MXU; accumulate f32
    block_q, d = q.shape
    j = pl.program_id(1)
    q_start = j * block_q

    m = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, d), jnp.float32)

    num_k = seq_k // block_k
    if causal:
        # KV blocks entirely past the last query row contribute nothing
        # (q_start is traced — program_id — so clamp with jnp)
        num_k_live = (q_start + block_q + block_k - 1) // block_k
        num_k = jnp.minimum(num_k, num_k_live)

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk] f32
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1)
        acc_new = acc * correction[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_k, body, (m, l, acc))
    l_safe = jnp.where(l > 0.0, l, 1.0)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # lse buffer is one full [1, 1, sq] row revisited across q blocks;
    # write just this block's slice (block shape (1,1,sq) satisfies the
    # TPU tiling rule by equaling the array dims)
    lse_ref[0, 0, pl.ds(q_start, block_q)] = m + jnp.log(l_safe)


try:  # pallas import is lazy-safe: CPU-only envs never touch the kernel
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False


def _flash_fwd_pallas(q, k, v, scale: float, causal: bool,
                      block_q: int, block_k: int, interpret: bool = False):
    bh, sq, d = q.shape
    sk = k.shape[1]
    grid = (bh, sq // block_q)
    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, scale=scale, causal=causal, seq_k=sk
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, sq), lambda i, j: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse.reshape(bh, sq)


def _supported(q, k, block_q: Optional[int] = None,
               block_k: Optional[int] = None) -> bool:
    if not _HAVE_PALLAS:
        return False
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = block_q or _pick_block(sq)
    block_k = block_k or _pick_block(sk)
    return (
        block_q is not None
        and block_k is not None
        and sq % block_q == 0
        and sk % block_k == 0
        and (d % 128 == 0 or d == 64)  # lane-dim friendly head sizes
        and sq >= block_q
        and sk >= block_k
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, scale: float, causal: bool):
    """q,k,v: [bh, s, d] -> [bh, sq, d].  Pallas on TPU, jnp elsewhere."""
    out, _ = _flash_fwd(q, k, v, scale, causal)
    return out


def _flash_fwd(q, k, v, scale, causal):
    # inside jit tracing array placement is unknown; decide by backend
    backend = jax.default_backend()
    if backend == "tpu" and _supported(q, k):
        return _flash_fwd_pallas(
            q, k, v, scale, causal,
            *_pick_blocks("fwd", q.shape[1], k.shape[1]),
        )
    # reference path: also produce lse for the backward
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool))  # absolute positions: q_i sees k_0..k_i
        s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)
    l = jnp.sum(jnp.exp(s - m[..., None]), axis=-1)
    out = jnp.einsum(
        "bqk,bkd->bqd",
        (jnp.exp(s - m[..., None]) / l[..., None]).astype(v.dtype),
        v,
    )
    return out, m + jnp.log(l)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, block_k: int, scale: float, causal: bool, seq_k: int):
    q = q_ref[0]             # [bq, d]
    do = do_ref[0]           # [bq, d]
    lse = lse_ref[0, 0]      # [bq] f32 (arrays carried [bh, 1, sq]:
    delta = delta_ref[0, 0]  # the TPU block rule wants 3-D tiles)
    block_q, d = q.shape
    j = pl.program_id(1)
    q_start = j * block_q

    num_k = seq_k // block_k
    if causal:
        num_k = jnp.minimum(
            num_k, (q_start + block_q + block_k - 1) // block_k
        )

    def body(kb, acc):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta[:, None]) * scale).astype(k_blk.dtype)
        return acc + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    acc = jax.lax.fori_loop(
        0, num_k, body, jnp.zeros((block_q, d), jnp.float32)
    )
    dq_ref[0] = acc.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref,
                    *, block_q: int, scale: float, causal: bool, seq_q: int):
    k = k_ref[0]  # [bk, d]
    v = v_ref[0]  # [bk, d]
    block_k, d = k.shape
    kb = pl.program_id(1)
    k_start = kb * block_k

    num_q = seq_q // block_q
    # causal: q blocks strictly before this kv block are fully masked
    jb_start = k_start // block_q if causal else 0

    def body(jb, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[0, pl.ds(jb * block_q, block_q), :]
        do_blk = do_ref[0, pl.ds(jb * block_q, block_q), :]
        lse_blk = lse_ref[0, 0, pl.ds(jb * block_q, block_q)]
        delta_blk = delta_ref[0, 0, pl.ds(jb * block_q, block_q)]
        s = jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]
        if causal:
            q_pos = jb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse_blk[:, None])  # [bq, bk] f32
        pt = p.astype(do_blk.dtype)
        dv_acc = dv_acc + jax.lax.dot_general(
            pt, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bk, d]
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        ds = (p * (dp - delta_blk[:, None]) * scale).astype(q_blk.dtype)
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bk, d]
        return dk_acc, dv_acc

    zeros = jnp.zeros((block_k, d), jnp.float32)
    dk_acc, dv_acc = jax.lax.fori_loop(jb_start, num_q, body, (zeros, zeros))
    dk_ref[0] = dk_acc.astype(dk_ref.dtype)
    dv_ref[0] = dv_acc.astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, dout, scale, causal,
                      block_q: int, block_k: int, interpret: bool = False,
                      dkv_blocks: Optional[Tuple[int, int]] = None):
    """block_q/block_k tile the dq kernel; dkv_blocks (defaulting to
    the same pair) tiles the dkv kernel — the two kernels' best tiles
    are opposite-handed (see _PREFERRED)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    dkv_bq, dkv_bk = dkv_blocks or (block_q, block_k)
    # delta = rowsum(dO * O): one cheap fused jnp pass, shared by both
    # kernels (standard flash-backward preprocessing)
    delta = jnp.sum(
        dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ).reshape(bh, 1, sq)  # f32; [bh, 1, sq] satisfies the 3-D tile rule
    lse = lse.astype(jnp.float32).reshape(bh, 1, sq)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_k=block_k, scale=scale, causal=causal,
            seq_k=sk,
        ),
        grid=(bh, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        interpret=interpret,
    )(q, k, v, dout, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, block_q=dkv_bq, scale=scale, causal=causal,
            seq_q=sq,
        ),
        grid=(bh, sk // dkv_bk),
        in_specs=[
            pl.BlockSpec((1, sq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, dkv_bk, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, dkv_bk, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, sq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, sq), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, sq), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, dkv_bk, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, dkv_bk, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, dout, lse, delta)
    return dq, dk, dv


def _flash_vjp_fwd(q, k, v, scale, causal):
    out, lse = _flash_fwd(q, k, v, scale, causal)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(scale, causal, res, dout):
    q, k, v, out, lse = res
    if jax.default_backend() == "tpu" and _supported(q, k):
        sq, sk = q.shape[1], k.shape[1]
        return _flash_bwd_pallas(
            q, k, v, out, lse, dout, scale, causal,
            *_pick_blocks("dq", sq, sk),
            dkv_blocks=_pick_blocks("dkv", sq, sk),
        )
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = dout.astype(jnp.float32)
    s = jnp.einsum("bqd,bkd->bqk", qf, kf) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool))  # absolute positions: q_i sees k_0..k_i
        s = jnp.where(mask, s, _NEG_INF)
    p = jnp.exp(s - lse[..., None])  # recomputed probabilities
    dv = jnp.einsum("bqk,bqd->bkd", p, dof)
    dp = jnp.einsum("bqd,bkd->bqk", dof, vf)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1)  # [bh, sq]
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bqk,bkd->bqd", ds, kf)
    dk = jnp.einsum("bqk,bqd->bkd", ds, qf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def mha_flash(qh, kh, vh, scale: float, causal: bool):
    """[b, s, h, d] convenience wrapper -> [b, sq, h, d]."""
    b, sq, h, d = qh.shape
    sk = kh.shape[1]
    q2 = qh.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    k2 = kh.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    v2 = vh.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    o = flash_attention(q2, k2, v2, scale, causal)
    return o.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
