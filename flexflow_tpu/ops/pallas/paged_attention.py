"""Fused PagedAttention kernel (Pallas TPU): flash-decoding over the
serving tier's paged KV pool, reading blocks IN PLACE through the
block table.

The gather formulation (`ops/attention.py _attend_decode_paged`, the
reference oracle) materializes a dense ``[slots, decode_max_seq, h, d]``
K/V view from the block pool every step, so per-step HBM traffic is
proportional to the TABLE WIDTH regardless of how many tokens are
actually live.  This kernel instead makes the block table part of the
kernel's index maps: grid ``(slots, heads, table_width)`` with the
table and the per-slot sequence lengths as SCALAR-PREFETCH operands,
so the K/V BlockSpecs resolve ``(block_table[i, kb], 0, h, 0)`` —
Pallas's pipeline DMAs exactly the physical pages a row owns, straight
from the pool's HBM layout, no dense view ever exists.

Traffic discipline: a row with ``pos`` tokens live owns
``pos // page + 1`` blocks.  Grid steps past that are mapped to the
row's LAST live block — a repeated block index, which Pallas's
pipeline elides (no re-fetch) — and their compute is skipped with
``pl.when``, so per-step HBM reads scale with live tokens, not
``decode_max_seq``.  Partial tail blocks and the scratch rows idle
slots park on (table all zeros, seq_len 0) are handled by the same
per-position mask the gather oracle uses: key positions past a row's
own length never enter the softmax.

Two entry points mirror the host-side twins (decoding.py):

  * ``paged_decode_attention`` — the seq-1 decode step;
  * ``paged_chunk_attention``  — the seq-C chunked-prefill step
    (``build_paged_chunk_step``): C queries per row, causal within the
    chunk via the mask ``key_pos <= pos + j``.  The gather twin's
    per-position scatter/gather/attend loop collapses into ONE kernel
    dispatch — the k/v scatter stays in plain JAX (it writes O(b*C*h*d)
    bytes, byte-identical to the oracle's), the kernel absorbs the
    read side.

Both accumulate the online softmax in f32 (m/l running rows + an
[s, d] accumulator in VMEM scratch carried across the kb grid axis),
like ops/pallas/flash_attention.py.  Off-TPU the same kernel runs
under ``interpret=True`` — the CPU parity tests
(tests/test_paged_kernel.py) execute the real kernel logic against
the gather oracle, the `_HAVE_PALLAS` / fallback discipline follows
the flash_attention precedent.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_NEG_INF = -1e30

try:  # lazy-safe: CPU-only envs without pallas never touch the kernel
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False


def have_paged_kernel() -> bool:
    """Whether the fused kernel can be built at all in this runtime
    (config-time guard: selecting --paged-kernel pallas without this
    must raise ConfigError at BUILD time, never a deep ImportError
    mid-compile)."""
    return _HAVE_PALLAS


def _live_block_count(pos, chunk: int, page: int, table_width: int):
    """Blocks row(s) at position `pos` touch when attending a chunk of
    `chunk` tokens: positions 0..pos+chunk-1 inclusive, clamped to the
    table.  Works on scalars and arrays (host telemetry + in-kernel)."""
    last = jnp.minimum(pos + chunk - 1, table_width * page - 1)
    return last // page + 1


def blocks_read(seq_lens: np.ndarray, live_mask: np.ndarray, chunk: int,
                page: int, table_width: int) -> int:
    """Host-side telemetry twin of the kernel's traffic discipline:
    physical KV blocks ONE fused dispatch streams for the rows
    `live_mask` marks live.  Idle rows count 0 — their single
    scratch-block fetch is a repeated index the pipeline elides, and
    excluding it keeps the counter a clean live-work signal (the
    convention ContinuousScheduler's serving/paged_kernel_* counters
    use; the scan-based prefill program is `chunk` seq-1 dispatches,
    accounted by summing this with chunk=1 per scan position).  The
    dense-gather equivalent is always ``len(seq_lens) *
    table_width``."""
    pos = np.asarray(seq_lens, np.int64)
    last = np.minimum(pos + chunk - 1, table_width * page - 1)
    per_row = np.where(np.asarray(live_mask, bool), last // page + 1, 0)
    return int(per_row.sum())


def _paged_kernel(btab_ref, slen_ref, q_ref, k_ref, v_ref, o_ref,
                  m_ref, l_ref, acc_ref, *, page: int, scale: float,
                  table_width: int, chunk: int):
    """One grid program = (row i, head h, table column kb): fold the
    physical page `block_table[i, kb]` into row i's online softmax."""
    i = pl.program_id(0)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    pos = slen_ref[i]
    live = _live_block_count(pos, chunk, page, table_width)

    @pl.when(kb < live)
    def _fold():
        q = q_ref[0, 0]        # [chunk, dk] — this head's queries
        k = k_ref[0, :, 0, :]  # [page, dk]  — one physical page
        v = v_ref[0, :, 0, :]  # [page, dv]
        if k.dtype != q.dtype:  # VMEM-tile cast (bf16 query, f32 pool)
            k = k.astype(q.dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [chunk, page] f32
        # chunk token j attends key positions <= pos + j: causal within
        # the chunk, visible-prefix across steps — exactly the gather
        # oracle's mask, so partial tail blocks and scratch rows
        # (pos 0, all-zero table) fall out of the same comparison
        k_pos = kb * page + jax.lax.broadcasted_iota(
            jnp.int32, (chunk, page), 1)
        q_pos = pos + jax.lax.broadcasted_iota(
            jnp.int32, (chunk, page), 0)
        s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_prev = m_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_new = l_ref[:, 0] * corr + jnp.sum(p, axis=-1)
        acc_ref[...] = acc_ref[...] * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(kb == table_width - 1)
    def _write():
        l = l_ref[:, 0]
        l_safe = jnp.where(l > 0.0, l, 1.0)
        o_ref[0, 0] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)


def paged_attention(qh, k_pool, v_pool, block_table, seq_lens,
                    scale: float, *, interpret: Optional[bool] = None):
    """Fused paged attention over the pool.

    qh:          [b, s, h, dk]  this step's queries (s = 1 or chunk C)
    k_pool:      [num_blocks, page, h, dk]  the physical K pool
    v_pool:      [num_blocks, page, h, dv]
    block_table: [b, table_width] int32 (host-owned, scratch-padded)
    seq_lens:    [b] int32 — row i's incoming position (its chunk
                 occupies positions seq_lens[i] .. seq_lens[i]+s-1,
                 already scattered into the pool by the caller)
    ->           [b, s, h, dv] context, qh's dtype

    `interpret` defaults to running the real TPU kernel on TPU and the
    Pallas interpreter elsewhere (the CPU parity-test vehicle)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, s, h, dk = qh.shape
    page, dv = k_pool.shape[1], v_pool.shape[-1]
    table_width = block_table.shape[1]
    qt = qh.transpose(0, 2, 1, 3)  # [b, h, s, dk]
    block_table = block_table.astype(jnp.int32)
    seq_lens = seq_lens.reshape(b).astype(jnp.int32)

    def kv_map(i, hh, kb, btab, slen):
        # out-of-range kb repeats the row's last live block: Pallas
        # elides the re-fetch, so HBM traffic follows live tokens
        live = _live_block_count(slen[i], s, page, table_width)
        return btab[i, jnp.minimum(kb, live - 1)], 0, hh, 0

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, table_width),
        in_specs=[
            pl.BlockSpec((1, 1, s, dk),
                         lambda i, hh, kb, btab, slen: (i, hh, 0, 0)),
            pl.BlockSpec((1, page, 1, dk), kv_map),
            pl.BlockSpec((1, page, 1, dv), kv_map),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, s, dv), lambda i, hh, kb, btab, slen: (i, hh, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((s, 128), jnp.float32),  # running max
            pltpu.VMEM((s, 128), jnp.float32),  # running denominator
            pltpu.VMEM((s, dv), jnp.float32),   # context accumulator
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_kernel, page=page, scale=scale,
                          table_width=table_width, chunk=s),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, s, dv), qh.dtype),
        interpret=interpret,
    )(block_table, seq_lens, qt, k_pool, v_pool)
    return out.transpose(0, 2, 1, 3)


def paged_decode_attention(qh, k_pool, v_pool, block_table, seq_lens,
                           scale: float, *,
                           interpret: Optional[bool] = None):
    """The seq-1 decode twin: qh [b, 1, h, dk] -> [b, 1, h, dv]."""
    assert qh.shape[1] == 1, "decode twin takes one query per row"
    return paged_attention(qh, k_pool, v_pool, block_table, seq_lens,
                           scale, interpret=interpret)


def paged_chunk_attention(qh, k_pool, v_pool, block_table, seq_lens,
                          scale: float, *,
                          interpret: Optional[bool] = None):
    """The seq-C chunked-prefill twin: qh [b, C, h, dk], causal within
    the chunk -> [b, C, h, dv]."""
    return paged_attention(qh, k_pool, v_pool, block_table, seq_lens,
                           scale, interpret=interpret)
