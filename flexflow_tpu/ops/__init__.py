from .op import Op, ShapeError, ShardConfig, WeightSpec
