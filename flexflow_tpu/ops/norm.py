"""Normalization ops: LayerNorm, BatchNorm, Softmax.

Reference: src/ops/layer_norm.cc (custom CUDA welford kernels),
src/ops/batch_norm.cc (cuDNN BN with running stats),
src/ops/softmax.cc (cuDNN softmax).  TPU-first: expressed in jnp so XLA
fuses the reductions; BatchNorm's running stats are carried as explicit
(non-trainable) state entries updated functionally, and the batch-mean/var
psum across data-parallel shards falls out of SPMD (the array is globally
logical).
"""
from __future__ import annotations

import dataclasses
from typing import List, Tuple

import jax
import jax.numpy as jnp

from ..fftype import DataType, OperatorType
from ..initializer import ConstantInitializer, ZeroInitializer
from ..tensor import ParallelDim, ParallelTensorShape
from .op import Op, ShapeError, WeightSpec


@dataclasses.dataclass(frozen=True)
class LayerNormParams:
    axes: Tuple[int, ...]  # logical axes normalized over (e.g. (-1,))
    elementwise_affine: bool = True
    eps: float = 1e-5


class LayerNorm(Op):
    op_type = OperatorType.LAYER_NORM

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        rank = ishape.logical_rank
        for ax in self.params.axes:
            d = [d for d in ishape.dims if not d.is_replica_dim][ax % rank]
            if d.degree != 1:
                raise ShapeError(f"{self.name}: normalized axis {ax} is partitioned")
        return [ishape]

    def make_weight_specs(self, input_shapes):
        p: LayerNormParams = self.params
        if not p.elementwise_affine:
            return []
        (ishape,) = input_shapes
        lshape = ishape.logical_shape
        rank = len(lshape)
        norm_shape = tuple(lshape[ax % rank] for ax in sorted(a % rank for a in p.axes))
        rep = ishape.total_degree
        dims = tuple(ParallelDim(s) for s in norm_shape) + (
            ParallelDim(1, rep, is_replica_dim=True),
        )
        wshape = ParallelTensorShape(dims, ishape.dtype)
        return [
            WeightSpec("gamma", wshape, ConstantInitializer(1.0)),
            WeightSpec("beta", wshape, ZeroInitializer()),
        ]

    def forward(self, inputs, weights, *, training=False, rng=None):
        (x,) = inputs
        p: LayerNormParams = self.params
        axes = tuple(a % x.ndim for a in p.axes)
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + p.eps)
        if p.elementwise_affine:
            gamma, beta = weights
            shape = [1] * x.ndim
            for i, ax in enumerate(sorted(axes)):
                shape[ax] = gamma.shape[i]
            y = y * gamma.reshape(shape) + beta.reshape(shape)
        return [y.astype(x.dtype)]


@dataclasses.dataclass(frozen=True)
class BatchNormParams:
    relu: bool = True  # reference batch_norm has fused relu option
    eps: float = 1e-5
    momentum: float = 0.9


class BatchNorm(Op):
    """NCHW batch norm.  Running stats live in weights[2:4] (non-trainable);
    forward returns updated stats via the op's `aux_state` convention
    handled by the executor."""

    op_type = OperatorType.BATCH_NORM
    has_aux_state = True  # weights[2:] are non-trainable state

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        return [ishape]

    def make_weight_specs(self, input_shapes):
        (ishape,) = input_shapes
        c = ishape.logical_shape[1]
        cdeg = [d for d in ishape.dims if not d.is_replica_dim][1].degree
        rep = ishape.total_degree // cdeg
        dims = (ParallelDim(c, cdeg), ParallelDim(1, rep, is_replica_dim=True))
        ws = ParallelTensorShape(dims, ishape.dtype)
        return [
            WeightSpec("gamma", ws, ConstantInitializer(1.0)),
            WeightSpec("beta", ws, ZeroInitializer()),
            WeightSpec("running_mean", ws, ZeroInitializer()),
            WeightSpec("running_var", ws, ConstantInitializer(1.0)),
        ]

    def num_trainable_weights(self) -> int:
        return 2

    def forward(self, inputs, weights, *, training=False, rng=None):
        (x,) = inputs
        p: BatchNormParams = self.params
        gamma, beta, rmean, rvar = weights
        # channel position follows the physical layout (pcg/layout.py);
        # NHWC keeps the reduction over the vector lanes
        nhwc = getattr(self, "_data_layout", "nchw") == "nhwc"
        axes = (0, 1, 2) if nhwc else (0, 2, 3)
        bshape = (
            (1, 1, 1, -1) if nhwc else (1, -1, 1, 1)
        )
        if training:
            # one-pass stats (E[x^2] - E[x]^2): a single fused read of
            # the activation instead of two; f32 accumulation so the
            # subtraction stays stable under bf16 compute
            mean = jnp.mean(x, axis=axes, dtype=jnp.float32)
            # clamp: the subtraction can round negative for a
            # near-constant channel with a large offset, and rsqrt of a
            # negative poisons the step with NaN
            var = jnp.maximum(
                jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axes)
                - jnp.square(mean),
                0.0,
            )
            new_rmean = p.momentum * rmean + (1 - p.momentum) * mean.astype(
                rmean.dtype
            )
            new_rvar = p.momentum * rvar + (1 - p.momentum) * var.astype(
                rvar.dtype
            )
        else:
            mean, var = rmean, rvar
            new_rmean, new_rvar = rmean, rvar
        scale = gamma.astype(var.dtype) * jax.lax.rsqrt(var + p.eps)
        shift = beta.astype(var.dtype) - mean * scale
        y = x * scale.reshape(bshape).astype(x.dtype) + shift.reshape(
            bshape
        ).astype(x.dtype)
        if p.relu:
            y = jax.nn.relu(y)
        return [y.astype(x.dtype), new_rmean, new_rvar]


@dataclasses.dataclass(frozen=True)
class SoftmaxParams:
    axis: int = -1


class Softmax(Op):
    op_type = OperatorType.SOFTMAX

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        rank = ishape.logical_rank
        ax = self.params.axis % rank
        d = [d for d in ishape.dims if not d.is_replica_dim][ax]
        if d.degree != 1:
            raise ShapeError(f"{self.name}: softmax axis {ax} is partitioned")
        return [ishape]

    def forward(self, inputs, weights, *, training=False, rng=None):
        return [jax.nn.softmax(inputs[0], axis=self.params.axis)]
