"""Batched per-expert dense layer.

The reference builds one `dense` op per expert inside its MoE composite
(src/ops/moe.cc:20-44) and relies on per-expert MachineViews for expert
parallelism.  On TPU that shape (n small matmuls) wastes the MXU; the
idiomatic form is ONE batched einsum over the stacked expert dim
[n, cap, d] with weights [n, d, out], where sharding the expert dim over
the "expert" mesh axis IS expert parallelism and XLA emits the all-to-all.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ..fftype import ActiMode, OperatorType
from ..initializer import DEFAULT_BIAS_INIT, DEFAULT_WEIGHT_INIT
from ..tensor import ParallelDim, ParallelTensorShape
from .dense import apply_activation
from .op import Op, ShapeError, WeightSpec


@dataclasses.dataclass(frozen=True)
class ExpertsDenseParams:
    out_dim: int
    use_bias: bool = True
    activation: ActiMode = ActiMode.NONE


class ExpertsDense(Op):
    op_type = OperatorType.LINEAR  # participates in search as a linear

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        dd = [d for d in ishape.dims if not d.is_replica_dim]
        if len(dd) != 3:
            raise ShapeError(f"{self.name}: expect [experts, cap, dim]")
        n, cap, din = dd
        expert_degree = max(n.degree, self.shard.expert)
        if n.size % expert_degree != 0:
            raise ShapeError(f"{self.name}: experts {n.size} not divisible")
        dims = (
            ParallelDim(n.size, expert_degree),
            ParallelDim(cap.size, cap.degree),
            ParallelDim(self.params.out_dim, self.shard.channel),
            ParallelDim(1, ishape.replica_degree * din.degree, is_replica_dim=True),
        )
        return [ParallelTensorShape(dims, ishape.dtype)]

    def make_weight_specs(self, input_shapes):
        (ishape,) = input_shapes
        dd = [d for d in ishape.dims if not d.is_replica_dim]
        n, cap, din = dd
        expert_degree = max(n.degree, self.shard.expert)
        p: ExpertsDenseParams = self.params
        kernel = ParallelTensorShape(
            (
                ParallelDim(n.size, expert_degree),
                ParallelDim(din.size, din.degree),
                ParallelDim(p.out_dim, self.shard.channel),
                ParallelDim(1, cap.degree, is_replica_dim=True),
            ),
            ishape.dtype,
        )
        specs = [WeightSpec("kernel", kernel, DEFAULT_WEIGHT_INIT)]
        if p.use_bias:
            bias = ParallelTensorShape(
                (
                    ParallelDim(n.size, expert_degree),
                    ParallelDim(p.out_dim, self.shard.channel),
                    ParallelDim(1, cap.degree * din.degree, is_replica_dim=True),
                ),
                ishape.dtype,
            )
            specs.append(WeightSpec("bias", bias, DEFAULT_BIAS_INIT))
        return specs

    def forward(self, inputs, weights, *, training=False, rng=None):
        (x,) = inputs
        p: ExpertsDenseParams = self.params
        y = jnp.einsum("ncd,ndo->nco", x, weights[0])
        if p.use_bias:
            y = y + weights[1][:, None, :]
        return [apply_activation(y, p.activation)]

    def flops(self):
        ishape = self.inputs[0].shape
        return 2.0 * ishape.num_elements() * self.params.out_dim
