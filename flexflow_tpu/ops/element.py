"""Elementwise ops: ElementUnary, ElementBinary, Cast, Dropout.

Reference: src/ops/element_unary.cc (cuDNN activation + custom kernels,
inplace-capable), src/ops/element_binary.cc (cuDNN OpTensor + custom
broadcast), src/ops/cast.cc, src/ops/dropout.cc (cuDNN dropout, seeded).
TPU-first: plain jnp ops — XLA fuses them into neighbouring matmuls so
they are HBM-bandwidth-free in practice; dropout uses the functional
jax PRNG (`threefry`) instead of cuDNN dropout state.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from ..fftype import DataType, OpBinary, OperatorType, OpUnary
from ..tensor import ParallelDim, ParallelTensorShape
from .op import Op, ShapeError


@dataclasses.dataclass(frozen=True)
class ElementUnaryParams:
    op: OpUnary
    inplace: bool = False
    scalar: float = 0.0


_UNARY_FNS = {
    OpUnary.EXP: jnp.exp,
    OpUnary.LOG: jnp.log,
    OpUnary.SIN: jnp.sin,
    OpUnary.COS: jnp.cos,
    OpUnary.RELU: jax.nn.relu,
    OpUnary.GELU: jax.nn.gelu,
    OpUnary.SIGMOID: jax.nn.sigmoid,
    OpUnary.TANH: jnp.tanh,
    OpUnary.ELU: jax.nn.elu,
    OpUnary.IDENTITY: lambda x: x,
    OpUnary.RSQRT: jax.lax.rsqrt,
    OpUnary.SQRT: jnp.sqrt,
    OpUnary.ERF: jax.lax.erf,
    OpUnary.FLOOR: jnp.floor,
    OpUnary.NEGATIVE: jnp.negative,
}


class ElementUnary(Op):
    op_type = OperatorType.ELEMENT_UNARY

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        return [ishape]

    def forward(self, inputs, weights, *, training=False, rng=None):
        (x,) = inputs
        p: ElementUnaryParams = self.params
        if p.op in _UNARY_FNS:
            return [_UNARY_FNS[p.op](x)]
        if p.op == OpUnary.POW:
            return [jnp.power(x, p.scalar)]
        if p.op == OpUnary.SCALAR_MULTIPLY:
            return [x * p.scalar]
        if p.op == OpUnary.SCALAR_ADD:
            return [x + p.scalar]
        if p.op == OpUnary.SCALAR_SUB:
            return [x - p.scalar]
        if p.op == OpUnary.SCALAR_TRUE_DIV:
            return [x / p.scalar]
        raise ValueError(p.op)


@dataclasses.dataclass(frozen=True)
class ElementBinaryParams:
    op: OpBinary
    inplace_a: bool = False


_BINARY_FNS = {
    OpBinary.ADD: jnp.add,
    OpBinary.SUB: jnp.subtract,
    OpBinary.MUL: jnp.multiply,
    OpBinary.DIV: jnp.divide,
    OpBinary.MAX: jnp.maximum,
    OpBinary.MIN: jnp.minimum,
    OpBinary.POW: jnp.power,
}


class ElementBinary(Op):
    """Numpy-broadcasting binary op (reference supports limited bcast;
    we support full numpy rules — degrees must agree on matching dims)."""

    op_type = OperatorType.ELEMENT_BINARY

    def infer_output_shapes(self, input_shapes):
        a, b = input_shapes
        ad = [d for d in a.dims if not d.is_replica_dim]
        bd = [d for d in b.dims if not d.is_replica_dim]
        # align trailing dims
        rank = max(len(ad), len(bd))
        out = []
        for i in range(1, rank + 1):
            da = ad[-i] if i <= len(ad) else None
            db = bd[-i] if i <= len(bd) else None
            if da is None:
                out.append(ParallelDim(db.size, db.degree))
            elif db is None:
                out.append(ParallelDim(da.size, da.degree))
            else:
                if da.size != db.size and 1 not in (da.size, db.size):
                    raise ShapeError(f"{self.name}: cannot broadcast {da.size} vs {db.size}")
                size = max(da.size, db.size)
                deg = da.degree if da.size >= db.size else db.degree
                other = db if da.size >= db.size else da
                if other.size == size and other.degree != deg:
                    raise ShapeError(f"{self.name}: degree mismatch on dim size {size}")
                out.append(ParallelDim(size, deg))
        out.reverse()
        replica = max(a.replica_degree, b.replica_degree)
        dims = tuple(out) + (ParallelDim(1, replica, is_replica_dim=True),)
        return [ParallelTensorShape(dims, a.dtype)]

    def forward(self, inputs, weights, *, training=False, rng=None):
        a, b = inputs
        return [_BINARY_FNS[self.params.op](a, b)]


@dataclasses.dataclass(frozen=True)
class CastParams:
    dtype: DataType


class Cast(Op):
    op_type = OperatorType.CAST

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        return [ParallelTensorShape(ishape.dims, self.params.dtype)]

    def forward(self, inputs, weights, *, training=False, rng=None):
        return [inputs[0].astype(self.params.dtype.np_dtype)]


@dataclasses.dataclass(frozen=True)
class DropoutParams:
    rate: float
    seed: int = 0


class Dropout(Op):
    op_type = OperatorType.DROPOUT

    def infer_output_shapes(self, input_shapes):
        return [input_shapes[0]]

    def forward(self, inputs, weights, *, training=False, rng=None):
        (x,) = inputs
        p: DropoutParams = self.params
        if not training or p.rate <= 0.0:
            return [x]
        if rng is None:
            rng = jax.random.key(p.seed)
        keep = 1.0 - p.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return [jnp.where(mask, x / keep, 0.0).astype(x.dtype)]
