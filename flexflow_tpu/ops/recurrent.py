"""Recurrent ops: LSTM.

Parity slot for the reference's legacy NMT subtree (nmt/rnn.h,
nmt/lstm.cu — a hand-rolled cuDNN LSTM with its own parallel ops and
mapper).  TPU-native: one fused LSTM op whose time loop is a
``lax.scan`` (XLA unrolls nothing; weights stay MXU-resident across
steps) and whose gate matmul is a single [in+hidden, 4*hidden] GEMM.
Data-parallel over batch like any other op; autodiff gives BPTT.
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from ..fftype import DataType, OperatorType
from ..initializer import GlorotUniform, Initializer
from ..tensor import ParallelDim, ParallelTensorShape
from .op import Op, ShapeError, WeightSpec


@dataclasses.dataclass(frozen=True)
class LSTMBiasInitializer(Initializer):
    """Zeros with the forget-gate block set to 1 — the offset lives in
    the stored weight itself so get_weights/set_weights round-trip with
    external LSTM implementations (Keras/ONNX bias convention)."""

    hidden: int

    def __call__(self, key, shape, dtype):
        b = jnp.zeros(shape, dtype)
        return b.at[self.hidden:2 * self.hidden].set(1.0)


@dataclasses.dataclass(frozen=True)
class LSTMParams:
    hidden_size: int
    return_sequences: bool = True
    dtype: DataType = DataType.FLOAT


class LSTM(Op):
    """Single-layer LSTM over [batch, seq, in_dim].

    Output: [batch, seq, hidden] (return_sequences) or [batch, hidden].
    Weights: kernel [in+hidden, 4*hidden] (i, f, g, o gate order),
    bias [4*hidden] with forget-gate bias init 1.
    """

    op_type = OperatorType.LSTM

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        if ishape.logical_rank != 3:
            raise ShapeError(f"{self.name}: LSTM expects [batch, seq, d]")
        b, s, d = ishape.logical_shape
        h = self.params.hidden_size
        bdim = ishape.dims[0]
        if self.params.return_sequences:
            dims = (
                ParallelDim(b, bdim.degree),
                ParallelDim(s),
                ParallelDim(h),
                ParallelDim(1, ishape.replica_degree, is_replica_dim=True),
            )
        else:
            dims = (
                ParallelDim(b, bdim.degree),
                ParallelDim(h),
                ParallelDim(1, ishape.replica_degree, is_replica_dim=True),
            )
        return [ParallelTensorShape(dims, ishape.dtype)]

    def make_weight_specs(self, input_shapes):
        (ishape,) = input_shapes
        d = ishape.logical_shape[2]
        h = self.params.hidden_size
        rep = ParallelDim(1, 1, is_replica_dim=True)
        kshape = ParallelTensorShape(
            (ParallelDim(d + h), ParallelDim(4 * h), rep), self.params.dtype
        )
        bshape = ParallelTensorShape(
            (ParallelDim(4 * h), rep), self.params.dtype
        )
        return [
            WeightSpec("kernel", kshape, GlorotUniform()),
            WeightSpec("bias", bshape, LSTMBiasInitializer(h)),
        ]

    def forward(self, inputs, weights, *, training=False, rng=None):
        (x,) = inputs
        kernel, bias = weights
        b, s, d = x.shape
        h = self.params.hidden_size

        def step(carry, xt):
            hprev, cprev = carry
            z = jnp.concatenate([xt, hprev], axis=-1) @ kernel + bias
            i, f, g, o = jnp.split(z, 4, axis=-1)
            c = jax.nn.sigmoid(f) * cprev + jax.nn.sigmoid(i) * jnp.tanh(g)
            hnew = jax.nn.sigmoid(o) * jnp.tanh(c)
            return (hnew, c), hnew

        h0 = jnp.zeros((b, h), x.dtype)
        (_, _), hs = jax.lax.scan(step, (h0, h0), x.transpose(1, 0, 2))
        hs = hs.transpose(1, 0, 2)  # [b, s, h]
        if self.params.return_sequences:
            return [hs]
        return [hs[:, -1, :]]

    def flops(self) -> float:
        (ishape,) = [t.shape for t in self.inputs]
        b, s, d = ishape.logical_shape
        h = self.params.hidden_size
        return 2.0 * b * s * (d + h) * 4 * h
